"""Tiered KV prefix store: host-RAM (+ optional disk) tier under the
radix prefix index.

The device-resident prefix index (inference/prefix.py) holds KV pages in
HBM — the scarcest memory on the machine — so LRU eviction under page
pressure used to DISCARD a prefix's KV and the next hit re-prefilled it
from token zero.  This module adds the tier below: when the index drops
a page whose KV is still valid, the engine demotes the page's contents
to a `TieredPrefixStore` (host RAM, spilling to disk past
`capacity_bytes`), and admission-time splicing extends a device-tier
match by PROMOTING pages back (one fixed-shape scatter through the
engine's existing `_swap_in` executable — zero new compiled programs).

Keying: one entry per PAGE, keyed by the full token prefix from
position 0 through the end of that page (a tuple of ints) — the same
granularity as the radix index, so a host-tier chain is walked with
plain dict lookups page by page.  Entries are whole-page only: the
engine's splice floor already treats sub-page matches as misses.

The store is deliberately ENGINE-AGNOSTIC and reattachable: it binds to
no registry and holds no device state, so a fleet Router can share one
store across every replica (thread-safe under one lock), reattach it to
a rebuilt replica after a crash (warm restart), and `_recover_pools` —
which must invalidate every DEVICE-tier prefix because the pool's KV is
gone — never touches it: host copies were taken while the KV was live.

Disk spill: past `capacity_bytes` the LRU RAM entry is written to
`spill_dir` as one `.npz` (token key stored inside the file), and a
fresh store pointed at the same directory re-indexes the spilled
entries — cached prefixes survive a full process restart.

`KVHandoff` is the disaggregated-serving transfer record: a finished
prefill's pages gathered to host staging on the prefill-class replica,
brokered by the Router to a decode-class replica, and scattered into
its pool there (`LLMEngine.import_prefix`).  It rides the same padded
fixed-shape host arrays the preempt/resume swap path uses.
"""

from __future__ import annotations

import collections
import glob
import os
import threading
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["TieredPrefixStore", "KVHandoff"]


class KVHandoff:
    """One prefill-to-decode KV transfer: `tokens` (the full prompt),
    `n_tokens` cached in `n_pages` full pages, and the padded host
    staging arrays `host_k`/`host_v` as gathered by the prefill
    replica's `_swap_out` (page i of the transfer at host index i;
    indices past n_pages hold scratch-page garbage that only ever
    scatters back into the reserved page 0)."""

    __slots__ = ("tokens", "n_tokens", "n_pages", "host_k", "host_v",
                 "src_replica")

    def __init__(self, tokens, n_tokens: int, n_pages: int,
                 host_k, host_v, src_replica: Optional[str] = None):
        self.tokens = np.asarray(tokens, np.int32).reshape(-1)
        self.n_tokens = int(n_tokens)
        self.n_pages = int(n_pages)
        self.host_k = host_k
        self.host_v = host_v
        self.src_replica = src_replica

    @property
    def nbytes(self) -> int:
        """Real payload bytes (the n_pages transferred, not the fixed
        padded staging shape)."""
        if self.n_pages == 0 or self.host_k is None:
            return 0
        slots = self.host_k.shape[1] if self.host_k.ndim > 1 else 1
        per_page = (self.host_k.nbytes + self.host_v.nbytes) \
            // max(1, slots)
        return per_page * min(self.n_pages, slots)


class TieredPrefixStore:
    """Host-RAM page store keyed by full token-prefix tuples, LRU under
    `capacity_bytes`, optionally spilling to `spill_dir` (see module
    doc).  Thread-safe: one lock guards the index — page payloads are
    immutable numpy arrays, so readers never see torn data."""

    def __init__(self, capacity_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 page_size: Optional[int] = None):
        self.capacity_bytes = capacity_bytes
        self.spill_dir = spill_dir
        # set by the first engine that attaches (cache.page_size); used
        # only by first_chunks() for the router's host-tier digest
        self.page_size = page_size
        self._lock = threading.Lock()
        self._ram: "collections.OrderedDict[tuple, tuple]" = \
            collections.OrderedDict()       # key -> (k_page, v_page)
        # key -> QoS tier (lower = more important); capacity eviction
        # drains the least important tier first, LRU within a tier
        self._tiers: dict = {}
        self._disk: dict = {}               # key -> npz path
        self._bytes = 0
        self._seq = 0
        # counters (plain ints under the lock; engines mirror the ones
        # they care about into their own registries)
        self.demoted_pages = 0
        self.promoted_pages = 0
        self.spilled_pages = 0
        self.loaded_pages = 0
        self.hits = 0
        self.misses = 0
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
            self._reindex_spill()

    # -- introspection ------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._ram) + len(self._disk)

    @property
    def resident_bytes(self) -> int:
        return self._bytes

    def keys(self) -> List[tuple]:
        with self._lock:
            return list(self._ram) + list(self._disk)

    def first_chunks(self) -> tuple:
        """Token tuples of the cached FIRST pages — the host-tier analog
        of `PrefixIndex.first_chunks()`, matched by the Router's
        prefix-affinity score so a demoted-but-warm prefix still
        attracts placement.  Empty until an engine attaches and stamps
        `page_size` (key length alone cannot identify depth-0 pages)."""
        ps = self.page_size
        if not ps:
            return ()
        with self._lock:
            return tuple(k for k in list(self._ram) + list(self._disk)
                         if len(k) == ps)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ram_pages": len(self._ram),
                "disk_pages": len(self._disk),
                "resident_bytes": self._bytes,
                "demoted_pages": self.demoted_pages,
                "promoted_pages": self.promoted_pages,
                "spilled_pages": self.spilled_pages,
                "loaded_pages": self.loaded_pages,
                "hits": self.hits,
                "misses": self.misses,
            }

    # -- put / get ----------------------------------------------------------

    def put(self, prefix, k_page, v_page, tier: int = 1) -> bool:
        """Demote one page: cache its KV under the full token prefix
        ending at this page's last token.  Copies are taken (the caller
        may reuse its staging buffer).  Returns False when the entry
        already exists (RAM or disk) — demotion is idempotent (a
        re-demotion still refreshes the entry's QoS tier toward the
        MORE important claimant).  `tier` orders capacity eviction:
        least important (highest number) spills/drops first."""
        key = tuple(int(t) for t in np.asarray(prefix).reshape(-1))
        k_page = np.array(k_page, copy=True)
        v_page = np.array(v_page, copy=True)
        tier = int(tier)
        with self._lock:
            if key in self._ram:
                self._ram.move_to_end(key)
                self._tiers[key] = min(self._tiers.get(key, tier), tier)
                return False
            if key in self._disk:
                return False
            self._ram[key] = (k_page, v_page)
            self._tiers[key] = tier
            self._bytes += k_page.nbytes + v_page.nbytes
            self.demoted_pages += 1
            self._enforce_capacity()
        return True

    def get(self, prefix) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """One page's (k, v) for the full prefix key, or None.  A RAM
        hit is LRU-touched; a disk hit is loaded (and stays on disk —
        re-promotion to device is the caller's job, re-admission to RAM
        would just re-spill it)."""
        key = tuple(int(t) for t in np.asarray(prefix).reshape(-1))
        with self._lock:
            hit = self._ram.get(key)
            if hit is not None:
                self._ram.move_to_end(key)
                self.hits += 1
                self.promoted_pages += 1
                return hit
            path = self._disk.get(key)
        if path is None:
            with self._lock:
                self.misses += 1
            return None
        try:
            with np.load(path) as z:
                k_page, v_page = z["k"], z["v"]
        except Exception:  # noqa: BLE001 — a corrupt spill file is a miss
            with self._lock:
                self._disk.pop(key, None)
                self.misses += 1
            return None
        with self._lock:
            self.hits += 1
            self.promoted_pages += 1
            self.loaded_pages += 1
        return k_page, v_page

    def contains(self, prefix) -> bool:
        key = tuple(int(t) for t in np.asarray(prefix).reshape(-1))
        with self._lock:
            return key in self._ram or key in self._disk

    def clear(self) -> None:
        """Drop every entry, RAM and disk."""
        with self._lock:
            self._ram.clear()
            self._tiers.clear()
            self._bytes = 0
            for path in self._disk.values():
                try:
                    os.unlink(path)
                except OSError:
                    pass
            self._disk.clear()

    # -- internals ----------------------------------------------------------

    def _enforce_capacity(self) -> None:
        """Under self._lock: spill (or drop) RAM entries past
        capacity_bytes — least important QoS tier first, LRU within a
        tier (the OrderedDict runs oldest-touched first, so the first
        key of the worst tier IS that tier's LRU entry)."""
        if self.capacity_bytes is None:
            return
        while self._bytes > self.capacity_bytes and self._ram:
            worst = max(self._tiers.get(k, 1) for k in self._ram)
            key = next(k for k in self._ram
                       if self._tiers.get(k, 1) == worst)
            k_page, v_page = self._ram.pop(key)
            self._tiers.pop(key, None)
            self._bytes -= k_page.nbytes + v_page.nbytes
            if not self.spill_dir:
                continue            # no disk tier: LRU entry is dropped
            self._seq += 1
            path = os.path.join(self.spill_dir,
                                f"kvp_{self._seq:08d}.npz")
            try:
                np.savez(path, k=k_page, v=v_page,
                         tokens=np.asarray(key, np.int64))
                self._disk[key] = path
                self.spilled_pages += 1
            except OSError:
                pass                # disk full: degrade to drop

    def _reindex_spill(self) -> None:
        """Rebuild the disk index from spill_dir (process restart: a
        fresh store reopened on the same directory serves the spilled
        prefixes again)."""
        for path in sorted(glob.glob(
                os.path.join(self.spill_dir, "kvp_*.npz"))):
            try:
                with np.load(path) as z:
                    key = tuple(int(t) for t in z["tokens"])
            except Exception:  # noqa: BLE001 — skip corrupt files
                continue
            self._disk[key] = path
            self._seq = max(self._seq, int(
                os.path.basename(path)[4:-4] or 0))
