"""paddle.inference — the deployment Predictor API (C39).

Reference parity: `paddle/fluid/inference/api/analysis_predictor.h:94`
(AnalysisPredictor) and the `paddle.inference` Python surface
(Config / create_predictor / get_input_handle / run / get_output_handle,
python/paddle/inference/__init__.py).  TPU-native mapping: the optimized
artifact is the StableHLO export written by `paddle_tpu.jit.save` — XLA is
the 274-pass analysis/optimization pipeline, so Config's IR/memory switches
are accepted-and-ignored (XLA always optimizes); the predictor AOT-loads
the artifact once and every `run()` is a cached compiled call.

A minimal HTTP JSON serving loop (`serve`) stands in for the reference's
C/Go serving surface: POST {"inputs": [[...], ...]} -> {"outputs": [...]}.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "InferTensor",
           "serve", "PlaceType", "LLMEngine", "serve_llm", "QueueFull",
           "RequestCancelled", "DeadlineExceeded", "EngineStopped",
           "Router", "FleetHandle", "serve_fleet", "FleetQueueFull",
           "NoHealthyReplica", "ReplicaDied", "RetriesExhausted",
           "RouterStopped", "EngineSupervisor", "BurnRateAutoscaler",
           "faults", "PrefillHandoff", "TieredPrefixStore", "KVHandoff",
           "TenantConfig", "QoSPolicy", "UnknownTenant"]


def __getattr__(name):
    # lazy: the LLM engine / fleet tier pull in the model stack, which
    # plain Config/Predictor users never touch
    if name in ("LLMEngine", "serve_llm", "QueueFull", "RequestCancelled",
                "DeadlineExceeded", "EngineStopped", "PrefillHandoff"):
        from . import llm_engine
        return getattr(llm_engine, name)
    if name in ("TieredPrefixStore", "KVHandoff"):
        from . import kvstore
        return getattr(kvstore, name)
    if name in ("Router", "FleetHandle", "serve_fleet", "FleetQueueFull",
                "NoHealthyReplica", "ReplicaDied", "RetriesExhausted",
                "RouterStopped"):
        from . import router
        return getattr(router, name)
    if name in ("EngineSupervisor", "BurnRateAutoscaler"):
        from . import supervisor
        return getattr(supervisor, name)
    if name in ("TenantConfig", "QoSPolicy", "UnknownTenant"):
        from . import qos
        return getattr(qos, name)
    if name == "faults":
        import importlib
        return importlib.import_module(".faults", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"   # accepted for API parity; maps to the default device
    TPU = "tpu"
    XPU = "xpu"


class Config:
    """Predictor configuration (reference inference/api/paddle_analysis_config.h).

    Graph-optimization and memory switches exist for source compatibility;
    XLA already performs those passes, so they are recorded but change
    nothing.  `set_model(path_prefix)` points at a `jit.save` artifact
    (path without the .pdmodel/.pdparams/.stablehlo suffixes).
    """

    def __init__(self, model_prefix: Optional[str] = None,
                 params_file: Optional[str] = None):
        # reference two-arg form Config(prog_file, params_file): both point
        # at the same jit.save prefix in this build
        self._model_prefix = None
        self._device = None
        self._switches: Dict[str, object] = {}
        if model_prefix:
            self.set_model(model_prefix)

    def set_model(self, prefix: str, params: Optional[str] = None):
        self._model_prefix = (prefix[:-len(".pdmodel")]
                              if prefix.endswith(".pdmodel") else prefix)

    def model_dir(self) -> Optional[str]:
        return self._model_prefix

    def set_device(self, device: str):
        self._device = device

    # accepted-for-parity switches (XLA optimizes unconditionally)
    def enable_use_gpu(self, memory_pool_mb: int = 100, device_id: int = 0):
        self._device = PlaceType.GPU

    def disable_gpu(self):
        self._device = PlaceType.CPU

    def switch_ir_optim(self, flag: bool = True):
        self._switches["ir_optim"] = flag

    def enable_memory_optim(self, flag: bool = True):
        self._switches["memory_optim"] = flag

    def set_cpu_math_library_num_threads(self, n: int):
        self._switches["cpu_threads"] = n

    def set_optim_cache_dir(self, path: str):
        """Persistent compile cache across process restarts (reference
        AnalysisConfig::SetOptimCacheDir) — maps to JAX's persistent
        compilation cache, so the predictor's XLA executable is AOT-reused
        by the next process instead of recompiled.

        NB the JAX compilation cache is PROCESS-GLOBAL: every XLA compile
        in this process (not just this predictor's) lands in `path` once a
        predictor is built from this config — intended for dedicated
        serving processes."""
        self._switches["optim_cache_dir"] = path

    def disable_glog_info(self):
        self._switches["glog"] = False

    def summary(self) -> str:
        return json.dumps({"model": self._model_prefix,
                           "device": self._device,
                           "switches": self._switches}, indent=2)


class InferTensor:
    """Input/output handle (reference paddle_infer::Tensor)."""

    def __init__(self, name: str, shape: Optional[Sequence[int]] = None,
                 dtype: str = "float32"):
        self.name = name
        self._shape = list(shape) if shape is not None else None
        self._dtype = dtype
        self._data: Optional[np.ndarray] = None

    def reshape(self, shape: Sequence[int]):
        self._shape = list(shape)

    def copy_from_cpu(self, arr):
        # a real copy (reference paddle_infer::Tensor semantics): the caller
        # may reuse its staging buffer for the next batch before run()
        arr = np.array(arr, copy=True)
        self._data = arr
        self._shape = list(arr.shape)
        self._dtype = str(arr.dtype)

    def copy_to_cpu(self) -> np.ndarray:
        if self._data is None:
            raise RuntimeError(f"tensor {self.name!r} holds no data yet "
                               f"(run() the predictor first)")
        return np.asarray(self._data)

    def shape(self) -> List[int]:
        return list(self._shape or [])

    def type(self) -> str:
        return self._dtype


class Predictor:
    """AOT predictor over a jit.save StableHLO artifact (AnalysisPredictor
    analog: load -> (XLA-)optimized graph -> zero-overhead repeat runs)."""

    def __init__(self, config: Config):
        from .. import jit

        if not config.model_dir():
            raise ValueError("Config has no model path (set_model)")
        cache_dir = config._switches.get("optim_cache_dir")
        if cache_dir:
            import os
            import jax as _jax
            try:  # persistent XLA executable cache (survives restarts)
                _jax.config.update("jax_compilation_cache_dir",
                                   os.path.abspath(cache_dir))
                _jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", 0)
                _jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)
                # the cache object is created lazily ONCE per process; a
                # dir set after the first compile needs an explicit reset
                from jax._src import compilation_cache as _cc
                _cc.reset_cache()
            except Exception as e:  # older jax without these knobs
                import warnings
                warnings.warn(
                    f"set_optim_cache_dir({cache_dir!r}) could not enable "
                    f"the persistent compile cache on this jax: {e!r}; "
                    "the predictor will recompile per process.",
                    RuntimeWarning)
        self._layer = jit.load(config.model_dir())
        meta = self._layer._meta
        if not meta.get("stablehlo"):
            raise ValueError(
                f"artifact {config.model_dir()!r} has no compiled graph "
                f"(re-export with jit.save(..., input_spec=...)); "
                f"export_error={meta.get('export_error')}")
        spec = meta.get("input_spec") or []
        self._inputs: Dict[str, InferTensor] = {}
        self._input_order: List[str] = []
        for i, s in enumerate(spec):
            name = s.get("name") or f"input_{i}"
            self._inputs[name] = InferTensor(name, s.get("shape"),
                                             s.get("dtype", "float32"))
            self._input_order.append(name)
        self._outputs: Dict[str, InferTensor] = {}
        self._output_order: List[str] = []

    # -- reference API ------------------------------------------------------

    def get_input_names(self) -> List[str]:
        return list(self._input_order)

    def get_input_handle(self, name: str) -> InferTensor:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        return list(self._output_order)

    def get_output_handle(self, name: str) -> InferTensor:
        return self._outputs[name]

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """Execute the compiled graph.  Either pre-fill the input handles
        (reference style) or pass arrays positionally; returns the output
        arrays (and fills the output handles)."""
        if inputs is not None:
            if len(inputs) != len(self._input_order):
                raise ValueError(
                    f"run() got {len(inputs)} inputs, model takes "
                    f"{len(self._input_order)} ({self._input_order}); a "
                    f"partial list would silently reuse stale handle data")
            for name, arr in zip(self._input_order, inputs):
                self._inputs[name].copy_from_cpu(arr)
        args = []
        for name in self._input_order:
            h = self._inputs[name]
            if h._data is None:
                raise RuntimeError(f"input {name!r} not set")
            args.append(h._data)
        out = self._layer.forward(*args)
        outs = out if isinstance(out, (tuple, list)) else [out]
        arrays = [np.asarray(o.numpy() if hasattr(o, "numpy") else o)
                  for o in outs]
        self._output_order = [f"output_{i}" for i in range(len(arrays))]
        # update handles IN PLACE: reference predictors let callers cache
        # get_output_handle once and re-read it after every run()
        for name, arr in zip(self._output_order, arrays):
            h = self._outputs.get(name)
            if h is None:
                h = self._outputs[name] = InferTensor(name)
            h._data = arr
            h._shape = list(arr.shape)
            h._dtype = str(arr.dtype)
        return arrays


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class _ClientError(ValueError):
    """Request-side fault -> HTTP 400 (anything else is a 500)."""


class DynamicBatcher:
    """Dynamic micro-batching for a fixed-shape compiled predictor.

    The exported executable takes a FIXED batch B (XLA static shapes), so
    the server coalesces concurrent requests: rows from queued requests are
    concatenated along dim 0, padded to B with the first row, run ONCE, and
    the per-request slices handed back.  This is the TPU analog of the
    reference serving stack's dynamic batching — one compiled program,
    maximum occupancy under concurrent load.

    ASSUMES the exported graph is row-independent along dim 0 (true for
    standard inference forwards): co-batched strangers and padding rows
    must not influence each other's outputs.  For models with cross-batch
    computation (e.g. batch statistics at inference time), start the
    server with ``serve(..., batching=False)``.
    """

    def __init__(self, predictor: Predictor, max_batch: int,
                 wait_ms: float = 3.0, log_len: int = 1024):
        import collections
        self._pred = predictor
        self.max_batch = max_batch
        self._wait = wait_ms / 1000.0
        self._cv = threading.Condition()
        self._queue: List[dict] = []
        self._stop = False
        # bounded: a long-running server must not leak one dict per batch
        self.batch_log = collections.deque(maxlen=log_len)
        # trailing dims per input from the exported spec: each request is
        # validated BEFORE enqueueing so one malformed request cannot sink
        # the co-batched strangers' requests with a 500
        self._tails = [tuple(predictor.get_input_handle(nm).shape()[1:])
                       for nm in predictor.get_input_names()]
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def submit(self, arrays: Sequence[np.ndarray]) -> List[np.ndarray]:
        rows = arrays[0].shape[0] if arrays[0].ndim else 1
        if rows < 1:
            raise _ClientError("request must carry at least one row")
        for j, a in enumerate(arrays):
            if a.ndim == 0 or a.shape[0] != rows:
                raise _ClientError(
                    "all inputs must share a leading batch dim for "
                    "batched serving")
            if tuple(a.shape[1:]) != self._tails[j]:
                raise _ClientError(
                    f"input {j} has per-row shape {tuple(a.shape[1:])}, "
                    f"model expects {self._tails[j]}")
        if rows > self.max_batch:
            raise _ClientError(
                f"request batch {rows} exceeds the compiled max batch "
                f"{self.max_batch}; split the request")
        item = {"arrays": arrays, "rows": rows,
                "event": threading.Event(), "result": None, "error": None}
        with self._cv:
            if self._stop:
                raise RuntimeError("server is shutting down")
            self._queue.append(item)
            self._cv.notify()
        item["event"].wait()
        if item["error"] is not None:
            raise item["error"]
        return item["result"]

    def shutdown(self):
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=2)

    def _loop(self):
        while True:
            with self._cv:
                while not self._queue and not self._stop:
                    self._cv.wait()
                if self._stop and not self._queue:
                    return
                # small coalescing window: let concurrent requests pile up
                deadline = time.monotonic() + self._wait
                while (sum(i["rows"] for i in self._queue) < self.max_batch
                       and not self._stop):
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cv.wait(timeout=left)
                batch, used = [], 0
                while self._queue and (
                        used + self._queue[0]["rows"] <= self.max_batch):
                    it = self._queue.pop(0)
                    batch.append(it)
                    used += it["rows"]
            if not batch:
                continue
            try:
                n_in = len(batch[0]["arrays"])
                cat = [np.concatenate([it["arrays"][j] for it in batch])
                       for j in range(n_in)]
                pad = self.max_batch - used
                if pad:
                    cat = [np.concatenate(
                        [c, np.repeat(c[:1], pad, axis=0)]) for c in cat]
                outs = self._pred.run(cat)
                self.batch_log.append({"requests": len(batch), "rows": used})
                off = 0
                for it in batch:
                    r = it["rows"]
                    it["result"] = [o[off:off + r] for o in outs]
                    off += r
            except Exception as e:  # noqa: BLE001
                for it in batch:
                    it["error"] = e
            finally:
                for it in batch:
                    it["event"].set()


def serve(predictor: Predictor, host: str = "127.0.0.1", port: int = 0,
          batching: bool = True, batch_wait_ms: float = 3.0,
          max_body_bytes: int = 64 * 1024 * 1024):
    """HTTP JSON endpoint over a predictor (reference serving surface,
    inference/capi_exp + analysis_predictor.h:94).

    POST / with {"inputs": [array, ...]} (nested lists; one entry per input
    in get_input_names() order, dtype from the exported spec) returns
    {"outputs": [array, ...]}.  Concurrent requests are dynamically
    micro-batched into the compiled batch size — this assumes the model is
    row-independent along the batch dim (see DynamicBatcher); pass
    batching=False to serialize requests instead.  Client faults return 400; server faults 500; bodies above
    `max_body_bytes` are rejected with 413.  Returns (server, thread);
    server.shutdown() stops both the HTTP loop and the batcher.
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    names = predictor.get_input_names()
    spec_dtypes = [predictor.get_input_handle(nm).type() for nm in names]
    batcher = None
    if batching and names:
        spec_shape = predictor.get_input_handle(names[0]).shape()
        if spec_shape and spec_shape[0] and spec_shape[0] > 0:
            batcher = DynamicBatcher(predictor, int(spec_shape[0]),
                                     wait_ms=batch_wait_ms)
    lock = threading.Lock()  # non-batched path: handles are stateful

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            status = 200
            try:
                n = int(self.headers.get("Content-Length", "0"))
                if n > max_body_bytes:
                    self.send_response(413)
                    self.end_headers()
                    return
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                    raw = req["inputs"]
                except (json.JSONDecodeError, KeyError, TypeError) as e:
                    raise _ClientError(f"bad request body: {e!r}")
                if len(raw) != len(names):
                    raise _ClientError(
                        f"expected {len(names)} inputs {names}, "
                        f"got {len(raw)}")
                try:
                    arrays = [np.asarray(a, dtype=np.dtype(dt))
                              for a, dt in zip(raw, spec_dtypes)]
                except (ValueError, TypeError) as e:
                    raise _ClientError(f"bad input arrays: {e!r}")
                if batcher is not None:
                    outs = batcher.submit(arrays)
                else:
                    with lock:
                        outs = predictor.run(arrays)
                body = json.dumps(
                    {"outputs": [o.tolist() for o in outs]}).encode()
            except _ClientError as e:
                body = json.dumps({"error": str(e)}).encode()
                status = 400
            except Exception as e:  # noqa: BLE001 — server-side fault
                body = json.dumps({"error": repr(e)}).encode()
                status = 500
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    srv = ThreadingHTTPServer((host, port), Handler)
    if batcher is not None:
        srv._batcher = batcher
        _orig_shutdown = srv.shutdown

        def _shutdown():
            # HTTP loop first: no new submissions can arrive once it stops,
            # so the batcher drains cleanly (reverse order could strand a
            # late submit() waiting on an event nobody will set)
            _orig_shutdown()
            batcher.shutdown()

        srv.shutdown = _shutdown
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, t
