"""paddle.inference — the deployment Predictor API (C39).

Reference parity: `paddle/fluid/inference/api/analysis_predictor.h:94`
(AnalysisPredictor) and the `paddle.inference` Python surface
(Config / create_predictor / get_input_handle / run / get_output_handle,
python/paddle/inference/__init__.py).  TPU-native mapping: the optimized
artifact is the StableHLO export written by `paddle_tpu.jit.save` — XLA is
the 274-pass analysis/optimization pipeline, so Config's IR/memory switches
are accepted-and-ignored (XLA always optimizes); the predictor AOT-loads
the artifact once and every `run()` is a cached compiled call.

A minimal HTTP JSON serving loop (`serve`) stands in for the reference's
C/Go serving surface: POST {"inputs": [[...], ...]} -> {"outputs": [...]}.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "InferTensor",
           "serve", "PlaceType"]


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"   # accepted for API parity; maps to the default device
    TPU = "tpu"
    XPU = "xpu"


class Config:
    """Predictor configuration (reference inference/api/paddle_analysis_config.h).

    Graph-optimization and memory switches exist for source compatibility;
    XLA already performs those passes, so they are recorded but change
    nothing.  `set_model(path_prefix)` points at a `jit.save` artifact
    (path without the .pdmodel/.pdparams/.stablehlo suffixes).
    """

    def __init__(self, model_prefix: Optional[str] = None,
                 params_file: Optional[str] = None):
        # reference two-arg form Config(prog_file, params_file): both point
        # at the same jit.save prefix in this build
        self._model_prefix = None
        self._device = None
        self._switches: Dict[str, object] = {}
        if model_prefix:
            self.set_model(model_prefix)

    def set_model(self, prefix: str, params: Optional[str] = None):
        self._model_prefix = (prefix[:-len(".pdmodel")]
                              if prefix.endswith(".pdmodel") else prefix)

    def model_dir(self) -> Optional[str]:
        return self._model_prefix

    def set_device(self, device: str):
        self._device = device

    # accepted-for-parity switches (XLA optimizes unconditionally)
    def enable_use_gpu(self, memory_pool_mb: int = 100, device_id: int = 0):
        self._device = PlaceType.GPU

    def disable_gpu(self):
        self._device = PlaceType.CPU

    def switch_ir_optim(self, flag: bool = True):
        self._switches["ir_optim"] = flag

    def enable_memory_optim(self, flag: bool = True):
        self._switches["memory_optim"] = flag

    def set_cpu_math_library_num_threads(self, n: int):
        self._switches["cpu_threads"] = n

    def disable_glog_info(self):
        self._switches["glog"] = False

    def summary(self) -> str:
        return json.dumps({"model": self._model_prefix,
                           "device": self._device,
                           "switches": self._switches}, indent=2)


class InferTensor:
    """Input/output handle (reference paddle_infer::Tensor)."""

    def __init__(self, name: str, shape: Optional[Sequence[int]] = None,
                 dtype: str = "float32"):
        self.name = name
        self._shape = list(shape) if shape is not None else None
        self._dtype = dtype
        self._data: Optional[np.ndarray] = None

    def reshape(self, shape: Sequence[int]):
        self._shape = list(shape)

    def copy_from_cpu(self, arr):
        # a real copy (reference paddle_infer::Tensor semantics): the caller
        # may reuse its staging buffer for the next batch before run()
        arr = np.array(arr, copy=True)
        self._data = arr
        self._shape = list(arr.shape)
        self._dtype = str(arr.dtype)

    def copy_to_cpu(self) -> np.ndarray:
        if self._data is None:
            raise RuntimeError(f"tensor {self.name!r} holds no data yet "
                               f"(run() the predictor first)")
        return np.asarray(self._data)

    def shape(self) -> List[int]:
        return list(self._shape or [])

    def type(self) -> str:
        return self._dtype


class Predictor:
    """AOT predictor over a jit.save StableHLO artifact (AnalysisPredictor
    analog: load -> (XLA-)optimized graph -> zero-overhead repeat runs)."""

    def __init__(self, config: Config):
        from .. import jit

        if not config.model_dir():
            raise ValueError("Config has no model path (set_model)")
        self._layer = jit.load(config.model_dir())
        meta = self._layer._meta
        if not meta.get("stablehlo"):
            raise ValueError(
                f"artifact {config.model_dir()!r} has no compiled graph "
                f"(re-export with jit.save(..., input_spec=...)); "
                f"export_error={meta.get('export_error')}")
        spec = meta.get("input_spec") or []
        self._inputs: Dict[str, InferTensor] = {}
        self._input_order: List[str] = []
        for i, s in enumerate(spec):
            name = s.get("name") or f"input_{i}"
            self._inputs[name] = InferTensor(name, s.get("shape"),
                                             s.get("dtype", "float32"))
            self._input_order.append(name)
        self._outputs: Dict[str, InferTensor] = {}
        self._output_order: List[str] = []

    # -- reference API ------------------------------------------------------

    def get_input_names(self) -> List[str]:
        return list(self._input_order)

    def get_input_handle(self, name: str) -> InferTensor:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        return list(self._output_order)

    def get_output_handle(self, name: str) -> InferTensor:
        return self._outputs[name]

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """Execute the compiled graph.  Either pre-fill the input handles
        (reference style) or pass arrays positionally; returns the output
        arrays (and fills the output handles)."""
        if inputs is not None:
            if len(inputs) != len(self._input_order):
                raise ValueError(
                    f"run() got {len(inputs)} inputs, model takes "
                    f"{len(self._input_order)} ({self._input_order}); a "
                    f"partial list would silently reuse stale handle data")
            for name, arr in zip(self._input_order, inputs):
                self._inputs[name].copy_from_cpu(arr)
        args = []
        for name in self._input_order:
            h = self._inputs[name]
            if h._data is None:
                raise RuntimeError(f"input {name!r} not set")
            args.append(h._data)
        out = self._layer.forward(*args)
        outs = out if isinstance(out, (tuple, list)) else [out]
        arrays = [np.asarray(o.numpy() if hasattr(o, "numpy") else o)
                  for o in outs]
        self._output_order = [f"output_{i}" for i in range(len(arrays))]
        # update handles IN PLACE: reference predictors let callers cache
        # get_output_handle once and re-read it after every run()
        for name, arr in zip(self._output_order, arrays):
            h = self._outputs.get(name)
            if h is None:
                h = self._outputs[name] = InferTensor(name)
            h._data = arr
            h._shape = list(arr.shape)
            h._dtype = str(arr.dtype)
        return arrays


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def serve(predictor: Predictor, host: str = "127.0.0.1", port: int = 0):
    """Minimal HTTP JSON endpoint over a predictor.

    POST / with {"inputs": [array, ...]} (nested lists; one entry per input
    in get_input_names() order, dtype taken from the exported spec) returns
    {"outputs": [array, ...]}.  Returns (server, thread); call
    server.shutdown() to stop.  Stands in for the reference's serving
    surface (inference/capi_exp, paddle serving) at demo scale.
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    lock = threading.Lock()  # predictor handles are stateful: serialize

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            try:
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n) or b"{}")
                raw = req["inputs"]
                names = predictor.get_input_names()
                if len(raw) != len(names):
                    raise ValueError(
                        f"expected {len(names)} inputs {names}, "
                        f"got {len(raw)}")
                spec_dtypes = [predictor.get_input_handle(nm).type()
                               for nm in names]
                arrays = [np.asarray(a, dtype=np.dtype(dt))
                          for a, dt in zip(raw, spec_dtypes)]
                with lock:
                    outs = predictor.run(arrays)
                body = json.dumps(
                    {"outputs": [o.tolist() for o in outs]}).encode()
                self.send_response(200)
            except Exception as e:  # noqa: BLE001 — report to the client
                body = json.dumps({"error": repr(e)}).encode()
                self.send_response(400)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    srv = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, t
