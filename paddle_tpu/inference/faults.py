"""Fault injection + invariant checking for the preemptible LLMEngine.

A preemptible engine is only trustworthy if every failure path — dispatch
errors on donated pools, page-allocation OOM, deadlines, cancellation,
shutdown — provably leaks nothing.  Happy-path tests cannot show that;
this harness can:

  * the engine calls ``fire(point, ...)`` at NAMED injection points
    (`FAULT_POINTS`) wrapped around prefill dispatch, decode dispatch,
    page allocation, sampling, and the swap-out/swap-in paths;
  * a `FaultSchedule` is a list of deterministic `FaultRule`s — "fail the
    3rd decode dispatch", "OOM every page allocation for slot 2", "fail
    the 1st prefill AND consume the donated pools" (simulating a TPU
    dispatch that dies after donation);
  * `check_invariants` is asserted after every schedule: zero leaked
    pages/slots, live (non-donated-away) pools, every submitted handle
    resolved exactly once, and the engine still able to serve a fresh
    request.

`tests/test_engine_chaos.py` runs the shipped schedules plus seeded
random ones (`random_schedule`); `tools/chaos_llm.py` is the soak CLI.
"""

from __future__ import annotations

import collections
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import llm_engine as _llm

__all__ = ["FAULT_POINTS", "FLEET_FAULT_POINTS", "InjectedFault",
           "InjectedCrash", "InvariantViolation", "FaultRule",
           "FaultInjector", "LockWitness", "arm_witness",
           "random_schedule", "drive", "check_invariants",
           "check_telemetry", "run_schedule", "ScriptedEngine",
           "EchoDrafter", "fleet_random_schedule", "drive_fleet",
           "fleet_check_invariants", "fleet_run_schedule"]

# the engine's named injection points, in rough lifecycle order ("step"
# wraps the whole step loop: a crash=True rule there kills the step
# THREAD, not just one request — replica death).  "prefill" fires once
# per prefill span scheduled into a ragged batch; "prefill_chunk" fires
# right after it with the chunk's (tokens, start) context — a rule there
# kills a request mid-chunked-prefill; "decode" fires once per unified
# ragged dispatch (the ONE attention dispatch of a mixed step).
# Speculative decoding adds "draft" (per decoding slot, before the
# drafter proposes — a fault there fails that request, a consume_pools
# rule poisons the step's dispatch) and "verify" (once per dispatch
# carrying >= 1 verify span, before the accept/reject pass — a fault
# there fails the step like a dispatch fault, mid-speculation).
# "fused_decode" fires right after "decode" on steps routed through the
# fused single-dispatch path (sampling inside the dispatch): a fault
# there lands at the exact point where the fused executable would
# consume the donated pools, the failure shape fused serving adds.
# "kv_transfer" fires on every tier/handoff movement of KV pages —
# prefill-side export, decode-side import, and host-tier promotion
# (fire-context `direction` says which).  Dispatch-class: a
# consume_pools rule poisons the gather/scatter exactly like a swap
# fault; a crash rule kills a prefill replica MID-TRANSFER, the
# zero-tokens-stranded shape the disaggregated fleet must retry.
FAULT_POINTS = ("step", "prefill", "prefill_chunk", "draft", "decode",
                "fused_decode", "verify", "page_alloc", "sample",
                "swap_out", "swap_in", "kv_transfer")

# the Router's named injection points — fleet-tier failure shapes.
#   replica_death:    fired per replica on each health tick; a match makes
#                     the router CRASH that replica at its next step (the
#                     engine strands slots/handles exactly as a real dead
#                     step thread would)
#   health_flap:      fired inside each health probe; a match makes the
#                     probe report unhealthy — a healthy replica gets
#                     ejected and must earn reinstatement via canary
#   stats_staleness:  fired inside each placement-score read; a match
#                     makes the replica's gauges unreadable — the router
#                     must deprioritize, not crash or eject
#   slow_replica:     use with delay=...: the score read stalls (slow
#                     stats RPC); the router keeps serving, placement
#                     just pays the latency
FLEET_FAULT_POINTS = ("replica_death", "slow_replica", "health_flap",
                      "stats_staleness")

# points where a `consume_pools` rule is meaningful: the engine passes its
# (to-be-donated or read) pools in the fire() context there
_DISPATCH_POINTS = ("prefill", "prefill_chunk", "draft", "decode",
                    "fused_decode", "verify", "swap_out", "swap_in",
                    "kv_transfer")


class InjectedFault(RuntimeError):
    """Raised by the injector at a scheduled point.  A RuntimeError so the
    page-allocation path treats an injected OOM exactly like a real
    pool-exhausted condition."""


class InjectedCrash(BaseException):
    """Raised by a crash=True rule.  A BaseException ON PURPOSE: it
    escapes every `except Exception` backstop in the engine — the step
    thread dies mid-step with slots held and handles unresolved, which is
    the replica-death shape the fleet tier (Router + EngineSupervisor)
    exists to survive.  Single-engine schedules should not use it; there
    is nothing above the engine to recover."""


class InvariantViolation(AssertionError):
    """check_invariants found a leak or an unresolved/double-resolved
    handle."""


class FaultRule:
    """One deterministic fault: fire at the `nth` matching visit of
    `point` (1-based, counted per rule after the slot/replica filters),
    or on EVERY matching visit (`always=True`, e.g. "OOM every allocation
    for slot 2").  `consume_pools=True` deletes the pool buffers before
    raising — simulating a TPU dispatch that fails AFTER consuming its
    donated arguments, which is the nastiest real-world failure the
    engine must recover from.

    Fleet extensions: `replica=` filters on the router-provided replica
    id (fleet points) the way `slot=` filters engine points;
    `crash=True` raises InjectedCrash (BaseException — kills the step
    thread, replica death) instead of InjectedFault; `delay=` seconds
    makes the rule SLEEP at the point instead of raising (a slow
    replica, not a broken one)."""

    def __init__(self, point: str, nth: int = 1,
                 slot: Optional[int] = None, always: bool = False,
                 consume_pools: bool = False,
                 replica: Optional[int] = None, crash: bool = False,
                 delay: Optional[float] = None):
        if point not in FAULT_POINTS and point not in FLEET_FAULT_POINTS:
            raise ValueError(f"unknown fault point {point!r}; one of "
                             f"{FAULT_POINTS + FLEET_FAULT_POINTS}")
        self.point = point
        self.nth = int(nth)
        self.slot = slot
        self.always = bool(always)
        self.consume_pools = bool(consume_pools)
        self.replica = replica
        self.crash = bool(crash)
        self.delay = None if delay is None else float(delay)
        self.seen = 0     # matching visits
        self.fired = 0

    def matches(self, point: str, ctx: Dict) -> bool:
        if point != self.point:
            return False
        if self.slot is not None and ctx.get("slot") != self.slot:
            return False
        if self.replica is not None and ctx.get("replica") != self.replica:
            return False
        self.seen += 1
        if self.always:
            return True
        return self.fired == 0 and self.seen == self.nth

    def __repr__(self):
        bits = [self.point]
        if self.always:
            bits.append("always")
        else:
            bits.append(f"nth={self.nth}")
        if self.slot is not None:
            bits.append(f"slot={self.slot}")
        if self.replica is not None:
            bits.append(f"replica={self.replica}")
        if self.consume_pools:
            bits.append("consume_pools")
        if self.crash:
            bits.append("crash")
        if self.delay is not None:
            bits.append(f"delay={self.delay}")
        return f"FaultRule({', '.join(bits)})"

    def to_dict(self) -> dict:
        return {"point": self.point, "nth": self.nth, "slot": self.slot,
                "always": self.always, "consume_pools": self.consume_pools,
                "replica": self.replica, "crash": self.crash,
                "delay": self.delay}


class FaultInjector:
    """Deterministic fault schedule.  Install via
    ``LLMEngine(..., faults=FaultInjector(rules))`` (or set
    ``engine.faults``); the engine calls `fire` at each injection point
    and a matching rule raises `InjectedFault` there."""

    def __init__(self, rules: Sequence[FaultRule] = ()):
        self.rules = list(rules)
        self.visits: collections.Counter = collections.Counter()
        self.fired: List[dict] = []
        # armed by the chaos soaks: a LockWitness records the firing
        # thread's held witnessed locks at every dispatch-class point
        self.witness = None

    def fire(self, point: str, engine=None, pools=None, **ctx) -> None:
        if self.witness is not None and point in _DISPATCH_POINTS:
            self.witness.check_dispatch(point)
        self.visits[point] += 1
        for rule in self.rules:
            if not rule.matches(point, ctx):
                continue
            rule.fired += 1
            self.fired.append({"point": point,
                               "visit": self.visits[point],
                               "rule": repr(rule),
                               "slot": ctx.get("slot"),
                               "replica": ctx.get("replica")})
            if rule.delay is not None:
                # slow, not broken: stall the caller and keep scanning —
                # a delay rule composes with a raise rule at the same point
                time.sleep(rule.delay)
                continue
            if rule.consume_pools and pools is not None:
                for arr in pools.values():
                    try:
                        arr.delete()   # simulate donation consuming it
                    except Exception:  # noqa: BLE001 — already deleted etc.
                        pass
            if rule.crash:
                raise InjectedCrash(
                    f"injected CRASH at {point!r} "
                    f"(visit {self.visits[point]}, {rule!r})")
            raise InjectedFault(
                f"injected fault at {point!r} "
                f"(visit {self.visits[point]}, {rule!r})")


def random_schedule(seed: int, max_rules: int = 2) -> List[FaultRule]:
    """Deterministic pseudo-random schedule for soak runs: 1..max_rules
    rules over random points/visits, with a slice of always-OOM-per-slot
    and consume-donated-pools variants."""
    rng = random.Random(seed)
    rules = []
    for _ in range(rng.randint(1, max_rules)):
        point = rng.choice(FAULT_POINTS)
        if point == "page_alloc" and rng.random() < 0.35:
            rules.append(FaultRule(point, slot=rng.randrange(3),
                                   always=True))
            continue
        consume = point in _DISPATCH_POINTS and rng.random() < 0.3
        rules.append(FaultRule(point, nth=rng.randint(1, 8),
                               consume_pools=consume))
    return rules


def drive(engine, handles: Sequence = (), max_steps: int = 5000) -> int:
    """Step the engine until every handle resolves (bounded).  Returns the
    number of steps taken; a stall (no progress with unresolved handles)
    simply stops — check_invariants will report the unresolved handles."""
    steps = 0
    while any(not h.done() for h in handles) and steps < max_steps:
        try:
            progressed = engine.step()
        except Exception:  # noqa: BLE001 — step() handles its own faults;
            progressed = True          # a backstop escape still made work
        steps += 1
        if not progressed:
            break
    return steps


def check_telemetry(engine) -> List[str]:
    """Cross-check the TELEMETRY surface against engine ground truth:
    every pool/queue/slot gauge the /metrics scrape (and the fleet
    router's placement score) reads must agree with the allocator state
    `check_invariants` verifies directly.  A mismatch means a gauge was
    rebound, its callback broke (NaN), or the telemetry layer drifted
    from the engine — leak detection via gauges only works if the two
    agree, so the chaos soaks fail on disagreement.  Returns mismatch
    strings ([] when the surfaces agree)."""
    reg = getattr(engine, "metrics", None)
    if reg is None:
        return []
    cache = engine.cache
    expect = {
        "llm_free_pages": cache.free_page_count,
        "llm_free_slots": cache.free_slot_count,
        "llm_pool_used_pages":
            cache.num_pages - 1 - cache.free_page_count,
        "llm_queue_depth": len(engine._pending),
        "llm_slots_in_flight": len(engine._slots),
    }
    idx = getattr(engine, "prefix_index", None)
    if idx is not None:
        expect["llm_prefix_cached_pages"] = idx.cached_pages
    mismatches = []
    for name, truth in expect.items():
        g = reg.get(name)
        if g is None:
            mismatches.append(f"telemetry gauge {name} is not registered")
            continue
        v = g.value
        if v != v or int(v) != int(truth):   # NaN-safe compare
            mismatches.append(
                f"telemetry drift: gauge {name}={v} but engine ground "
                f"truth is {truth} (leak detection via gauges would "
                "lie)")
    return mismatches


# -- dynamic lock-order witness --------------------------------------------
#
# analysis.threadlint PREDICTS the serving stack's lock discipline from
# the ASTs; the witness CONFIRMS it at runtime — the same static-
# predicts/dynamic-confirms contract analysis.equiv gives the rewrite
# tier.  The soaks arm it (run_schedule/fleet_run_schedule witness=True,
# the tools/chaos_* default) and fail on any witnessed violation.

class _WitnessedLock:
    """Delegating wrapper around a Lock/RLock/Condition that reports
    every acquire/release to its `LockWitness`.  The full Condition
    surface is forwarded; `wait`/`wait_for` pop the held stack for the
    duration (the condition releases its lock inside) and re-check the
    re-acquire as a fresh ordering event.  A `with` statement binds the
    wrapper object itself, so swapping an attribute mid-run can never
    orphan an acquired inner lock."""

    __slots__ = ("_w", "_inner", "_name")

    def __init__(self, witness: "LockWitness", inner, name: str):
        self._w = witness
        self._inner = inner
        self._name = name

    def acquire(self, *args, **kwargs):
        # order is noted BEFORE blocking: an acquisition that would
        # deadlock still records the inversion that caused it
        self._w.note_order(self._name)
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._w.push(self._name)
        return got

    def release(self):
        self._inner.release()
        self._w.pop(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # Condition surface
    def wait(self, timeout=None):
        self._w.pop(self._name)
        try:
            return self._inner.wait(timeout)
        finally:
            self._w.note_order(self._name)
            self._w.push(self._name)

    def wait_for(self, predicate, timeout=None):
        self._w.pop(self._name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._w.note_order(self._name)
            self._w.push(self._name)

    def notify(self, n: int = 1):
        return self._inner.notify(n)

    def notify_all(self):
        return self._inner.notify_all()

    def __getattr__(self, attr):
        return getattr(self._inner, attr)

    def __repr__(self):
        return f"<witnessed {self._name}: {self._inner!r}>"


class LockWitness:
    """Per-thread lock-acquisition-order recorder over the serving
    stack's witnessed locks.  One global edge graph (A -> B: some thread
    acquired B while holding A); two violation shapes:

      * ORDER INVERSION — a new edge closes a cycle in the graph; the
        violation names the full cycle (`A -> B -> A`), which is exactly
        the deadlock schedule two threads can now interleave into.
        Re-entrant re-acquisition (RLock, Condition re-acquire after
        wait) is not an ordering event and never self-edges.
      * LOCK HELD ACROSS A FENCED DISPATCH — the thread firing a
        dispatch-class injection point (`_DISPATCH_POINTS`) holds a
        witnessed lock: a device dispatch under a Python lock serializes
        every other thread behind device latency.

    Violations are deduplicated (one per new edge / per held-set+point),
    so a soak's report stays readable; `check_invariants` folds them
    into the soak verdict."""

    def __init__(self):
        self._mu = threading.Lock()          # guards graph + violations
        self._tls = threading.local()        # per-thread held stack
        self._edges: Dict[str, set] = {}
        self._dispatch_seen: set = set()
        self.acquisitions = 0
        self.violations: List[str] = []
        self._names: set = set()
        self._wrapped: List[Tuple[object, str, object]] = []

    # -- arming -------------------------------------------------------------

    def wrap(self, owner, attr: str, name: str) -> _WitnessedLock:
        """Replace `owner.attr` with a witnessed wrapper named `name`
        (idempotent).  `name` uses the static tier's lock ids
        ("LLMEngine._cv", "Router._lock"), so a witnessed cycle names
        the same nodes a threadlint LOCK_ORDER_CYCLE would."""
        inner = getattr(owner, attr)
        if isinstance(inner, _WitnessedLock):
            return inner
        wrapped = _WitnessedLock(self, inner, name)
        setattr(owner, attr, wrapped)
        self._names.add(name)
        self._wrapped.append((owner, attr, inner))
        return wrapped

    def unwrap_all(self) -> None:
        """Restore every wrapped attribute (tests clean up with this)."""
        for owner, attr, inner in self._wrapped:
            setattr(owner, attr, inner)
        self._wrapped.clear()

    # -- per-thread held stack ----------------------------------------------

    def _held(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def push(self, name: str) -> None:
        self._held().append(name)

    def pop(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    # -- events -------------------------------------------------------------

    def note_order(self, name: str) -> None:
        held = self._held()
        with self._mu:
            self.acquisitions += 1
            for h in dict.fromkeys(held):     # distinct, order-kept
                if h == name:
                    continue                  # re-entrant, not ordering
                succ = self._edges.setdefault(h, set())
                if name in succ:
                    continue                  # edge known (and checked)
                path = self._path(name, h)    # existing name ~> h?
                if path is not None:
                    cycle = " -> ".join([h] + path)
                    self.violations.append(
                        f"lock-order inversion: thread "
                        f"{threading.current_thread().name!r} acquired "
                        f"{name} while holding {h}, but the order "
                        f"{' -> '.join(path)} was already witnessed — "
                        f"cycle {cycle}")
                succ.add(name)

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """BFS over the edge graph; [src, ..., dst] or None.  Called
        under _mu."""
        prev = {src: None}
        queue = collections.deque([src])
        while queue:
            node = queue.popleft()
            if node == dst:
                out = []
                while node is not None:
                    out.append(node)
                    node = prev[node]
                return out[::-1]
            for nxt in self._edges.get(node, ()):
                if nxt not in prev:
                    prev[nxt] = node
                    queue.append(nxt)
        return None

    def check_dispatch(self, point: str) -> None:
        """Called by FaultInjector.fire at dispatch-class points."""
        held = tuple(dict.fromkeys(self._held()))
        if not held:
            return
        with self._mu:
            key = (held, point)
            if key in self._dispatch_seen:
                return
            self._dispatch_seen.add(key)
            self.violations.append(
                f"lock held across fenced dispatch: thread "
                f"{threading.current_thread().name!r} holds "
                f"{', '.join(held)} at injection point {point!r} — a "
                "device dispatch under a Python lock serializes the "
                "stack behind device latency")

    # -- reading ------------------------------------------------------------

    def report(self) -> dict:
        with self._mu:
            edges = sorted(f"{a} -> {b}"
                           for a, succ in self._edges.items()
                           for b in succ)
            locks = sorted(self._names
                           | set(self._edges)
                           | {b for s in self._edges.values() for b in s})
            return {"ok": not self.violations,
                    "acquisitions": self.acquisitions,
                    "locks": locks,
                    "edges": edges,
                    "violations": list(self.violations)}


def arm_witness(engine, witness: Optional[LockWitness] = None,
                attach: bool = True) -> LockWitness:
    """Wrap one engine's serving locks (`_cv`, and the attached
    kvstore's `_lock` if any) under a LockWitness.  `attach=True` also
    sets `engine._lock_witness` so `check_invariants` folds the
    witness's verdicts into its threads section — fleet runs pass
    attach=False and keep ONE shared witness at the fleet level instead
    (the edge graph must span router + every replica to see cross-
    component cycles).  An installed FaultInjector gets the witness for
    its dispatch-point check."""
    w = witness if witness is not None else LockWitness()
    w.wrap(engine, "_cv", "LLMEngine._cv")
    store = getattr(engine, "kvstore", None)
    if store is not None and hasattr(store, "_lock"):
        w.wrap(store, "_lock", "TieredPrefixStore._lock")
    if attach:
        engine._lock_witness = w
    inj = getattr(engine, "faults", None)
    if inj is not None:
        inj.witness = w
    return w


def check_invariants(engine, handles: Sequence = (), probe: bool = True,
                     raise_on_violation: bool = True,
                     probe_timeout: float = 120.0) -> dict:
    """Assert the engine leaked nothing.  Call once quiesced (all handles
    resolved — see `drive`).  Checks:

      * zero leaked slots: no in-flight slots, no pending requests, every
        decode slot back in the free list;
      * zero leaked pages: free pages + slot-held pages + prefix-index
        pages are EXACTLY pages 1..num_pages-1, no page both free and
        referenced (page 0 reserved, never allocated);
      * refcount proofs: every allocated page's refcount equals its
        page-table occupancy (slot-list appearances + index references);
        no page sits in the free pool while its refcount is nonzero, and
        no refcount survives without a holder — so a shared page can
        never be freed out from under a co-holder, and a cached prefix
        can never point at a recycled page (the "no prefix survives pool
        deallocation" guarantee: pool recovery clears the index, and any
        stale reference would trip this identity);
      * pools live: the k/v buffers were not donated away and lost;
      * every submitted handle resolved exactly once;
      * metrics registry consistency: every accepted request landed in
        EXACTLY one terminal counter (accepted == completed + cancelled
        + timed_out + failed + still-queued + in-flight), and the
        stats_snapshot values match the registry counters /metrics
        renders (the two surfaces share storage and must not drift);
      * the engine still serves: a fresh 1-token request completes.

    Returns a report dict; raises InvariantViolation on any breach unless
    raise_on_violation=False."""
    cache = engine.cache
    violations: List[str] = []

    if engine._pending:
        violations.append(f"{len(engine._pending)} requests still pending")
    if engine._slots:
        violations.append(f"slots still in flight: {sorted(engine._slots)}")
    held = [p for pages in cache._slot_pages.values() for p in pages]
    if cache._slot_pages:
        violations.append(
            f"slot page lists not reclaimed: {dict(cache._slot_pages)}")
    idx = getattr(engine, "prefix_index", None)
    idx_refs = {} if idx is None else dict(idx.page_refs())
    # page accounting under sharing: every allocatable page is either
    # free or referenced (never both, never neither), and a shared page
    # appears once per holder in the refcount identity below
    referenced = set(held) | set(idx_refs)
    free_set = set(cache._free_pages)
    if len(cache._free_pages) != len(free_set):
        violations.append(
            f"free list holds duplicates (double-free): "
            f"{sorted(cache._free_pages)}")
    both = free_set & referenced
    if both:
        violations.append(
            f"pages {sorted(both)} are in the free pool AND referenced "
            "(freed while refcount > 0 — a co-holder's KV can be "
            "recycled under it)")
    pages = sorted(free_set | referenced)
    if pages != list(range(1, cache.num_pages)):
        violations.append(
            f"page accounting broken: free+held+cached={pages} != "
            f"1..{cache.num_pages - 1} (leak or double-free)")
    # refcount == page-table occupancy: slot-list appearances plus
    # prefix-index references, for every allocated page
    want_refs = collections.Counter(held)
    for p, n in idx_refs.items():
        want_refs[p] += n
    for p in range(1, cache.num_pages):
        have = cache.refcount(p)
        want = want_refs.get(p, 0)
        if have != want:
            violations.append(
                f"refcount identity broken: page {p} has refcount "
                f"{have} but {want} holder(s) (slot lists + prefix "
                "index) — shared-page bookkeeping drifted")
    slots = sorted(cache._free_slots + list(cache._slot_pages))
    if slots != list(range(cache.max_slots)):
        violations.append(
            f"slot accounting broken: free+held={slots} != "
            f"0..{cache.max_slots - 1}")
    for side in ("k", "v"):
        arr = cache.pools[side]
        if getattr(arr, "is_deleted", lambda: False)():
            violations.append(f"{side} pool was donated away and never "
                              "recovered")

    # metrics registry consistency.  Counters and registry values are
    # read in ONE pass under engine._cv (every counter write holds it),
    # so the snapshot cannot tear against a concurrent step thread.  The
    # strict terminal-counter identity is only decidable at quiescence —
    # mid-flight, a slot leaves engine._slots (lock-free, step-thread
    # owned) strictly before its terminal counter lands — so it is
    # asserted exactly when the leak checks above found the engine
    # quiesced, which is how every chaos schedule calls this.
    registry = getattr(engine, "metrics", None)
    with engine._cv:
        snap = dict(engine.stats)
        quiesced = not engine._pending and not engine._slots
        reg_vals = {}
        if registry is not None:
            for key in ("accepted", "admitted", "completed", "cancelled",
                        "timed_out", "failed", "preemptions",
                        "spec_drafted", "spec_accepted", "handoffs"):
                counter = registry.get(f"llm_{key}_total")
                reg_vals[key] = (None if counter is None
                                 else int(counter.value))
    if "accepted" in snap and quiesced:
        # a handoff is a terminal outcome at THIS engine: the request
        # resolved here with PrefillHandoff (zero tokens) and continues
        # life as a fresh submit on a decode replica
        outcomes = (snap["completed"] + snap["cancelled"]
                    + snap["timed_out"] + snap["failed"]
                    + snap.get("handoffs", 0))
        if snap["accepted"] != outcomes:
            violations.append(
                f"metrics identity broken: accepted={snap['accepted']} != "
                f"completed+cancelled+timed_out+failed+handoffs="
                f"{outcomes} (a request leaked out of, or was "
                "double-counted into, the terminal counters)")
    if "ragged_batch_tokens" in snap:
        # every valid token of every ragged dispatch is either a decode
        # span's token, part of a prefill chunk, or a speculative verify
        # row — counted in one place, so drift means a batch was built
        # and accounted inconsistently
        ragged = snap["ragged_batch_tokens"]
        parts = (snap.get("decode_tokens", 0)
                 + snap.get("prefill_tokens", 0)
                 + snap.get("verify_tokens", 0))
        if ragged != parts:
            violations.append(
                f"ragged token identity broken: ragged_batch_tokens="
                f"{ragged} != decode_tokens+prefill_tokens+verify_tokens="
                f"{parts}")
    if "verify_tokens" in snap:
        # speculative token identities: every dispatched verify row is an
        # accepted draft, a rejected draft, or the span's one bonus row
        # (whose logits sample the correction/bonus token); every draft
        # is accepted or rejected exactly once; a span emits its accepted
        # drafts plus the bonus token, minus anything cut by
        # eos/max_new_tokens truncation.  The row-vs-verdict identity is
        # only decidable at quiescence: verify_tokens lands with the
        # dispatch accounting, the verdicts land after the accept/reject
        # pass, so mid-step the rows legitimately lead.
        rows = snap["verify_tokens"]
        acc, rej = snap.get("spec_accepted", 0), snap.get("spec_rejected", 0)
        bonus, drafted = snap.get("spec_bonus", 0), snap.get("spec_drafted", 0)
        if quiesced and rows != acc + rej + bonus:
            violations.append(
                f"verify row identity broken: verify_tokens={rows} != "
                f"spec_accepted+spec_rejected+spec_bonus="
                f"{acc + rej + bonus}")
        if drafted != acc + rej:
            violations.append(
                f"draft identity broken: spec_drafted={drafted} != "
                f"spec_accepted+spec_rejected={acc + rej}")
        if snap.get("spec_emitted", 0) > acc + bonus:
            violations.append(
                f"spec emission overflow: spec_emitted="
                f"{snap['spec_emitted']} > spec_accepted+spec_bonus="
                f"{acc + bonus} (a verify span emitted tokens it never "
                "sampled)")
    if registry is not None:
        for key, val in reg_vals.items():
            if val is None:
                violations.append(f"registry missing counter "
                                  f"llm_{key}_total")
            elif key in snap and val != snap[key]:
                violations.append(
                    f"/stats and /metrics drifted: {key}={snap[key]} vs "
                    f"llm_{key}_total={val}")

    # per-tenant QoS identities: every untagged counter the engine keeps
    # is the SUM of its per-tenant twins (each global inc carries a
    # tenant inc at the same site, under the same lock), and the
    # per-tenant queue-depth gauges must match a ground-truth recount of
    # the WFQ queue — a tenant counter that drifts from the allocator
    # truth would let a flooding tenant hide inside the aggregate
    tenant_stats = getattr(engine, "_tenant_stats", None)
    if tenant_stats is not None:
        with engine._cv:
            per_tenant = {t: dict(st) for t, st in tenant_stats.items()}
            tsnap = dict(engine.stats)
            tquiesced = not engine._pending and not engine._slots
            depths_kept = (engine._pending.depths()
                           if hasattr(engine._pending, "depths") else {})
            recount: Dict[str, int] = {}
            for req in engine._pending:
                t = getattr(req, "tenant", "default")
                recount[t] = recount.get(t, 0) + 1
            pending_total = len(engine._pending)
        if tquiesced:
            for tkey, gkey in (("accepted", "accepted"),
                               ("admitted", "admitted"),
                               ("completed", "completed"),
                               ("preempted", "preemptions"),
                               ("emitted_tokens", "emitted_tokens")):
                if gkey not in tsnap:
                    continue
                total = sum(st.get(tkey, 0)
                            for st in per_tenant.values())
                if total != tsnap[gkey]:
                    violations.append(
                        f"per-tenant identity broken: sum of tenant "
                        f"{tkey}={total} != llm_{gkey}_total="
                        f"{tsnap[gkey]} (a request was counted under "
                        "the wrong tenant, or not at all)")
        kept_nonzero = {t: d for t, d in depths_kept.items() if d}
        if kept_nonzero != recount:
            violations.append(
                f"per-tenant queue depth drifted: WFQ bookkeeping says "
                f"{kept_nonzero} but a recount of the pending queue "
                f"says {recount}")
        if sum(depths_kept.values()) != pending_total:
            violations.append(
                f"per-tenant queue depths sum to "
                f"{sum(depths_kept.values())} but len(engine._pending)="
                f"{pending_total}")
        reg2 = getattr(engine, "metrics", None)
        if reg2 is not None:
            label_of = getattr(engine, "_tenant_label", lambda s: s)
            for t in per_tenant:
                g = reg2.get(f"llm_tenant_{label_of(t)}_queue_depth")
                if g is None:
                    violations.append(
                        f"tenant {t!r} has counters but no queue-depth "
                        "gauge")
                    continue
                v = g.value
                truth = recount.get(t, 0)
                if v != v or int(v) != int(truth):
                    violations.append(
                        f"tenant {t!r} queue-depth gauge={v} but ground "
                        f"truth is {truth}")

    for i, h in enumerate(handles):
        if not h.done():
            violations.append(f"handle {i} never resolved")
        elif h.resolutions != 1:
            violations.append(
                f"handle {i} resolved {h.resolutions} times (want 1)")
        elif h.error is None and not h.tokens:
            violations.append(f"handle {i} resolved empty without error")

    probe_tokens = None
    if probe and not violations:
        saved, engine.faults = engine.faults, None
        try:
            # handoff=False: on a prefill-class replica the probe must
            # decode locally — a PrefillHandoff resolution would be a
            # false "cannot serve" verdict
            h = engine.submit([1], max_new_tokens=1, handoff=False)
            if engine._thread is not None:
                probe_tokens = h.result(timeout=probe_timeout)
            else:
                drive(engine, [h])
                probe_tokens = h.result(timeout=0)
            if len(probe_tokens) != 1:
                violations.append(
                    f"fresh probe returned {probe_tokens!r}, want 1 token")
        except Exception as e:  # noqa: BLE001
            violations.append(f"engine cannot serve a fresh request: {e!r}")
        finally:
            engine.faults = saved

    # telemetry cross-check at quiescence: the gauges /metrics scrapes
    # (and the router places on) must agree with the allocator ground
    # truth just verified above — the chaos soaks use this as
    # gauge-based leak detection, and it only works if the two surfaces
    # cannot disagree silently
    telemetry = check_telemetry(engine)
    violations.extend(telemetry)

    # threads section: step-thread liveness discipline plus the dynamic
    # lock-order witness's verdicts (armed by the chaos soaks).  The
    # step thread is daemon, but daemon-ness is a crash cushion, not a
    # lifecycle: once _stop is set the thread must JOIN, or slots/pages
    # it owns outlive the engine that accounts for them.
    th = getattr(engine, "_thread", None)
    threads = {
        "step_thread_alive": bool(th is not None and th.is_alive()),
        "stopped": bool(getattr(engine, "_stop", False)),
    }
    if th is not None and getattr(engine, "_stop", False):
        th.join(timeout=5.0)
        if th.is_alive():
            violations.append(
                "step thread still alive after _stop was set — "
                "shutdown() must join it before the engine is abandoned "
                "(a leaked step thread owns slots and pages)")
    witness = getattr(engine, "_lock_witness", None)
    if witness is not None:
        wrep = witness.report()
        threads["witness"] = wrep
        violations.extend(f"lock witness: {v}"
                          for v in wrep["violations"])

    report = {
        "ok": not violations,
        "violations": violations,
        "free_pages": cache.free_page_count,
        "free_slots": cache.free_slot_count,
        "num_pages": cache.num_pages,
        "probe_tokens": probe_tokens,
        "telemetry": {"ok": not telemetry, "mismatches": telemetry},
        "threads": threads,
        "stats": engine.stats_snapshot(),
    }
    if violations:
        # black-box the leaking engine: the state the checker just
        # caught is exactly what a post-mortem needs (no-op without an
        # armed flight recorder; dump() never raises)
        fl = getattr(engine, "flight", None)
        if fl is not None:
            fl.dump("invariant_violation",
                    error=InvariantViolation("; ".join(violations)))
    if violations and raise_on_violation:
        raise InvariantViolation("; ".join(violations))
    return report


def run_schedule(make_engine: Callable[[], object],
                 rules: Sequence[FaultRule],
                 requests: Sequence[Tuple[Sequence[int], int]],
                 probe: bool = True, max_steps: int = 5000,
                 witness: bool = False) -> dict:
    """Build a fresh engine, install the schedule, submit the workload
    ((prompt, max_new_tokens) pairs, optionally (prompt, max_new_tokens,
    submit_kwargs) triples — the kwargs dict passes through to
    engine.submit, which is how tenant-labeled chaos schedules tag their
    traffic), drive to quiescence, and run the invariant checker.
    `witness=True` arms the LockWitness on the engine's locks (order
    inversions and locks-across-dispatch become invariant violations)
    and proves the schedule leaked no threads.  Returns the invariant
    report extended with the schedule, the faults actually fired, and
    the final counters.  Raises InvariantViolation on any leak."""
    before_threads = set(threading.enumerate())
    injector = FaultInjector(rules)
    engine = make_engine()
    engine.faults = injector
    if witness:
        arm_witness(engine)
    handles = []
    rejected = 0
    for item in requests:
        prompt, max_new = item[0], item[1]
        kw = item[2] if len(item) > 2 else {}
        try:
            handles.append(engine.submit(prompt, max_new, **kw))
        except (ValueError, RuntimeError):
            rejected += 1      # QueueFull / validation — resolved by refusal
    steps = drive(engine, handles, max_steps=max_steps)
    report = check_invariants(engine, handles, probe=probe)
    # thread-leak proof: a schedule must not leave threads behind (the
    # factory may have started a step thread or helpers; everything must
    # be joinable within grace once the run quiesced)
    leaked = [t for t in threading.enumerate()
              if t not in before_threads and t.is_alive()]
    for t in leaked:
        t.join(timeout=1.0)
    leaked = [t for t in leaked if t.is_alive()]
    report.setdefault("threads", {})["leaked"] = \
        [f"{t.name} (daemon={t.daemon})" for t in leaked]
    if any(not t.daemon for t in leaked):
        raise InvariantViolation(
            "non-daemon thread(s) leaked past the schedule: "
            + ", ".join(t.name for t in leaked if not t.daemon))
    report.update({
        "schedule": [r.to_dict() for r in rules],
        "fired": list(injector.fired),
        "requests": len(handles),
        "rejected": rejected,
        "completed": sum(1 for h in handles if h.error is None),
        "failed": sum(1 for h in handles if h.error is not None),
        "steps": steps,
    })
    return report


# -- scripted engine: the real scheduler at chaos-suite speed --------------

class EchoDrafter:
    """Always-propose drafter for chaos/soak runs: proposes the
    history's own head, so EVERY decode step carries a verify span and
    the drafts are mostly rejected — the most chaotic case, since every
    span rolls back under the injected faults and page pressure.
    Duck-typed to generation.Drafter (propose(history, k)) without
    importing the model stack."""

    def propose(self, history, k):
        return np.asarray(history[:k], np.int32)

class _ScriptedConfig:
    """Minimal model config for a ScriptedEngine: just enough for the
    paged-cache bookkeeping (1 layer, 1 KV head, head_dim 2 — a few KB of
    pool, but real jax buffers so consume_pools rules and pool-recovery
    behave exactly as on the full model)."""

    num_hidden_layers = 1
    num_key_value_heads = 1
    hd = 2
    dtype = np.float32
    max_position_embeddings = 128

    def __init__(self, vocab_size: int = 97):
        self.vocab_size = int(vocab_size)


def _script_next(seq: Sequence[int], vocab: int) -> int:
    """The scripted model: next token = FNV-ish hash of the recent
    history + position.  A pure function of (prompt, tokens so far), so
    preemption resume (swap OR recompute), cross-replica retry, and the
    single-engine reference all reproduce the identical chain."""
    h = 2166136261
    for t in list(seq)[-6:]:
        h = ((h ^ (int(t) + 1)) * 16777619) % (1 << 32)
    return (h + 7 * len(seq)) % vocab


class ScriptedEngine(_llm.LLMEngine):
    """The REAL LLMEngine scheduler with the model compute swapped for a
    deterministic numpy script — no weights, no jit, no device dispatch.

    Everything the fleet tier exercises is the genuine article: admission,
    chunked ragged scheduling, page allocation, preemption (swap and
    recompute, including mid-prefill victims), deadlines, cancellation,
    shutdown, the metrics registry, and every fault point.  Only the
    compute callables (_ragged/_ragged_fused/_swap_out/_swap_in/_sample)
    are replaced,
    which makes a step pure python — fast enough that tier-1 can afford
    whole-fleet chaos schedules.

    `reference_tokens()` is the token-exactness oracle: what a single
    healthy engine produces for a prompt, hence what the fleet must
    produce no matter which replicas died along the way."""

    DEFAULT_VOCAB = 97

    def __init__(self, num_slots: int = 2, page_size: int = 4,
                 max_seq_len: int = 16, vocab: int = DEFAULT_VOCAB, **kw):
        cfg = _ScriptedConfig(vocab)
        super().__init__(None, cfg, num_slots=num_slots,
                         page_size=page_size, max_seq_len=max_seq_len,
                         **kw)
        V = cfg.vocab_size

        def _fake_logits():
            # logits rows [out_start, out_start+out_len) belong to span i
            # of engine._batch_spans; only spans that SAMPLE (decode, a
            # chunk completing a fresh prefill, or every row of a verify
            # span) are consumed, and for those the scripted next token
            # is a pure function of the tokens cached up to that row —
            # exactly what the real kernel's per-row logits see
            logits = np.zeros((self._num_out, V), np.float32)
            for i, (slot, kind, n) in enumerate(self._batch_spans):
                st = self._slots.get(slot)
                if st is None:
                    continue
                o0, on = self._batch_out[i]
                if kind == "decode":
                    seqs = [[int(t) for t in st.req.prompt]
                            + list(st.req.tokens)]
                elif kind == "verify":
                    # row j scores the next token after draft[:j] landed
                    base = [int(t) for t in st.req.prompt] \
                        + list(st.req.tokens)
                    draft = self._batch_drafts[slot]
                    seqs = [base + [int(t) for t in draft[:j]]
                            for j in range(on)]
                else:
                    seqs = [[int(t) for t in st.pending[:st.ctx + n]]]
                for j, seq in enumerate(seqs):
                    logits[o0 + j, _script_next(seq, V)] = 1.0
            return logits

        def fake_ragged(params, tok, row_page, row_off, row_pos,
                        block_seq, block_qpos, span_len, ctx_len, span_pt,
                        out_rows, k_pool, v_pool):
            return _fake_logits(), k_pool, v_pool

        def fake_ragged_fused(params, tok, row_page, row_off, row_pos,
                              block_seq, block_qpos, span_len, ctx_len,
                              span_pt, out_rows, key, k_pool, v_pool):
            # the scripted model is deterministic (one-hot logits), so
            # device-side sampling degenerates to the same argmax the
            # scripted _sample performs — fused and unfused scripted
            # engines emit identical chains, like the real ones
            toks = np.argmax(_fake_logits(), axis=-1).astype(np.int32)
            return toks, k_pool, v_pool

        self._ragged = fake_ragged
        self._ragged_fused = fake_ragged_fused
        # keep scripted steps pure python: the fused route threads a key
        # per step and the scripted compute ignores it
        self._next_key = lambda: None
        self._swap_out = lambda k, v, idx: (np.zeros((1,), np.float32),
                                            np.zeros((1,), np.float32))
        self._swap_in = lambda k, v, idx, hk, hv: (k, v)
        # copy-on-write bookkeeping (refcounts, page swaps) is the real
        # allocator's; only the device page copy is scripted away
        self._cow = lambda k, v, src, dst: (k, v)
        self._sample = lambda logits: np.argmax(np.asarray(logits), axis=-1)

    @staticmethod
    def reference_tokens(prompt: Sequence[int], max_new_tokens: int,
                         eos_id: Optional[int] = None,
                         vocab: int = DEFAULT_VOCAB) -> List[int]:
        """What ONE healthy scripted engine generates for this request —
        the fleet chaos suite's token-exactness reference."""
        seq = [int(t) for t in prompt]
        out: List[int] = []
        for _ in range(int(max_new_tokens)):
            t = _script_next(seq, vocab)
            out.append(t)
            seq.append(t)
            if eos_id is not None and t == eos_id:
                break
        return out


# -- fleet tier: schedules, driving, invariants ----------------------------

def fleet_random_schedule(seed: int, n_replicas: int = 2,
                          max_rules: int = 3):
    """Deterministic pseudo-random FLEET schedule: per-replica engine
    rules (including crash=True replica deaths at step/prefill/decode)
    plus router-level rules (health flaps, stale stats, slow score
    reads).  Returns (engine_rules: {replica_id: [FaultRule]},
    router_rules: [FaultRule])."""
    rng = random.Random(seed ^ 0x5EED)
    engine_rules: Dict[int, List[FaultRule]] = \
        {i: [] for i in range(n_replicas)}
    router_rules: List[FaultRule] = []
    for _ in range(rng.randint(1, max_rules)):
        roll = rng.random()
        rid = rng.randrange(n_replicas)
        if roll < 0.35:
            # replica death mid-step / mid-prefill / mid-decode / mid-
            # transfer (the disaggregated handoff's stranded shape; the
            # point only fires on fleets running kv movement — a no-op
            # rule on mixed fleets, harmless)
            point = rng.choice(("step", "prefill", "decode",
                                "kv_transfer"))
            engine_rules[rid].append(
                FaultRule(point, nth=rng.randint(1, 6), crash=True))
        elif roll < 0.55:
            # plain single-replica faults (the PR-4 shapes) inside a fleet
            engine_rules[rid].extend(
                random_schedule(rng.randrange(1 << 30)))
        elif roll < 0.70:
            router_rules.append(FaultRule(
                "health_flap", replica=rid, nth=rng.randint(1, 4)))
        elif roll < 0.85:
            router_rules.append(FaultRule(
                "stats_staleness", replica=rid, nth=rng.randint(1, 5),
                always=rng.random() < 0.3))
        else:
            router_rules.append(FaultRule(
                "slow_replica", replica=rid, nth=rng.randint(1, 4),
                delay=0.01))
    return engine_rules, router_rules


def drive_fleet(router, handles: Sequence = (), max_steps: int = 20000,
                timeout: float = 120.0, settle: bool = True) -> int:
    """Drive a fleet until every fleet handle resolves (bounded), then —
    faults disabled — let the fleet SETTLE: outstanding canaries finish,
    flapped replicas reinstate, parked retries drain, every live engine
    quiesces.  Manual mode pumps the router; threaded mode waits.
    Returns pump steps taken (0 in threaded mode)."""
    steps = 0
    if getattr(router, "threaded", False):
        deadline = time.monotonic() + timeout
        for h in handles:
            h._event.wait(max(0.01, deadline - time.monotonic()))
    else:
        while any(not h.done() for h in handles) and steps < max_steps:
            router.pump()
            steps += 1
    if settle:
        saved, router.faults = router.faults, None
        try:
            deadline = time.monotonic() + min(timeout, 30.0)
            while time.monotonic() < deadline:
                if not getattr(router, "threaded", False):
                    router.pump()
                if router.quiesced():
                    break
                time.sleep(0.002)
        finally:
            router.faults = saved
    return steps


def fleet_check_invariants(router, handles: Sequence = (), reference=None,
                           probe: bool = True,
                           raise_on_violation: bool = True,
                           probe_timeout: float = 120.0) -> dict:
    """Assert the FLEET leaked nothing.  Call once quiesced (see
    `drive_fleet`).  Checks:

      * every submitted fleet handle resolved EXACTLY once fleet-wide —
        retries must never double-resolve, death must never strand;
      * token-exactness: every successfully resolved handle (including
        the retried ones, `len(h.hops) > 1`) matches `reference(h)` —
        what a single healthy engine would have produced;
      * per-replica zero leaks: `check_invariants` (pages/slots/pools/
        counter identity) on every live replica's engine;
      * fleet counter identity: accepted == completed + cancelled +
        timed_out + failed;
      * the fleet still serves: a fresh 1-token request through the
        ROUTER completes (faults disabled for the probe).

    `reference` is a callable handle -> expected token list (e.g. built
    on ScriptedEngine.reference_tokens).  Returns a report dict; raises
    InvariantViolation on any breach unless raise_on_violation=False."""
    violations: List[str] = []

    for i, h in enumerate(handles):
        if not h.done():
            violations.append(f"fleet handle {i} never resolved "
                              f"(hops={h.hops})")
        elif h.resolutions != 1:
            violations.append(f"fleet handle {i} resolved {h.resolutions} "
                              f"times (want 1; hops={h.hops})")
        elif h.error is None and not h.tokens:
            violations.append(f"fleet handle {i} resolved empty without "
                              "error")
    if reference is not None:
        for i, h in enumerate(handles):
            if h.done() and h.error is None and h.resolutions == 1:
                want = list(reference(h))
                if list(h.tokens) != want:
                    violations.append(
                        f"fleet handle {i} tokens diverge from the "
                        f"single-engine reference (hops={h.hops}): "
                        f"got {list(h.tokens)} want {want}")

    telemetry: Dict[int, List[str]] = {}
    for r in router.replicas:
        if r.dead:
            continue
        rep = check_invariants(r.engine, probe=False,
                               raise_on_violation=False)
        if not rep["ok"]:
            violations.append(f"replica {r.rid}: "
                              f"{'; '.join(rep['violations'])}")
        telemetry[r.rid] = rep["telemetry"]["mismatches"]

    snap = router.stats_snapshot()
    outcomes = (snap["completed"] + snap["cancelled"] + snap["timed_out"]
                + snap["failed"])
    if snap["accepted"] != outcomes:
        violations.append(
            f"fleet counter identity broken: accepted={snap['accepted']} "
            f"!= completed+cancelled+timed_out+failed={outcomes} (a "
            "request leaked out of, or was double-counted into, the "
            "fleet terminal counters)")

    probe_tokens = None
    if probe and not violations:
        # disable the ROUTER injector and every live replica's ENGINE
        # injector: the probe proves the fleet serves once the fault
        # storm stops, exactly like the single-engine checker
        saved_router, router.faults = router.faults, None
        saved_engines = [(r.engine, r.engine.faults)
                         for r in router.replicas if not r.dead]
        for eng, _ in saved_engines:
            eng.faults = None
        try:
            h = router.submit([1], max_new_tokens=1)
            if getattr(router, "threaded", False):
                probe_tokens = h.result(timeout=probe_timeout)
            else:
                drive_fleet(router, [h], settle=False)
                probe_tokens = h.result(timeout=0)
            if len(probe_tokens) != 1:
                violations.append(
                    f"fleet probe returned {probe_tokens!r}, want 1 token")
        except Exception as e:  # noqa: BLE001
            violations.append(
                f"fleet cannot serve a fresh request: {e!r}")
        finally:
            router.faults = saved_router
            for eng, inj in saved_engines:
                eng.faults = inj

    report = {
        "ok": not violations,
        "violations": violations,
        "probe_tokens": probe_tokens,
        "stats": snap,
        # per-live-replica gauge-vs-invariants cross-check (mismatches
        # are already violations; the soak CLIs surface this tally)
        "telemetry": {"ok": not any(telemetry.values()),
                      "replicas": telemetry},
        "replicas": {r.rid: {"state": r.state, "dead": r.dead,
                             "rebuilds": r.rebuilds}
                     for r in router.replicas},
    }
    if violations and raise_on_violation:
        raise InvariantViolation("; ".join(violations))
    return report


def fleet_run_schedule(make_engine: Callable[[], object],
                       engine_rules: Dict[int, Sequence[FaultRule]],
                       router_rules: Sequence[FaultRule],
                       requests: Sequence[Tuple[Sequence[int], int]],
                       n_replicas: int = 2, max_hops: int = 3,
                       probe: bool = True, threaded: bool = False,
                       reference=None, max_steps: int = 20000,
                       router_kw: Optional[dict] = None,
                       witness: bool = False) -> dict:
    """Build a fresh N-replica fleet (Router + EngineSupervisor over
    `make_engine`), install the per-replica and router-level schedules,
    submit the workload ((prompt, max_new) pairs, or triples whose third
    element is a kwargs dict for Router.submit — tenant/priority-tagged
    fleet schedules), drive to quiescence, and run the fleet invariant
    checker.  Rebuilt replicas come from the same factory,
    fault-free.  `witness=True` arms ONE shared LockWitness across the
    router lock and every replica's locks (rebuilds included, via a
    wrapped factory) — its edge graph must span components to see an
    engine-lock/router-lock cycle — and proves shutdown joined every
    thread the run started.  Returns the invariant report extended with
    schedule, fired faults, retry/death counts.  Raises
    InvariantViolation on any breach.  The router is shut down before
    returning."""
    from .router import (Router, FleetQueueFull, NoHealthyReplica,
                         RouterStopped)
    from .supervisor import EngineSupervisor

    before_threads = set(threading.enumerate())
    w = LockWitness() if witness else None
    factory = make_engine
    if w is not None:
        def factory():
            eng = make_engine()
            # attach=False: check_invariants runs per-replica inside
            # fleet_check_invariants, and folding the SHARED witness
            # there would repeat its violations once per replica — the
            # fleet layer reports them once, below
            arm_witness(eng, w, attach=False)
            return eng

    engines = []
    injectors = []
    for i in range(n_replicas):
        eng = factory()
        inj = FaultInjector(list(engine_rules.get(i, ())))
        inj.witness = w
        eng.faults = inj
        injectors.append(inj)
        engines.append(eng)
    router_injector = FaultInjector(list(router_rules))
    router_injector.witness = w
    kw = dict(max_hops=max_hops, backoff_base=0.01, backoff_max=0.25,
              health_interval=0.005)
    kw.update(router_kw or {})
    router = Router(engines, supervisor=EngineSupervisor(factory),
                    faults=router_injector, threaded=threaded, **kw)
    if w is not None:
        # safe mid-run swap: `with` holds the object it acquired, so a
        # health tick that grabbed the raw lock releases the raw lock
        w.wrap(router, "_lock", "Router._lock")
    handles, rejected = [], 0
    try:
        for item in requests:
            prompt, max_new = item[0], item[1]
            skw = item[2] if len(item) > 2 else {}
            try:
                handles.append(router.submit(prompt, max_new, **skw))
            except (FleetQueueFull, NoHealthyReplica, RouterStopped,
                    ValueError):
                rejected += 1   # resolved by refusal, never accepted
            if not threaded:
                router.pump()   # interleave placement with progress
        steps = drive_fleet(router, handles, max_steps=max_steps)
        report = fleet_check_invariants(router, handles,
                                        reference=reference, probe=probe)
    finally:
        router.shutdown(timeout=10.0)
    # post-shutdown proofs: shutdown() must have JOINED every thread the
    # run started (step threads, the health loop), and the shared
    # witness must have seen a clean lock discipline fleet-wide
    leaked = [t for t in threading.enumerate()
              if t not in before_threads and t.is_alive()]
    for t in leaked:
        t.join(timeout=2.0)
    leaked = [t for t in leaked if t.is_alive()]
    threads = {"leaked": [f"{t.name} (daemon={t.daemon})"
                          for t in leaked]}
    post_violations: List[str] = []
    if leaked:
        post_violations.append(
            "thread(s) leaked past router.shutdown(): "
            + ", ".join(threads["leaked"]))
    if w is not None:
        wrep = w.report()
        threads["witness"] = wrep
        post_violations.extend(f"lock witness: {v}"
                               for v in wrep["violations"])
    report["threads"] = threads
    if post_violations:
        report["ok"] = False
        report["violations"] = list(report["violations"]) + post_violations
        fl = getattr(router, "flight", None)
        if fl is not None:
            fl.dump("invariant_violation",
                    error=InvariantViolation("; ".join(post_violations)))
        raise InvariantViolation("; ".join(post_violations))
    fired = list(router_injector.fired)
    for i, inj in enumerate(injectors):
        fired.extend({**f, "replica": i} for f in inj.fired)
    report.update({
        "schedule": {
            "engines": {i: [r.to_dict() for r in engine_rules.get(i, ())]
                        for i in range(n_replicas)},
            "router": [r.to_dict() for r in router_rules],
        },
        "fired": fired,
        "requests": len(handles),
        "rejected": rejected,
        "completed": sum(1 for h in handles if h.error is None),
        "failed": sum(1 for h in handles if h.error is not None),
        "retried": sum(1 for h in handles if len(h.hops) > 1),
        "steps": steps,
    })
    return report
