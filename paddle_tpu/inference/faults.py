"""Fault injection + invariant checking for the preemptible LLMEngine.

A preemptible engine is only trustworthy if every failure path — dispatch
errors on donated pools, page-allocation OOM, deadlines, cancellation,
shutdown — provably leaks nothing.  Happy-path tests cannot show that;
this harness can:

  * the engine calls ``fire(point, ...)`` at NAMED injection points
    (`FAULT_POINTS`) wrapped around prefill dispatch, decode dispatch,
    page allocation, sampling, and the swap-out/swap-in paths;
  * a `FaultSchedule` is a list of deterministic `FaultRule`s — "fail the
    3rd decode dispatch", "OOM every page allocation for slot 2", "fail
    the 1st prefill AND consume the donated pools" (simulating a TPU
    dispatch that dies after donation);
  * `check_invariants` is asserted after every schedule: zero leaked
    pages/slots, live (non-donated-away) pools, every submitted handle
    resolved exactly once, and the engine still able to serve a fresh
    request.

`tests/test_engine_chaos.py` runs the shipped schedules plus seeded
random ones (`random_schedule`); `tools/chaos_llm.py` is the soak CLI.
"""

from __future__ import annotations

import collections
import random
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["FAULT_POINTS", "InjectedFault", "InvariantViolation",
           "FaultRule", "FaultInjector", "random_schedule", "drive",
           "check_invariants", "run_schedule"]

# the engine's named injection points, in rough lifecycle order
FAULT_POINTS = ("prefill", "decode", "page_alloc", "sample",
                "swap_out", "swap_in")

# points where a `consume_pools` rule is meaningful: the engine passes its
# (to-be-donated or read) pools in the fire() context there
_DISPATCH_POINTS = ("prefill", "decode", "swap_out", "swap_in")


class InjectedFault(RuntimeError):
    """Raised by the injector at a scheduled point.  A RuntimeError so the
    page-allocation path treats an injected OOM exactly like a real
    pool-exhausted condition."""


class InvariantViolation(AssertionError):
    """check_invariants found a leak or an unresolved/double-resolved
    handle."""


class FaultRule:
    """One deterministic fault: fire at the `nth` matching visit of
    `point` (1-based, counted per rule after the slot filter), or on
    EVERY matching visit (`always=True`, e.g. "OOM every allocation for
    slot 2").  `consume_pools=True` deletes the pool buffers before
    raising — simulating a TPU dispatch that fails AFTER consuming its
    donated arguments, which is the nastiest real-world failure the
    engine must recover from."""

    def __init__(self, point: str, nth: int = 1,
                 slot: Optional[int] = None, always: bool = False,
                 consume_pools: bool = False):
        if point not in FAULT_POINTS:
            raise ValueError(f"unknown fault point {point!r}; "
                             f"one of {FAULT_POINTS}")
        self.point = point
        self.nth = int(nth)
        self.slot = slot
        self.always = bool(always)
        self.consume_pools = bool(consume_pools)
        self.seen = 0     # matching visits
        self.fired = 0

    def matches(self, point: str, ctx: Dict) -> bool:
        if point != self.point:
            return False
        if self.slot is not None and ctx.get("slot") != self.slot:
            return False
        self.seen += 1
        if self.always:
            return True
        return self.fired == 0 and self.seen == self.nth

    def __repr__(self):
        bits = [self.point]
        if self.always:
            bits.append("always")
        else:
            bits.append(f"nth={self.nth}")
        if self.slot is not None:
            bits.append(f"slot={self.slot}")
        if self.consume_pools:
            bits.append("consume_pools")
        return f"FaultRule({', '.join(bits)})"

    def to_dict(self) -> dict:
        return {"point": self.point, "nth": self.nth, "slot": self.slot,
                "always": self.always, "consume_pools": self.consume_pools}


class FaultInjector:
    """Deterministic fault schedule.  Install via
    ``LLMEngine(..., faults=FaultInjector(rules))`` (or set
    ``engine.faults``); the engine calls `fire` at each injection point
    and a matching rule raises `InjectedFault` there."""

    def __init__(self, rules: Sequence[FaultRule] = ()):
        self.rules = list(rules)
        self.visits: collections.Counter = collections.Counter()
        self.fired: List[dict] = []

    def fire(self, point: str, engine=None, pools=None, **ctx) -> None:
        self.visits[point] += 1
        for rule in self.rules:
            if not rule.matches(point, ctx):
                continue
            rule.fired += 1
            self.fired.append({"point": point,
                               "visit": self.visits[point],
                               "rule": repr(rule),
                               "slot": ctx.get("slot")})
            if rule.consume_pools and pools is not None:
                for arr in pools.values():
                    try:
                        arr.delete()   # simulate donation consuming it
                    except Exception:  # noqa: BLE001 — already deleted etc.
                        pass
            raise InjectedFault(
                f"injected fault at {point!r} "
                f"(visit {self.visits[point]}, {rule!r})")


def random_schedule(seed: int, max_rules: int = 2) -> List[FaultRule]:
    """Deterministic pseudo-random schedule for soak runs: 1..max_rules
    rules over random points/visits, with a slice of always-OOM-per-slot
    and consume-donated-pools variants."""
    rng = random.Random(seed)
    rules = []
    for _ in range(rng.randint(1, max_rules)):
        point = rng.choice(FAULT_POINTS)
        if point == "page_alloc" and rng.random() < 0.35:
            rules.append(FaultRule(point, slot=rng.randrange(3),
                                   always=True))
            continue
        consume = point in _DISPATCH_POINTS and rng.random() < 0.3
        rules.append(FaultRule(point, nth=rng.randint(1, 8),
                               consume_pools=consume))
    return rules


def drive(engine, handles: Sequence = (), max_steps: int = 5000) -> int:
    """Step the engine until every handle resolves (bounded).  Returns the
    number of steps taken; a stall (no progress with unresolved handles)
    simply stops — check_invariants will report the unresolved handles."""
    steps = 0
    while any(not h.done() for h in handles) and steps < max_steps:
        try:
            progressed = engine.step()
        except Exception:  # noqa: BLE001 — step() handles its own faults;
            progressed = True          # a backstop escape still made work
        steps += 1
        if not progressed:
            break
    return steps


def check_invariants(engine, handles: Sequence = (), probe: bool = True,
                     raise_on_violation: bool = True,
                     probe_timeout: float = 120.0) -> dict:
    """Assert the engine leaked nothing.  Call once quiesced (all handles
    resolved — see `drive`).  Checks:

      * zero leaked slots: no in-flight slots, no pending requests, every
        decode slot back in the free list;
      * zero leaked pages: free pages + slot-held pages are EXACTLY pages
        1..num_pages-1, each once (page 0 reserved, never allocated);
      * pools live: the k/v buffers were not donated away and lost;
      * every submitted handle resolved exactly once;
      * metrics registry consistency: every accepted request landed in
        EXACTLY one terminal counter (accepted == completed + cancelled
        + timed_out + failed + still-queued + in-flight), and the
        stats_snapshot values match the registry counters /metrics
        renders (the two surfaces share storage and must not drift);
      * the engine still serves: a fresh 1-token request completes.

    Returns a report dict; raises InvariantViolation on any breach unless
    raise_on_violation=False."""
    cache = engine.cache
    violations: List[str] = []

    if engine._pending:
        violations.append(f"{len(engine._pending)} requests still pending")
    if engine._slots:
        violations.append(f"slots still in flight: {sorted(engine._slots)}")
    held = [p for pages in cache._slot_pages.values() for p in pages]
    if cache._slot_pages:
        violations.append(
            f"slot page lists not reclaimed: {dict(cache._slot_pages)}")
    pages = sorted(cache._free_pages + held)
    if pages != list(range(1, cache.num_pages)):
        violations.append(
            f"page accounting broken: free+held={pages} != "
            f"1..{cache.num_pages - 1} (leak or double-free)")
    slots = sorted(cache._free_slots + list(cache._slot_pages))
    if slots != list(range(cache.max_slots)):
        violations.append(
            f"slot accounting broken: free+held={slots} != "
            f"0..{cache.max_slots - 1}")
    for side in ("k", "v"):
        arr = cache.pools[side]
        if getattr(arr, "is_deleted", lambda: False)():
            violations.append(f"{side} pool was donated away and never "
                              "recovered")

    # metrics registry consistency.  Counters and registry values are
    # read in ONE pass under engine._cv (every counter write holds it),
    # so the snapshot cannot tear against a concurrent step thread.  The
    # strict terminal-counter identity is only decidable at quiescence —
    # mid-flight, a slot leaves engine._slots (lock-free, step-thread
    # owned) strictly before its terminal counter lands — so it is
    # asserted exactly when the leak checks above found the engine
    # quiesced, which is how every chaos schedule calls this.
    registry = getattr(engine, "metrics", None)
    with engine._cv:
        snap = dict(engine.stats)
        quiesced = not engine._pending and not engine._slots
        reg_vals = {}
        if registry is not None:
            for key in ("accepted", "admitted", "completed", "cancelled",
                        "timed_out", "failed", "preemptions"):
                counter = registry.get(f"llm_{key}_total")
                reg_vals[key] = (None if counter is None
                                 else int(counter.value))
    if "accepted" in snap and quiesced:
        outcomes = (snap["completed"] + snap["cancelled"]
                    + snap["timed_out"] + snap["failed"])
        if snap["accepted"] != outcomes:
            violations.append(
                f"metrics identity broken: accepted={snap['accepted']} != "
                f"completed+cancelled+timed_out+failed={outcomes} (a "
                "request leaked out of, or was double-counted into, the "
                "terminal counters)")
    if registry is not None:
        for key, val in reg_vals.items():
            if val is None:
                violations.append(f"registry missing counter "
                                  f"llm_{key}_total")
            elif key in snap and val != snap[key]:
                violations.append(
                    f"/stats and /metrics drifted: {key}={snap[key]} vs "
                    f"llm_{key}_total={val}")

    for i, h in enumerate(handles):
        if not h.done():
            violations.append(f"handle {i} never resolved")
        elif h.resolutions != 1:
            violations.append(
                f"handle {i} resolved {h.resolutions} times (want 1)")
        elif h.error is None and not h.tokens:
            violations.append(f"handle {i} resolved empty without error")

    probe_tokens = None
    if probe and not violations:
        saved, engine.faults = engine.faults, None
        try:
            h = engine.submit([1], max_new_tokens=1)
            if engine._thread is not None:
                probe_tokens = h.result(timeout=probe_timeout)
            else:
                drive(engine, [h])
                probe_tokens = h.result(timeout=0)
            if len(probe_tokens) != 1:
                violations.append(
                    f"fresh probe returned {probe_tokens!r}, want 1 token")
        except Exception as e:  # noqa: BLE001
            violations.append(f"engine cannot serve a fresh request: {e!r}")
        finally:
            engine.faults = saved

    report = {
        "ok": not violations,
        "violations": violations,
        "free_pages": cache.free_page_count,
        "free_slots": cache.free_slot_count,
        "num_pages": cache.num_pages,
        "probe_tokens": probe_tokens,
        "stats": engine.stats_snapshot(),
    }
    if violations and raise_on_violation:
        raise InvariantViolation("; ".join(violations))
    return report


def run_schedule(make_engine: Callable[[], object],
                 rules: Sequence[FaultRule],
                 requests: Sequence[Tuple[Sequence[int], int]],
                 probe: bool = True, max_steps: int = 5000) -> dict:
    """Build a fresh engine, install the schedule, submit the workload
    ((prompt, max_new_tokens) pairs), drive to quiescence, and run the
    invariant checker.  Returns the invariant report extended with the
    schedule, the faults actually fired, and the final counters.  Raises
    InvariantViolation on any leak."""
    injector = FaultInjector(rules)
    engine = make_engine()
    engine.faults = injector
    handles = []
    rejected = 0
    for prompt, max_new in requests:
        try:
            handles.append(engine.submit(prompt, max_new))
        except (ValueError, RuntimeError):
            rejected += 1      # QueueFull / validation — resolved by refusal
    steps = drive(engine, handles, max_steps=max_steps)
    report = check_invariants(engine, handles, probe=probe)
    report.update({
        "schedule": [r.to_dict() for r in rules],
        "fired": list(injector.fired),
        "requests": len(handles),
        "rejected": rejected,
        "completed": sum(1 for h in handles if h.error is None),
        "failed": sum(1 for h in handles if h.error is not None),
        "steps": steps,
    })
    return report
