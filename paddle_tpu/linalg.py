"""paddle.linalg namespace (re-exports; python/paddle/tensor/linalg.py parity)."""

from .ops.linalg import *  # noqa: F401,F403
from .ops.linalg import __all__  # noqa: F401
