"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capabilities, built on JAX/XLA/Pallas/pjit.

Public surface mirrors `python/paddle/__init__.py:487` of the reference (~356
symbols): tensor ops, nn, optimizer, amp, autograd, io, jit, static, distributed,
device, profiler, vision/audio/text, incubate.  Architecture is TPU-first (see
SURVEY.md §7): XLA is the compiler/executor, GSPMD mesh-sharding is the
distributed backend, Pallas kernels are the fused-op library.
"""

from __future__ import annotations

import jax as _jax

# float64/int64 are first-class dtypes in the reference; enable x64 so dtype
# semantics match (default dtype stays float32 — see framework.get_default_dtype).
_jax.config.update("jax_enable_x64", True)

from . import framework  # noqa: E402
from .framework import (  # noqa: E402
    bfloat16, bool_, complex64, complex128, float16, float32, float64,
    get_default_dtype, get_device, get_flags, int8, int16, int32, int64,
    seed, set_default_dtype, set_device, set_flags, uint8,
)
from .tensor import Tensor, to_tensor, is_tensor  # noqa: E402
from .tensor import Parameter as _Parameter  # noqa: E402
from . import ops  # noqa: E402
from .ops import *  # noqa: E402,F401,F403
from . import autograd  # noqa: E402
from .autograd import grad, no_grad, enable_grad, set_grad_enabled, is_grad_enabled  # noqa: E402
from .autograd import backward as _backward  # noqa: E402

# subpackage namespaces (populated in later import stages of the build)
from . import nn  # noqa: E402
from . import optimizer  # noqa: E402
from . import io  # noqa: E402
from . import amp  # noqa: E402
from . import jit  # noqa: E402
from . import device  # noqa: E402
from . import linalg  # noqa: E402
from .serialization import save, load  # noqa: E402
from . import metric  # noqa: E402
from . import incubate  # noqa: E402
from . import vision  # noqa: E402
from . import hub  # noqa: E402
from .nn.layer import ParamAttr  # noqa: E402
# dtype objects are strings in this build; paddle.dtype/paddle.bool parity
dtype = str
bool = "bool"  # noqa: A001 — paddle exports `paddle.bool`
from . import hapi  # noqa: E402
from . import distribution  # noqa: E402
from . import sparse  # noqa: E402
from . import geometric  # noqa: E402
from . import quantization  # noqa: E402
from . import audio  # noqa: E402
from . import text  # noqa: E402
from . import fft  # noqa: E402
from . import signal  # noqa: E402
from . import utils  # noqa: E402
from . import static  # noqa: E402
from . import profiler  # noqa: E402
from . import inference  # noqa: E402
from . import analysis  # noqa: E402  (Graph Doctor: jaxpr lint framework)
from . import obs  # noqa: E402  (runtime telemetry: spans/metrics/MFU)
from .framework_tensors import SelectedRows, StringTensor  # noqa: E402
from .hapi import Model  # noqa: E402
from .hapi.summary import summary  # noqa: E402

CPUPlace = lambda: "cpu"  # noqa: E731 — place objects are strings on TPU build
TPUPlace = lambda idx=0: f"tpu:{idx}"  # noqa: E731
CUDAPlace = lambda idx=0: f"gpu:{idx}"  # noqa: E731

__version__ = "0.1.0"


def disable_static(place=None):
    return None


def enable_static():
    """Static-graph mode: build programs under static.program_guard.  The
    eager API keeps working (capture rides on op dispatch), so this toggles
    nothing globally — kept for source compatibility."""
    return None


def in_dynamic_mode():
    return True


def device_count():
    return framework.device_count()
