"""Legacy static-graph surface (reference python/paddle/static/__init__.py
remainders).  The record-replay Program stands in for ProgramDesc; these
shims keep the reference's training-infra idioms (EMA, append_backward,
py_func, persistable serialization) working on the eager/tape core.
"""

from __future__ import annotations

import contextlib
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..tensor import Parameter, Tensor, apply_op, to_tensor

__all__ = [
    "Variable", "BuildStrategy", "ExecutionStrategy", "CompiledProgram",
    "IpuStrategy", "IpuCompiledProgram", "ipu_shard_guard", "set_ipu_shard",
    "ExponentialMovingAverage", "Print", "WeightNormParamAttr",
    "accuracy", "auc", "append_backward", "gradients",
    "create_global_var", "create_parameter", "ctr_metric_bundle",
    "device_guard", "py_func", "normalize_program",
    "save_to_file", "load_from_file",
    "serialize_persistables", "deserialize_persistables",
    "save_persistables", "load_persistables",
    "load_program_state", "set_program_state",
]

Variable = Tensor  # the reference's static Variable == this build's Tensor


class _AttrBag:
    def __init__(self, **kw):
        self.__dict__.update(kw)

    def __setattr__(self, k, v):
        self.__dict__[k] = v


class BuildStrategy(_AttrBag):
    """Graph-build knobs (reference BuildStrategy).  XLA owns fusion and
    memory planning, so the attributes are accepted and recorded only."""


class ExecutionStrategy(_AttrBag):
    """Executor knobs (reference ExecutionStrategy); same recording shim."""


class CompiledProgram:
    """Reference CompiledProgram(program, build_strategy): under XLA the
    Executor compiles every program, so this is a thin marker wrapper."""

    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy

    def __getattr__(self, name):
        return getattr(self.__dict__["program"], name)


class IpuStrategy(_AttrBag):
    """Accepted for API parity; no IPU backend exists here."""


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise RuntimeError(
            "IPU support is not available in this build (no IPU PJRT "
            "plugin); use the default Executor")


@contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    raise RuntimeError(
        "IPU support is not available in this build; for pipeline sharding "
        "use distributed.pipeline")
    yield  # pragma: no cover


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise RuntimeError(
        "IPU support is not available in this build; for pipeline sharding "
        "use distributed.pipeline")


class ExponentialMovingAverage:
    """EMA over trainable parameters (reference static/ema.py):
    update() after each step; apply() swaps EMA weights in (context
    manager), restore() swaps back."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._thres_steps = thres_steps
        self._ema = {}
        self._backup = None
        self._step = 0

    def update(self, parameters=None):
        params = parameters if parameters is not None else \
            self._default_params()
        self._step += 1
        # constant decay by default; the warmup ramp only with thres_steps
        # (reference static/ema.py semantics)
        d = self._decay if self._thres_steps is None else \
            min(self._decay, (1 + self._step) / (10 + self._step))
        for p in params:
            k = id(p)
            v = np.asarray(p._data, np.float32)
            if k not in self._ema:
                self._ema[k] = (p, v.copy())
            else:
                _, old = self._ema[k]
                self._ema[k] = (p, d * old + (1 - d) * v)

    @staticmethod
    def _default_params():
        prog = framework.get_state().capture_program
        if prog is not None:
            return prog.all_parameters()
        raise ValueError("EMA.update needs parameters= in eager mode")

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        self._backup = {k: np.asarray(p._data) for k, (p, _)
                        in self._ema.items()}
        for k, (p, v) in self._ema.items():
            p._data = jnp.asarray(v).astype(p._data.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._backup is None:
            return
        for k, (p, _) in self._ema.items():
            p._data = jnp.asarray(self._backup[k])
        self._backup = None


def Print(input, first_n=-1, message=None, summarize=20,  # noqa: N802
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug print op (reference static/nn/common.py Print): prints and
    passes the value through (works eagerly and under capture)."""
    x = input if isinstance(input, Tensor) else to_tensor(input)
    head = message or ""

    def f(a):
        jax.debug.print(head + " {v}", v=a)
        return a
    return apply_op("print", f, x)


class WeightNormParamAttr:
    """Reference WeightNormParamAttr(dim=...).  Weight-norm
    reparameterization is a training-dynamics choice; this build records
    the attr and initializes like ParamAttr (use nn.utils.spectral_norm /
    explicit reparam for normalized training)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        from ..nn.layer import ParamAttr
        self.dim = dim
        self._attr = ParamAttr(name=name, initializer=initializer,
                               learning_rate=learning_rate,
                               regularizer=regularizer, trainable=trainable,
                               need_clip=need_clip)

    def __getattr__(self, k):
        return getattr(self.__dict__["_attr"], k)


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Top-k accuracy (reference static/nn/metric.py accuracy)."""
    x = input if isinstance(input, Tensor) else to_tensor(input)
    y = label if isinstance(label, Tensor) else to_tensor(label)

    def f(xr, yr):
        topk = jnp.argsort(-xr, axis=-1)[..., :k]
        hit = (topk == yr.reshape(-1, 1)).any(-1)
        return hit.mean(dtype=jnp.float32)
    return apply_op("accuracy", f, x, y, nondiff=(0, 1))


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """ROC AUC over prob-of-positive (reference static/nn/metric.py auc).
    Returns (auc_value, batch_auc, [stat tensors]) like the reference."""
    x = input if isinstance(input, Tensor) else to_tensor(input)
    y = label if isinstance(label, Tensor) else to_tensor(label)
    probs = np.asarray(x._data)
    pos = probs[:, 1] if probs.ndim == 2 else probs.reshape(-1)
    lab = np.asarray(y._data).reshape(-1)
    order = np.argsort(pos)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(pos) + 1)
    n_pos = (lab == 1).sum()
    n_neg = (lab == 0).sum()
    if n_pos == 0 or n_neg == 0:
        val = 0.5
    else:
        val = (ranks[lab == 1].sum() - n_pos * (n_pos + 1) / 2) \
            / (n_pos * n_neg)
    out = to_tensor(np.float32(val))
    return out, out, []


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """CTR metric set (reference static/nn/metric.py ctr_metric_bundle):
    (auc, batch_auc, stats) + squared error / abs error sums."""
    a, b, stats = auc(input, label)
    x = np.asarray((input._data if isinstance(input, Tensor)
                    else jnp.asarray(input)))
    pos = x[:, 1] if x.ndim == 2 else x.reshape(-1)
    lab = np.asarray((label._data if isinstance(label, Tensor)
                      else jnp.asarray(label))).reshape(-1)
    sqrerr = to_tensor(np.float32(((pos - lab) ** 2).sum()))
    abserr = to_tensor(np.float32(np.abs(pos - lab).sum()))
    prob = to_tensor(np.float32(pos.sum()))
    q = to_tensor(np.float32(pos.sum()))
    pos_cnt = to_tensor(np.float32((lab == 1).sum()))
    total = to_tensor(np.float32(len(lab)))
    return a, sqrerr, abserr, prob, q, pos_cnt, total


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Reference static append_backward: wires grad ops into the program.
    On the tape core this IS loss.backward(); returns [(param, grad)]."""
    loss.backward(retain_graph=True)
    params = parameter_list
    if params is None:
        prog = framework.get_state().capture_program
        params = prog.all_parameters() if prog is not None else []
    out = []
    for p in params:
        if isinstance(p, Parameter) and p.grad is not None:
            out.append((p, p.grad))
    return out


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference static.gradients -> autograd.grad on the tape."""
    from ..autograd import grad as _grad
    tgts = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    gg = target_gradients
    return _grad(tgts, ins, grad_outputs=gg, allow_unused=True,
                 retain_graph=True)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..framework import convert_dtype, to_jax_dtype
    t = Tensor(jnp.full(tuple(int(s) for s in shape), value,
                        to_jax_dtype(convert_dtype(dtype))),
               stop_gradient=True, name=name)
    t.persistable = persistable
    return t


from ..ops.compat import create_parameter  # noqa: E402,F401


@contextlib.contextmanager
def device_guard(device=None):
    """Reference static.device_guard: op placement hint.  XLA places the
    whole program; accepted and ignored."""
    yield


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Wrap a host python function as an op (reference static/nn/common.py
    py_func).  Eager-only; backward_func(*inputs, *outputs, *out_grads) ->
    input grads supplies the custom gradient (recorded as a tape node
    directly — the host function cannot be traced for a JAX vjp)."""
    from ..tensor import TapeNode
    xs = x if isinstance(x, (list, tuple)) else [x]
    xs = [i if isinstance(i, Tensor) else to_tensor(i) for i in xs]
    if any(isinstance(i._data, jax.core.Tracer) for i in xs):
        raise RuntimeError("py_func runs host python; call it eagerly "
                           "(outside jit/to_static)")
    res = func(*xs)
    rs = res if isinstance(res, (list, tuple)) else [res]
    outs = [r if isinstance(r, Tensor) else to_tensor(r) for r in rs]
    diff_in = [i for i in xs if not i.stop_gradient]
    if backward_func is not None and framework.is_grad_enabled() \
            and diff_in:
        def pullback(cts):
            cts = cts if isinstance(cts, (tuple, list)) else (cts,)
            grads = backward_func(
                *xs, *outs, *[to_tensor(np.asarray(c)) for c in cts])
            gs = grads if isinstance(grads, (tuple, list)) else (grads,)
            return tuple(
                g._data if isinstance(g, Tensor) else jnp.asarray(g)
                for g in gs)
        node = TapeNode("py_func", pullback, tuple(diff_in), tuple(outs))
        for idx, o in enumerate(outs):
            o.stop_gradient = False
            o._node = node
            o._out_idx = idx
    return outs if isinstance(res, (list, tuple)) else outs[0]


def normalize_program(program, feeds, fetches):
    """Prune to the feed->fetch slice (reference normalize_program); the
    recorded Program replays lazily so the program itself is returned."""
    return program


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def _param_state(program):
    return {f"param_{i}": np.asarray(p._data)
            for i, p in enumerate(program.all_parameters())}


def serialize_persistables(feed_vars, fetch_vars, executor=None,
                           program=None, **kwargs):
    """Pickle the program's persistable parameters (reference
    serialize_persistables -> bytes)."""
    from . import default_main_program
    prog = program or default_main_program()
    return pickle.dumps(_param_state(prog))


def deserialize_persistables(program, data, executor=None):
    state = pickle.loads(data)
    set_program_state(program, state)
    return program


def save_persistables(executor, dirname, main_program=None, filename=None):
    import os
    from . import default_main_program
    prog = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, filename or "persistables.pkl")
    with open(path, "wb") as f:
        f.write(serialize_persistables(None, None, program=prog))


def load_persistables(executor, dirname, main_program=None, filename=None):
    import os
    from . import default_main_program
    prog = main_program or default_main_program()
    path = os.path.join(dirname, filename or "persistables.pkl")
    deserialize_persistables(prog, load_from_file(path))


def load_program_state(model_path, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    params = program.all_parameters()
    for i, p in enumerate(params):
        for key in (f"param_{i}", i):
            if key in state_dict:
                p._data = jnp.asarray(state_dict[key]).astype(p._data.dtype)
                break
