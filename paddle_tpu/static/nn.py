"""paddle.static.nn — data-dependent control flow for compiled programs.

Reference: the dy2static AST transformer pipeline
(`python/paddle/jit/dy2static/program_translator.py:313` and the
`*_transformer.py` passes) rewrites python `if`/`while` over tensor values
into `cond`/`while_loop` ops.  Trace-based `to_static` cannot rewrite the
AST; instead the same ops are exposed DIRECTLY, lax-backed:

    paddle.static.nn.cond(pred, true_fn, false_fn)     -> lax.cond
    paddle.static.nn.while_loop(cond_fn, body_fn, vars) -> lax.while_loop
    paddle.static.nn.case / switch_case                 -> lax.switch

and a python `if tensor:` inside a traced function raises an actionable
error pointing here (tensor.Tensor.__bool__).  Everything works eagerly
too (the ops simply execute the taken branch), so code is portable between
dygraph and to_static — the same contract the reference's
paddle.static.nn.cond (python/paddle/static/nn/control_flow.py:934) gives.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp

from ..tensor import Tensor, to_tensor

__all__ = ["cond", "while_loop", "case", "switch_case"]


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _wrap_tree(raws):
    return jax.tree_util.tree_map(
        lambda r: Tensor(r, stop_gradient=True)
        if isinstance(r, (jax.Array, jax.core.Tracer)) else r, raws)


def _unwrap_tree(vals):
    return jax.tree_util.tree_map(
        lambda v: v._data if isinstance(v, Tensor) else v, vals,
        is_leaf=lambda v: isinstance(v, Tensor))


def cond(pred, true_fn: Callable, false_fn: Callable, name=None,
         return_names=None):
    """Run `true_fn()` or `false_fn()` depending on scalar boolean `pred`
    (reference python/paddle/static/nn/control_flow.py:934).  Both branches
    must return the same structure/shapes/dtypes (checked by lax.cond)."""
    p = _unwrap(pred)
    p = jnp.asarray(p)
    if p.size != 1:
        raise ValueError(
            f"cond() pred must be a scalar boolean, got shape {p.shape}")
    p = p.reshape(()).astype(jnp.bool_)

    def tb(_):
        return _unwrap_tree(true_fn())

    def fb(_):
        return _unwrap_tree(false_fn())

    out = jax.lax.cond(p, tb, fb, operand=None)
    return _wrap_tree(out)


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence,
               is_test: bool = False, name=None):
    """lax-backed while loop (reference control_flow.py:1330).  `cond_fn` and
    `body_fn` take the loop vars positionally; shapes/dtypes must be loop
    invariant (XLA's compiled-loop contract — the same restriction the
    reference's static while_loop has on its block vars)."""
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise TypeError("while_loop loop_vars must be a non-empty list")
    init = tuple(_unwrap_tree(v) for v in loop_vars)

    def c(vs):
        out = cond_fn(*_wrap_tree(vs))
        return jnp.asarray(_unwrap(out)).reshape(()).astype(jnp.bool_)

    def b(vs):
        out = body_fn(*_wrap_tree(vs))
        if not isinstance(out, (list, tuple)):
            out = (out,)
        raws = tuple(_unwrap_tree(v) for v in out)
        # keep each carry's dtype loop-invariant: python-scalar promotion
        # (x64 ints) must not silently retype the loop vars
        return tuple(
            jnp.asarray(r).astype(i.dtype)
            if hasattr(i, "dtype") and jnp.asarray(r).dtype != i.dtype else r
            for r, i in zip(raws, init))

    final = jax.lax.while_loop(c, b, init)
    return list(_wrap_tree(final))


def case(pred_fn_pairs, default=None, name=None):
    """First-match-wins dispatch (reference control_flow.py:1580): pairs of
    (scalar bool Tensor, fn).  Lowered to nested lax.cond."""
    if not pred_fn_pairs:
        raise ValueError("case() needs at least one (pred, fn) pair")
    for pr, fn in pred_fn_pairs:
        if not callable(fn):
            raise TypeError("case() fns must be callable")

    def build(pairs):
        if not pairs:
            if default is None:
                # reference behavior: last fn is the fallback
                return lambda: pred_fn_pairs[-1][1]()
            return default
        (pr, fn), rest = pairs[0], pairs[1:]
        return lambda: cond(pr, fn, build(rest))

    return build(list(pred_fn_pairs))()


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Indexed dispatch -> lax.switch (reference control_flow.py:1718).
    `branch_fns`: dict {int: fn} or list of (int, fn) or list of fns."""
    idx = jnp.asarray(_unwrap(branch_index)).reshape(()).astype(jnp.int32)
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        items = sorted((int(k), f) for k, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    keys = [k for k, _ in items]
    fns = [f for _, f in items]
    if default is None:
        default = fns[-1]

    # map arbitrary integer keys (negative included) onto dense switch
    # indices via an offset table; unknown keys -> default
    lo, hi = min(keys), max(keys)
    table = {k: i for i, (k, _) in enumerate(items)}
    branches = [lambda _, f=f: _unwrap_tree(f()) for f in fns]
    branches.append(lambda _: _unwrap_tree(default()))
    dense = jnp.full((hi - lo + 1,), len(fns), jnp.int32)
    for k, i in table.items():
        dense = dense.at[k - lo].set(i)
    safe = jnp.clip(idx - lo, 0, hi - lo)
    sel = jnp.where((idx >= lo) & (idx <= hi), dense[safe], len(fns))
    out = jax.lax.switch(sel, branches, None)
    return _wrap_tree(out)
