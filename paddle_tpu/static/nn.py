"""paddle.static.nn — data-dependent control flow for compiled programs.

Reference: the dy2static AST transformer pipeline
(`python/paddle/jit/dy2static/program_translator.py:313` and the
`*_transformer.py` passes) rewrites python `if`/`while` over tensor values
into `cond`/`while_loop` ops.  Trace-based `to_static` cannot rewrite the
AST; instead the same ops are exposed DIRECTLY, lax-backed:

    paddle.static.nn.cond(pred, true_fn, false_fn)     -> lax.cond
    paddle.static.nn.while_loop(cond_fn, body_fn, vars) -> lax.while_loop
    paddle.static.nn.case / switch_case                 -> lax.switch

and a python `if tensor:` inside a traced function raises an actionable
error pointing here (tensor.Tensor.__bool__).  Everything works eagerly
too (the ops simply execute the taken branch), so code is portable between
dygraph and to_static — the same contract the reference's
paddle.static.nn.cond (python/paddle/static/nn/control_flow.py:934) gives.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp

from ..tensor import Tensor, to_tensor

__all__ = ["cond", "while_loop", "case", "switch_case"]


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _wrap_tree(raws):
    return jax.tree_util.tree_map(
        lambda r: Tensor(r, stop_gradient=True)
        if isinstance(r, (jax.Array, jax.core.Tracer)) else r, raws)


def _unwrap_tree(vals):
    return jax.tree_util.tree_map(
        lambda v: v._data if isinstance(v, Tensor) else v, vals,
        is_leaf=lambda v: isinstance(v, Tensor))


def cond(pred, true_fn: Callable, false_fn: Callable, name=None,
         return_names=None):
    """Run `true_fn()` or `false_fn()` depending on scalar boolean `pred`
    (reference python/paddle/static/nn/control_flow.py:934).

    Eager (concrete pred): the taken branch executes DIRECTLY, so eager
    autograd flows through its ops unchanged — matching the reference's
    dygraph cond.  Traced (to_static/jit): lowers to lax.cond; both
    branches must return matching structures/shapes/dtypes."""
    p = _unwrap(pred)
    p = jnp.asarray(p)
    if p.size != 1:
        raise ValueError(
            f"cond() pred must be a scalar boolean, got shape {p.shape}")
    p = p.reshape(()).astype(jnp.bool_)
    if not isinstance(p, jax.core.Tracer):
        return true_fn() if bool(p) else false_fn()

    def tb(_):
        return _unwrap_tree(true_fn())

    def fb(_):
        return _unwrap_tree(false_fn())

    out = jax.lax.cond(p, tb, fb, operand=None)
    return _wrap_tree(out)


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence,
               is_test: bool = False, name=None):
    """lax-backed while loop (reference control_flow.py:1330).  `cond_fn` and
    `body_fn` take the loop vars positionally; shapes/dtypes must be loop
    invariant (XLA's compiled-loop contract — the same restriction the
    reference's static while_loop has on its block vars)."""
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise TypeError("while_loop loop_vars must be a non-empty list")
    init = tuple(_unwrap_tree(v) for v in loop_vars)
    # eager (all concrete): run a python loop over the user fns directly so
    # the eager tape records every body op (reference dygraph while_loop)
    if not any(isinstance(r, jax.core.Tracer)
               for r in jax.tree_util.tree_leaves(init)):
        vars_now = list(loop_vars)
        while bool(jnp.asarray(_unwrap(cond_fn(*vars_now))).reshape(())):
            out = body_fn(*vars_now)
            vars_now = list(out) if isinstance(out, (list, tuple)) else [out]
        return vars_now

    def c(vs):
        out = cond_fn(*_wrap_tree(vs))
        return jnp.asarray(_unwrap(out)).reshape(()).astype(jnp.bool_)

    def b(vs):
        out = body_fn(*_wrap_tree(vs))
        if not isinstance(out, (list, tuple)):
            out = (out,)
        raws = tuple(_unwrap_tree(v) for v in out)
        # keep each carry's dtype loop-invariant: python-scalar promotion
        # (x64 ints) must not silently retype the loop vars
        return tuple(
            jnp.asarray(r).astype(i.dtype)
            if hasattr(i, "dtype") and jnp.asarray(r).dtype != i.dtype else r
            for r, i in zip(raws, init))

    final = jax.lax.while_loop(c, b, init)
    return list(_wrap_tree(final))


def case(pred_fn_pairs, default=None, name=None):
    """First-match-wins dispatch (reference control_flow.py:1580): pairs of
    (scalar bool Tensor, fn).  Lowered to nested lax.cond."""
    if not pred_fn_pairs:
        raise ValueError("case() needs at least one (pred, fn) pair")
    for pr, fn in pred_fn_pairs:
        if not callable(fn):
            raise TypeError("case() fns must be callable")

    def build(pairs):
        if not pairs:
            if default is None:
                # reference behavior: last fn is the fallback
                return lambda: pred_fn_pairs[-1][1]()
            return default
        (pr, fn), rest = pairs[0], pairs[1:]
        return lambda: cond(pr, fn, build(rest))

    return build(list(pred_fn_pairs))()


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Indexed dispatch -> lax.switch (reference control_flow.py:1718).
    `branch_fns`: dict {int: fn} or list of (int, fn) or list of fns."""
    idx = jnp.asarray(_unwrap(branch_index)).reshape(()).astype(jnp.int32)
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        items = sorted((int(k), f) for k, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    fns = [f for _, f in items]
    if default is None:
        default = fns[-1]
    if not isinstance(idx, jax.core.Tracer):
        # eager: dispatch directly (tape flows through the taken branch)
        fn = dict(items).get(int(idx), default)
        return fn()

    # traced: O(len(keys)) scalar compare chain selects the dense branch
    # index (arbitrary — negative, sparse — integer keys; no O(range)
    # lookup table)
    branches = [lambda _, f=f: _unwrap_tree(f()) for f in fns]
    branches.append(lambda _: _unwrap_tree(default()))
    sel = jnp.full((), len(fns), jnp.int32)
    for i, (k, _) in enumerate(items):
        sel = jnp.where(idx == k, jnp.int32(i), sel)
    out = jax.lax.switch(sel, branches, None)
    return _wrap_tree(out)
