"""Graph passes over record-replay Programs (SURVEY C14 depth).

Reference analog: the IR pass pipeline (`paddle/fluid/framework/ir/*_pass.cc`,
applied via build_strategy / `paddle.static.apply_build_strategy`) — ~274
passes doing fusion/DCE/folding on ProgramDesc graphs.  Under XLA the heavy
rewriting (fusion, layout, CSE) happens in the compiler, so the pass story
shrinks to what still pays off at the RECORD level:

  * dead_code_elimination — ops whose outputs never reach a fetch target
    are dropped (fewer records to trace, and a cloned-for-test program
    sheds its training-only tail);
  * constant_folding — ops with no transitive placeholder/parameter
    dependency are dropped outright: their captured output values (the
    eager values observed at record time) ARE the constants, and replay's
    environment falls back to them automatically;
  * fuse_elementwise — chains of single-consumer records merge into one
    record (one python dispatch + one closure at trace time instead of N;
    XLA would fuse the math anyway — this trims record/trace overhead).

Passes are registered by name (`register_pass`) and applied with
`apply_pass(program, names, fetch_list=...)` or
`Program.apply_pass(...)`; they return a TRANSFORMED CLONE (the input
program is untouched), mirroring the reference's pass immutability.

The ANALYSIS half of the reference pipeline (diagnose, don't rewrite)
lives in `paddle_tpu.analysis` (the Graph Doctor) over jaxprs — same
registry shape (`register_checker`/`list_checkers`/`analyze`), structured
`Finding`s instead of transforms; `Program.lint()` runs those checkers
over a recorded program's replay function.

Since Graph Doctor grew its own REWRITE tier (`analysis/rewrite.py`),
the jaxpr-level halves of `dead_code_elimination` and `fuse_elementwise`
delegate there: `jaxpr_rewrite(program, ...)` (= `Program.rewrite()`)
runs verified DCE/fusion/dtype/donation passes over the program's replay
jaxpr — the level that actually compiles.  The record passes above
remain useful for trimming what gets TRACED; the jaxpr engine transforms
what got traced, with an equivalence gate the record level never had.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["register_pass", "apply_pass", "list_passes", "jaxpr_rewrite"]

PASS_REGISTRY: Dict[str, Callable] = {}


def register_pass(name: str):
    """Register a pass.  The registered callable CLONES its input, applies
    the transform, records removed output ids (so a later fetch of a
    removed tensor errors instead of returning a stale sample value), and
    clears the clone's compile cache — direct calls are as safe as
    apply_pass."""
    def deco(fn):
        def wrapped(program, fetch_list=None):
            out = program.clone()
            before = {id(o) for op in out.ops for o in op.outs}
            res = fn(out, fetch_list=fetch_list) or out
            after = {id(o) for op in res.ops for o in op.outs}
            res._removed_outputs = (
                getattr(program, "_removed_outputs", set())
                | (before - after))
            res._cache.clear()
            return res
        wrapped.__name__ = fn.__name__
        wrapped.__doc__ = fn.__doc__
        PASS_REGISTRY[name] = wrapped
        return wrapped
    return deco


def list_passes() -> List[str]:
    return sorted(PASS_REGISTRY)


def apply_pass(program, names, fetch_list: Optional[Sequence] = None):
    """Apply one pass (or a list, in order); returns a transformed clone."""
    if isinstance(names, str):
        names = [names]
    out = program
    for n in names:
        if n not in PASS_REGISTRY:
            raise ValueError(
                f"unknown pass {n!r}; available: {list_passes()}")
        out = PASS_REGISTRY[n](out, fetch_list=fetch_list)
    return out


def _target_ids(program, fetch_list):
    """Ids of tensors that must stay computable.  String entries resolve
    by tensor name (the same names Executor.run accepts); an unresolvable
    name raises rather than silently making EVERY op dead."""
    ids = set()
    if fetch_list:
        by_name = {getattr(t, "name", None): t for t in program.list_vars()}
        for f in fetch_list:
            if isinstance(f, str):
                t = by_name.get(f)
                if t is None:
                    raise ValueError(
                        f"fetch target {f!r} not found in program")
                ids.add(id(t))
            else:
                ids.add(id(f))
    if program._train is not None:
        ids.add(id(program._train[1]))           # the loss
    if not ids and program.ops:
        ids |= {id(o) for o in program.ops[-1].outs}
    return ids


def jaxpr_rewrite(program, feed=None, fetch_list=None, passes=None, **kw):
    """Delegate to the jaxpr rewrite engine: run the VERIFIED Graph
    Doctor passes (dce/dtype_cast/fusion/donation by default) over the
    program's replay jaxpr.  Unlike the record passes this returns a
    `(rewritten_fn, RewriteReport)` pair, not a Program — the jaxpr is
    the compiled artifact, records are its recipe.  Equivalent to
    `program.rewrite(...)`; registered here so pass-pipeline callers
    find the bridge next to the record-level DCE/fusion it supersedes."""
    return program.rewrite(feed=feed, fetch_list=fetch_list,
                           passes=passes, **kw)


@register_pass("dead_code_elimination")
def dead_code_elimination(program, fetch_list=None):
    """Drop ops whose outputs never reach a fetch target (reference
    ir/graph passes' DCE; here a reverse liveness sweep over records).
    Record-level only — `jaxpr_rewrite` / `Program.rewrite(passes=
    ["dce"])` performs the same elimination on the traced jaxpr with a
    verification gate."""
    live = _target_ids(program, fetch_list)
    kept = []
    for op in reversed(program.ops):
        if any(id(o) in live for o in op.outs):
            kept.append(op)
            for kind, v in op.arg_specs:
                if kind == "v":
                    live.add(id(v))
    program.ops = list(reversed(kept))
    return program


@register_pass("constant_folding")
def constant_folding(program, fetch_list=None):
    """Drop ops with no transitive placeholder/parameter dependency: the
    output tensors already carry their record-time values, which replay's
    value environment falls back to — i.e. the fold result is the captured
    constant (reference constant_folding_pass.cc, without re-execution)."""
    ph = {id(t) for t in program.placeholders.values()}
    produced = {id(o) for op in program.ops for o in op.outs}
    variable = set(ph)                            # grows with kept ops' outs

    def is_variable(spec):
        kind, v = spec
        if kind != "v":
            return False
        i = id(v)
        if i in variable:
            return True
        if i in produced:
            return False     # produced by a FOLDED op: captured constant
        # external tensor: parameters and registered buffers carry
        # persistable=True (the reference pass likewise only folds
        # non-persistable vars) and may change between replays; plain
        # captured tensors (to_tensor/full results) are frozen constants
        return bool(getattr(v, "persistable", False))

    kept = []
    for op in program.ops:
        if any(is_variable(s) for s in op.arg_specs):
            kept.append(op)
            variable.update(id(o) for o in op.outs)
    program.ops = kept
    return program


@register_pass("fuse_elementwise")
def fuse_elementwise(program, fetch_list=None):
    """Merge A->B record chains where A has one output consumed ONLY by B
    (and A's output is not itself a fetch target) into a single record
    whose fn composes the two closures.  Record-level (trims python
    dispatch at trace time); the jaxpr-level chain stitching with a real
    fused kernel lives in `jaxpr_rewrite` / the rewrite tier's "fusion"
    pass."""
    targets = _target_ids(program, fetch_list)
    ops = list(program.ops)

    def consumers(tid):
        return [j for j, op in enumerate(ops) if op is not None
                and any(k == "v" and id(v) == tid for k, v in op.arg_specs)]

    # one backward sweep: fusing op[i] into its (later) single consumer
    # leaves indices > i already-final, so no global restart is needed —
    # O(n^2) worst case from the consumer lookups, not O(n^3)
    for i in range(len(ops) - 2, -1, -1):
        a = ops[i]
        if a is None or len(a.outs) != 1:
            continue
        out_id = id(a.outs[0])
        if out_id in targets:
            continue
        cons = consumers(out_id)
        if len(cons) != 1 or cons[0] <= i:
            continue
        fused = _fuse_pair(a, ops[cons[0]], out_id)
        if fused is None:
            continue
        ops[cons[0]] = fused
        ops[i] = None
    program.ops = [op for op in ops if op is not None]
    return program


def _fuse_pair(a, b, a_out_id):
    """One record computing b(fn... a(...) ...): arg list = a's args + b's
    non-a args, positions rewired inside the closure."""
    from . import _OpRecord

    n_a = len(a.arg_specs)
    b_map = []                                   # per b-arg: ("a",) | index
    fused_specs = list(a.arg_specs)
    for kind, v in b.arg_specs:
        if kind == "v" and id(v) == a_out_id:
            b_map.append(("a",))
        else:
            b_map.append(("i", len(fused_specs)))
            fused_specs.append((kind, v))

    a_fn, a_kwargs, b_fn, b_kwargs = a.fn, a.kwargs, b.fn, b.kwargs

    def fused_fn(*raws):
        a_out = a_fn(*raws[:n_a], **a_kwargs)
        if isinstance(a_out, (tuple, list)):
            a_out = a_out[0]
        b_args = [a_out if m[0] == "a" else raws[m[1]] for m in b_map]
        return b_fn(*b_args, **b_kwargs)

    return _OpRecord(f"{a.name}+{b.name}", fused_fn, fused_specs, {}, b.outs)
