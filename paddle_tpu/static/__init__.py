"""paddle.static parity — Program/Executor/data over XLA (SURVEY.md C14/C15).

Reference architecture: ProgramDesc built op-by-op (base/framework.py:5529
Program, :2733 Operator), executed by StandaloneExecutor/InterpreterCore
(new_executor/standalone_executor.cc:158, program_interpreter.cc:99) with a
per-(program, shape) instruction cache (base/executor.py:816 _ExecutorCache).

TPU-native redesign: under `program_guard`, every dispatched op is RECORDED
into the Program (tensor.apply_op capture hook) while still executing eagerly
on sample values — graph build doubles as shape inference.  `Executor.run`
replays the recorded op list as one pure function and hands it to `jax.jit`:
XLA plays the roles of instruction scheduler, stream assigner, fusion pass
and GC all at once.  Cached per (program, feed shapes/dtypes) exactly like
_ExecutorCache.  `minimize` captures (optimizer, loss); run() then computes
grads with jax.grad over the SAME replayed function — the static backward
pass is autodiff-on-replay, not a second recorded program.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..framework import convert_dtype, to_jax_dtype
from ..tensor import Tensor, to_tensor

__all__ = [
    "Program", "program_guard", "default_main_program",
    "default_startup_program", "data", "Executor", "InputSpec", "name_scope",
    "save", "load", "save_inference_model", "load_inference_model",
    "serialize_program", "deserialize_program", "cpu_places", "cuda_places",
    "xpu_places", "global_scope", "scope_guard", "Scope", "nn",
    "passes", "apply_pass",
]

from ..jit import InputSpec  # noqa: E402  (same spec type as jit)
from . import nn  # noqa: E402  (cond/while_loop/case/switch_case)
from . import passes  # noqa: E402  (DCE/fold/fuse over recorded programs)
from .passes import apply_pass  # noqa: E402


class _OpRecord:
    __slots__ = ("name", "fn", "arg_specs", "kwargs", "outs")

    def __init__(self, name, fn, arg_specs, kwargs, outs):
        self.name = name
        self.fn = fn
        self.arg_specs = arg_specs    # list of ("v", tensor) | ("c", const)
        self.kwargs = kwargs
        self.outs = outs              # tuple of output Tensors (identity keys)


class Program:
    """A recorded op-list (the ProgramDesc analog — but ops are jax closures)."""

    def __init__(self):
        self.ops: List[_OpRecord] = []
        self.placeholders: Dict[str, Tensor] = {}
        self.placeholder_shapes: Dict[str, tuple] = {}  # declared (None dims kept)
        self._train: Optional[Tuple[Any, Tensor]] = None  # (optimizer, loss)
        self.random_seed = None
        self._cache: Dict[Any, Any] = {}
        self._removed_outputs: set = set()   # op outputs deleted by passes

    # -- capture hook (called from tensor.apply_op) ------------------------
    def _record(self, name, fn, args, kwargs, outs):
        specs = []
        for a in args:
            if isinstance(a, Tensor):
                specs.append(("v", a))
            else:
                specs.append(("c", a))
        self.ops.append(_OpRecord(name, fn, specs, dict(kwargs), tuple(
            o for o in outs if isinstance(o, Tensor))))

    def _mark_train(self, optimizer, loss):
        self._train = (optimizer, loss)
        self._cache.clear()

    # -- replay ------------------------------------------------------------
    def _replay(self, feed_raws: Dict[str, Any], param_raws=None, params=None):
        """Execute the op list purely.  env maps id(tensor) -> raw value."""
        env: Dict[int, Any] = {}
        ph_names = {id(t): n for n, t in self.placeholders.items()}
        for name, ph in self.placeholders.items():
            if name in feed_raws:
                env[id(ph)] = feed_raws[name]
        if params is not None:
            for p, raw in zip(params, param_raws):
                env[id(p)] = raw

        def val(spec):
            kind, v = spec
            if kind == "c":
                return v
            i = id(v)
            if i in env:
                return env[i]
            if i in ph_names:
                # a silently-defaulted placeholder would bake its zero sample
                # into the compiled executable as a constant
                raise KeyError(
                    f"feed target '{ph_names[i]}' was not fed "
                    f"(reference: 'feed_target not found' error)")
            return v._data  # parameter / captured constant: current value

        for op in self.ops:
            raws = [val(s) for s in op.arg_specs]
            outs = op.fn(*raws, **op.kwargs)
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            for t, r in zip(op.outs, outs):
                env[id(t)] = r
        return env

    def global_block(self):
        return self

    def clone(self, for_test=False):
        p = Program.__new__(Program)
        p.ops = list(self.ops)
        p.placeholders = dict(self.placeholders)
        p.placeholder_shapes = dict(self.placeholder_shapes)
        p._train = None if for_test else self._train
        p.random_seed = self.random_seed
        p._cache = {}
        p._removed_outputs = set(getattr(self, "_removed_outputs", ()))
        return p

    def all_parameters(self):
        return [t for t in self._externals()
                if getattr(t, "trainable", None) is not None]

    def _externals(self):
        """Tensors read by ops but produced outside the program (parameters,
        buffers, captured constants).  These become jit ARGUMENTS at replay —
        closure capture would bake them into the executable as constants and
        silently freeze parameter updates."""
        seen, out = set(), []
        produced = {id(o) for op in self.ops for o in op.outs}
        phs = {id(t) for t in self.placeholders.values()}
        for op in self.ops:
            for kind, v in op.arg_specs:
                i = id(v)
                if kind == "v" and i not in produced and i not in phs \
                        and i not in seen:
                    seen.add(i)
                    out.append(v)
        return out

    def list_vars(self):
        return list(self.placeholders.values()) + [
            o for op in self.ops for o in op.outs]

    def apply_pass(self, names, fetch_list=None):
        """Return a transformed clone (static.passes: DCE/fold/fuse)."""
        from .passes import apply_pass as _apply
        return _apply(self, names, fetch_list=fetch_list)

    def _check_fetchable(self, fetch_targets):
        """A fetch of a pass-removed tensor must error, not return the
        stale sample value (shared by Executor.run and lint)."""
        removed = getattr(self, "_removed_outputs", ())
        for f in fetch_targets:
            if id(f) in removed:
                raise KeyError(
                    f"fetch target {getattr(f, 'name', f)!r} was removed by "
                    "a graph pass (re-run apply_pass with it in fetch_list)")

    def _replay_fn(self, fetch_targets):
        """(pure, externals) where pure(feed_raws, ext_raws) replays the op
        list and returns the fetched raws — the ONE closure Executor.run
        jits and Program.lint analyzes (sharing it keeps run and lint on
        the same graph)."""
        self._check_fetchable(fetch_targets)
        ext = self._externals()
        fetch_ids = [id(f) for f in fetch_targets]
        fetch_consts = [f._data for f in fetch_targets]

        def pure(feed_raws, ext_raws):
            env = self._replay(feed_raws, ext_raws, ext)
            return [env[i] if i in env else c
                    for i, c in zip(fetch_ids, fetch_consts)]

        return pure, ext

    def lint(self, feed=None, fetch_list=None, **analyze_kwargs):
        """Run the Graph Doctor (paddle_tpu.analysis) over this program's
        replay function — the jaxpr-level *analysis* counterpart of
        apply_pass's record-level *rewrite* passes.  `feed` defaults to
        each placeholder's recorded sample value (shapes are what matter —
        nothing executes); `fetch_list` defaults to the last op's outputs,
        like the passes' target rule.  Extra kwargs (checkers=, suppress=,
        options=, ...) pass through to analysis.analyze; returns a Report.

        The nearest `.graphlintrc` (walking up from cwd) is auto-loaded
        for project suppressions/severity overrides unless an explicit
        `config=` is passed; per-call `suppress=` unions on top of it.
        """
        pure, call_args, analyze_kwargs = self._doctor_args(
            feed, fetch_list, analyze_kwargs)
        from .. import analysis
        return analysis.analyze(pure, *call_args, **analyze_kwargs)

    def rewrite(self, feed=None, fetch_list=None, passes=None,
                **rewrite_kwargs):
        """Run the Graph Doctor REWRITE tier (analysis/rewrite.py) over
        this program's replay function — the jaxpr-engine counterpart of
        `apply_pass`: where the record-level passes trim the op list,
        this transforms the traced jaxpr itself (what actually compiles),
        with every pass gated by the equivalence harness.

        Returns `(rewritten_fn, RewriteReport)`; `rewritten_fn` takes
        the feed dict of raw arrays (external tensors are bound in, like
        Executor.run binds them) and carries the final jaxpr as
        `.rewritten_jaxpr`.  See `passes.jaxpr_rewrite` for the
        pass-registry-side bridge.
        """
        pure, call_args, kw = self._doctor_args(feed, fetch_list,
                                                rewrite_kwargs)
        from .. import analysis
        fn, report = analysis.rewrite(pure, *call_args, passes=passes, **kw)
        ext_raws = call_args[1]

        def bound(feed_raws):
            return fn(feed_raws, ext_raws)

        bound.rewritten_jaxpr = fn.rewritten_jaxpr
        bound.rewrite_report = report
        return bound, report

    def _doctor_args(self, feed, fetch_list, extra_kwargs):
        """Shared lint/rewrite plumbing: default feed from placeholder
        samples, fetch targets per the passes' rule, rcfile config."""
        from .. import analysis

        extra_kwargs = dict(extra_kwargs)
        if "config" not in extra_kwargs:
            rc = analysis.find_rcfile()
            if rc is not None:
                extra_kwargs["config"] = analysis.load_rcfile(rc)
        feed = dict(feed or {})
        for name, ph in self.placeholders.items():
            feed.setdefault(name, ph)
        feed_raws = {k: (v._data if isinstance(v, Tensor) else jnp.asarray(v))
                     for k, v in feed.items()}
        targets = [Executor._resolve(self, f) for f in (fetch_list or [])]
        if not targets and self.ops:
            targets = list(self.ops[-1].outs)
        pure, ext = self._replay_fn(targets)
        return pure, (feed_raws, [t._data for t in ext]), extra_kwargs


_default_main = Program()
_default_startup = Program()
_guard_stack: List[Tuple[Program, Program]] = []


def default_main_program() -> Program:
    return _default_main


def default_startup_program() -> Program:
    return _default_startup


class program_guard:
    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program or Program()

    def __enter__(self):
        global _default_main, _default_startup
        _guard_stack.append((_default_main, _default_startup))
        _default_main, _default_startup = self.main, self.startup
        framework.get_state().capture_program = self.main
        return self

    def __exit__(self, *exc):
        global _default_main, _default_startup
        _default_main, _default_startup = _guard_stack.pop()
        framework.get_state().capture_program = (
            _default_main if _guard_stack else None)
        return False


def data(name: str, shape, dtype="float32", lod_level=0):
    """Placeholder variable (reference: static/input.py data).  Returns a
    sample-valued Tensor (None/-1 dims -> 1) registered as a feed target."""
    prog = framework.get_state().capture_program or _default_main
    concrete = tuple(1 if (d is None or d == -1) else int(d) for d in shape)
    jdt = to_jax_dtype(convert_dtype(dtype))
    t = Tensor(jnp.zeros(concrete, jdt), stop_gradient=True, name=name)
    prog.placeholders[name] = t
    prog.placeholder_shapes[name] = tuple(shape)  # keep None dims for export
    return t


class Scope:
    def __init__(self):
        self.vars = {}


_global_scope = Scope()


def global_scope():
    return _global_scope


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def cpu_places(device_count=None):
    return ["cpu"]


def cuda_places(device_ids=None):
    return ["gpu:0"]


def xpu_places(device_ids=None):
    return ["xpu:0"]


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class Executor:
    """The StandaloneExecutor analog: compiles + caches replays per program
    and feed signature (reference executor.py:1036 Executor, :816 cache)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, feed_var_name=None,
            fetch_var_name=None, scope=None, return_numpy=True):
        program = program or _default_main
        feed = feed or {}
        fetch_list = fetch_list or []
        if not isinstance(fetch_list, (list, tuple)):
            fetch_list = [fetch_list]
        fetch_list = [self._resolve(program, f) for f in fetch_list]
        program._check_fetchable(fetch_list)
        # startup/empty programs: nothing to do (params init eagerly)
        if not program.ops and not fetch_list:
            return []

        feed_raws = {k: (v._data if isinstance(v, Tensor) else jnp.asarray(v))
                     for k, v in feed.items()}
        sig = (tuple(sorted((k, tuple(r.shape), str(r.dtype))
                            for k, r in feed_raws.items())),
               tuple(id(f) for f in fetch_list))

        if program._train is not None:
            return self._run_train(program, feed_raws, fetch_list, sig,
                                   return_numpy)

        compiled = program._cache.get(sig)
        if compiled is None:
            pure, ext = program._replay_fn(fetch_list)
            compiled = jax.jit(pure)
            program._cache[sig] = compiled
        else:
            ext = program._externals()
        outs = compiled(feed_raws, [t._data for t in ext])
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    def _run_train(self, program, feed_raws, fetch_list, sig, return_numpy):
        optimizer, loss = program._train
        # static-mode Paddle often builds optimizers without parameters=;
        # they come from the program itself
        if optimizer._parameter_list is None:
            optimizer._parameter_list = program.all_parameters()
        params = [p for p in optimizer._parameter_list]
        if not params:
            raise ValueError(
                "minimize() captured no trainable parameters — pass "
                "parameters= to the optimizer or use nn.Layer parameters "
                "inside the program")
        param_ids = {id(p) for p in params}
        other = [t for t in program._externals() if id(t) not in param_ids]
        fetch_ids = [id(f) for f in fetch_list]
        fetch_consts = [f._data for f in fetch_list]
        loss_id = id(loss)

        compiled = program._cache.get(sig)
        if compiled is None:
            def pure(feed_raws, param_raws, other_raws):
                env = program._replay(feed_raws, list(param_raws)
                                      + list(other_raws), params + other)
                fetches = [env[i] if i in env else c
                           for i, c in zip(fetch_ids, fetch_consts)]
                return env[loss_id], fetches

            # one compiled pass: loss grads + pre-update fetches (has_aux)
            compiled = jax.jit(jax.value_and_grad(
                lambda pr, fr, orr: pure(fr, pr, orr), has_aux=True))
            program._cache[sig] = compiled

        param_raws = [p._data for p in params]
        other_raws = [t._data for t in other]
        (_, outs), grads = compiled(param_raws, feed_raws, other_raws)
        # hand grads to the eager optimizer (hybrid: compiled fwd/bwd, eager
        # update — the reference's static optimizer ops collapse to this)
        for p, g in zip(params, grads):
            p.grad = Tensor(g) if p.grad is None else Tensor(
                p.grad._data + g)
        optimizer.step()
        optimizer.clear_grad()
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    @staticmethod
    def _resolve(program, f):
        """Accept fetch-list entries by variable name (legacy idiom)."""
        if not isinstance(f, str):
            return f
        if f in program.placeholders:
            return program.placeholders[f]
        for t in program.list_vars():
            if getattr(t, "name", None) == f:
                return t
        raise ValueError(f"fetch target '{f}' not found in program")

    def close(self):
        return None


# -- inference model save/load (reference: static/io.py) --------------------


def serialize_program(feed_vars, fetch_vars, program=None):
    import pickle

    program = program or _default_main
    return pickle.dumps({"n_feed": len(feed_vars), "n_fetch": len(fetch_vars)})


def deserialize_program(data):
    import pickle

    return pickle.loads(data)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None):
    """StableHLO export of the replay function (reference save_inference_model
    writes ProgramDesc+params; here the artifact is a serialized XLA export +
    params pickle)."""
    import os
    import pickle

    from jax import export as jax_export

    program = program or _default_main
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = (fetch_vars if isinstance(fetch_vars, (list, tuple))
                  else [fetch_vars])
    names = [next(n for n, t in program.placeholders.items() if t is fv)
             for fv in feed_vars]
    fetch_ids = [id(f) for f in fetch_vars]

    def pure(*arg_raws):
        env = program._replay(dict(zip(names, arg_raws)))
        return [env.get(i, f._data) for i, f in zip(fetch_ids, fetch_vars)]

    args_abs = [jax.ShapeDtypeStruct(tuple(fv.shape),
                                     fv._data.dtype) for fv in feed_vars]
    exported = jax_export.export(jax.jit(pure))(*args_abs)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({"feed_names": names}, f)


def load_inference_model(path_prefix, executor=None):
    import pickle

    from jax import export as jax_export

    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path_prefix + ".pdiparams", "rb") as f:
        meta = pickle.load(f)

    class _InferProgram:
        def __init__(self):
            self.exported = exported

    prog = _InferProgram()
    names = meta["feed_names"]

    def run(feed):
        outs = exported.call(*[jnp.asarray(feed[n]) for n in names])
        return [np.asarray(o) for o in outs]

    prog.run = run
    return [prog, names, None]


def save(program, model_path, protocol=4):
    import pickle

    params = {i: np.asarray(p._data)
              for i, p in enumerate(program.all_parameters())}
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(params, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    import pickle

    with open(model_path + ".pdparams", "rb") as f:
        params = pickle.load(f)
    for i, p in enumerate(program.all_parameters()):
        if i in params:
            p.data = jnp.asarray(params[i])


# legacy static-graph surface (EMA, append_backward, py_func, persistable
# serialization, strategy shims) — see compat.py
from . import compat as _compat  # noqa: E402
from .compat import *  # noqa: E402,F401,F403

__all__ += list(_compat.__all__)


class _StaticIo:
    save_persistables = staticmethod(_compat.save_persistables)
    load_persistables = staticmethod(_compat.load_persistables)


io = _StaticIo()
