"""incubate.autograd — functional autodiff (reference:
incubate/autograd/functional.py:22 vjp, :80 jvp; Jacobian/Hessian classes).

TPU-native: thin re-exports of the jax.vjp/jvp/jacobian-backed implementations
in paddle_tpu.autograd (C46)."""

from ...autograd import (  # noqa: F401
    vjp, jvp, jacobian, hessian, grad, no_grad,
)

# reference exposes class-style lazy Jacobian/Hessian too; the function forms
# cover the API (autograd.py:450,544) — alias the names
Jacobian = jacobian
Hessian = hessian


def forward_grad(outputs, inputs, grad_inputs=None):
    """incubate.autograd.forward_grad — JVP with default-ones tangents."""
    return jvp(lambda *xs: outputs, inputs, grad_inputs)


def enable_prim():
    """Reference toggles primitive-op lowering for the static AD engine; the
    TPU build always differentiates through jax primitives — no-op."""
    return None


def disable_prim():
    return None


def prim_enabled():
    return True
