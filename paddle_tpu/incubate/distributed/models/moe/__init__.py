"""incubate.distributed.models.moe parity — re-exports the TPU-native MoE
stack (distributed/moe.py).  Reference: moe_layer.py:263 MoELayer + gate/."""

from .....distributed.moe import (  # noqa: F401
    MoEConfig, MoELayer, NaiveGate, SwitchGate, GShardGate,
    moe_ffn, top_k_gating, gating_indices, global_scatter, global_gather,
)
