"""paddle.incubate parity namespace.

Reference: python/paddle/incubate/ — fused-op APIs (nn/functional/
fused_transformer.py, fused_rotary_position_embedding.py, fused_rms_norm),
functional autodiff (autograd/functional.py:22 vjp, :80 jvp), ASP 2:4 sparsity
(asp/asp.py), MoE models (distributed/models/moe/moe_layer.py).

On TPU the "fused" ops are Pallas kernels or XLA-fused jnp programs from
paddle_tpu.kernels — same API, compiler-native fusion.
"""

from __future__ import annotations

from . import nn  # noqa: F401
from . import autograd  # noqa: F401
from . import asp  # noqa: F401
from . import distributed  # noqa: F401


def softmax_mask_fuse_upper_triangle(x):
    """Causal-masked softmax (incubate.softmax_mask_fuse_upper_triangle)."""
    import jax.numpy as jnp

    from ..tensor import apply_op
    from ..nn.functional import _t

    def f(v):
        import jax

        s = v.shape[-1]
        m = jnp.tril(jnp.ones((s, s), bool))
        return jax.nn.softmax(jnp.where(m, v, -jnp.inf), axis=-1)

    return apply_op("softmax_mask_fuse_upper_triangle", f, _t(x))


def softmax_mask_fuse(x, mask):
    """softmax(x + mask) fused (reference incubate/operators/
    softmax_mask_fuse.py — XLA fuses the add into the softmax)."""
    import jax
    from ..tensor import apply_op
    from ..nn.functional import _t

    return apply_op("softmax_mask_fuse",
                    lambda v, m: jax.nn.softmax(v + m, axis=-1),
                    _t(x), _t(mask))


def identity_loss(x, reduction="none"):
    """Marks a loss for IPU-style identity backward (reference
    incubate/autograd); here reduction over x with grad flowing as-is."""
    from .. import ops
    if reduction in (0, "sum"):
        return ops.sum(x)
    if reduction in (1, "mean"):
        return ops.mean(x)
    if reduction in (2, "none"):
        return x
    raise ValueError(f"unknown reduction {reduction!r}")


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Deprecated alias of geometric.send_u_recv (reference kept it
    exported under incubate)."""
    from ..geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Deprecated alias of geometric.reindex_graph."""
    from ..geometric import reindex_graph
    return reindex_graph(x, neighbors, count, value_buffer, index_buffer)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """Deprecated alias of geometric.sample_neighbors."""
    from ..geometric import sample_neighbors
    return sample_neighbors(row, colptr, input_nodes,
                            sample_size=sample_size, eids=eids,
                            return_eids=return_eids)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling (reference incubate/operators/
    graph_khop_sampler.py): sample_sizes per hop; returns
    (edge_src, edge_dst, sample_index, reindex_nodes) [+ edge_eids]."""
    import numpy as np
    from ..geometric import sample_neighbors, reindex_graph
    from ..tensor import to_tensor

    cur = input_nodes
    all_nb, all_ct = [], []
    seen_order = list(np.asarray(
        cur._data if hasattr(cur, "_data") else cur).reshape(-1))
    for size in sample_sizes:
        nb, ct = sample_neighbors(row, colptr, cur, sample_size=size)
        all_nb.append(np.asarray(nb._data))
        all_ct.append(np.asarray(ct._data))
        nxt = []
        seen = set(int(v) for v in seen_order)
        for v in np.asarray(nb._data).reshape(-1):
            if int(v) not in seen:
                seen.add(int(v))
                nxt.append(int(v))
                seen_order.append(int(v))
        cur = to_tensor(np.asarray(nxt, np.asarray(nb._data).dtype)) \
            if nxt else to_tensor(np.zeros((0,), np.int64))
        if not nxt:
            break
    neighbors = np.concatenate(all_nb) if all_nb else np.zeros((0,), np.int64)
    counts = np.concatenate(all_ct) if all_ct \
        else np.zeros((0,), np.int32)
    src, dst, nodes = reindex_graph(
        to_tensor(np.asarray(
            [v for v in seen_order][:len(counts)], np.int64)),
        to_tensor(neighbors), to_tensor(counts))
    return src, dst, to_tensor(np.asarray(seen_order, np.int64)), nodes


def _segment(kind):
    def op(data, segment_ids, name=None):
        from .. import geometric
        return getattr(geometric, f"segment_{kind}")(data, segment_ids)
    op.__name__ = f"segment_{kind}"
    op.__doc__ = f"Alias of geometric.segment_{kind} (reference incubate " \
                 "export)."
    return op


segment_sum = _segment("sum")
segment_mean = _segment("mean")
segment_min = _segment("min")
segment_max = _segment("max")


class LookAhead:
    """Lookahead optimizer wrapper (reference incubate/optimizer/lookahead.py):
    every k steps, slow weights move alpha toward the fast weights and the
    fast weights reset to them."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        if int(k) < 1:
            raise ValueError("k should be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._steps = 0
        self._slow = None

    def _params(self):
        return [p for p in (self.inner_optimizer._parameter_list or [])]

    def step(self):
        import numpy as np
        params = self._params()
        if self._slow is None:
            # slow weights start at the INITIAL parameters (pre-step)
            self._slow = [np.asarray(p._data) for p in params]
        self.inner_optimizer.step()
        self._steps += 1
        if self._steps % self.k == 0:
            import jax.numpy as jnp
            for i, p in enumerate(params):
                slow = (jnp.asarray(self._slow[i])
                        + self.alpha * (p._data - jnp.asarray(self._slow[i])))
                self._slow[i] = np.asarray(slow)
                p._data = slow.astype(p._data.dtype)

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def minimize(self, loss):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage:
    """Running parameter average for eval (reference incubate/optimizer/
    modelaverage.py, simplified to the sliding-rate form): accumulate on
    `step()`; `apply()` swaps averaged weights in, `restore()` swaps back."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params = list(parameters or [])
        self._sum = None
        self._count = 0
        self._backup = None

    def step(self):
        import numpy as np
        if self._sum is None:
            self._sum = [np.zeros_like(np.asarray(p._data, np.float32))
                         for p in self._params]
        for s, p in zip(self._sum, self._params):
            s += np.asarray(p._data, np.float32)
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        import jax.numpy as jnp
        import numpy as np
        if not self._count:
            return
        self._backup = [np.asarray(p._data) for p in self._params]
        for s, p in zip(self._sum, self._params):
            p._data = jnp.asarray(s / self._count).astype(p._data.dtype)

    def restore(self, executor=None):
        import jax.numpy as jnp
        if self._backup is None:
            return
        for b, p in zip(self._backup, self._params):
            p._data = jnp.asarray(b)
        self._backup = None
