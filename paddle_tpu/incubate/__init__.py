"""paddle.incubate parity namespace.

Reference: python/paddle/incubate/ — fused-op APIs (nn/functional/
fused_transformer.py, fused_rotary_position_embedding.py, fused_rms_norm),
functional autodiff (autograd/functional.py:22 vjp, :80 jvp), ASP 2:4 sparsity
(asp/asp.py), MoE models (distributed/models/moe/moe_layer.py).

On TPU the "fused" ops are Pallas kernels or XLA-fused jnp programs from
paddle_tpu.kernels — same API, compiler-native fusion.
"""

from __future__ import annotations

from . import nn  # noqa: F401
from . import autograd  # noqa: F401
from . import asp  # noqa: F401
from . import distributed  # noqa: F401


def softmax_mask_fuse_upper_triangle(x):
    """Causal-masked softmax (incubate.softmax_mask_fuse_upper_triangle)."""
    import jax.numpy as jnp

    from ..tensor import apply_op
    from ..nn.functional import _t

    def f(v):
        import jax

        s = v.shape[-1]
        m = jnp.tril(jnp.ones((s, s), bool))
        return jax.nn.softmax(jnp.where(m, v, -jnp.inf), axis=-1)

    return apply_op("softmax_mask_fuse_upper_triangle", f, _t(x))
