"""incubate.asp — 2:4 structured sparsity (reference: incubate/asp/asp.py,
supported_layer_list.py, utils.py).

The reference prunes weights to the NVIDIA 2:4 pattern for sparse tensor
cores.  TPUs have no 2:4 hardware path, but the pruning/masking workflow is
kept: masks are computed the same way (best 2-of-4 by magnitude) and applied
as elementwise multiplies that XLA fuses into the consuming matmul — the
workflow (prune -> finetune -> export) is portable.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

_masks: Dict[int, object] = {}
_excluded: Dict[int, set] = {}


def calculate_density(x) -> float:
    arr = np.asarray(getattr(x, "data", x))
    return float((arr != 0).sum() / arr.size)


def _mask_2to4_1d(v: np.ndarray) -> np.ndarray:
    """Keep the 2 largest |v| of every 4 along the last axis."""
    n = v.shape[-1]
    pad = (-n) % 4
    if pad:
        v = np.concatenate([v, np.zeros(v.shape[:-1] + (pad,), v.dtype)], -1)
    g = np.abs(v).reshape(v.shape[:-1] + (-1, 4))
    order = np.argsort(g, axis=-1)
    mask = np.ones_like(g, dtype=bool)
    np.put_along_axis(mask, order[..., :2], False, axis=-1)
    mask = mask.reshape(v.shape)
    return mask[..., :n] if pad else mask


def create_mask(tensor, func_name: str = "mask_1d", n: int = 2, m: int = 4):
    """2:4 mask with the same (n, m) meaning as the reference's
    CheckMethod/MaskAlgo (asp/utils.py): keep n of every m by magnitude."""
    arr = np.asarray(getattr(tensor, "data", tensor))
    if (n, m) != (2, 4):
        k = m - n
        pad = (-arr.shape[-1]) % m
        v = np.concatenate([arr, np.zeros(arr.shape[:-1] + (pad,), arr.dtype)], -1) if pad else arr
        g = np.abs(v).reshape(v.shape[:-1] + (-1, m))
        order = np.argsort(g, axis=-1)
        mask = np.ones_like(g, dtype=bool)
        np.put_along_axis(mask, order[..., :k], False, axis=-1)
        mask = mask.reshape(v.shape)
        return mask[..., :arr.shape[-1]] if pad else mask
    return _mask_2to4_1d(arr)


def check_sparsity(tensor, n: int = 2, m: int = 4, func_name="check_1d") -> bool:
    arr = np.asarray(getattr(tensor, "data", tensor))
    pad = (-arr.shape[-1]) % m
    if pad:
        arr = np.concatenate([arr, np.zeros(arr.shape[:-1] + (pad,), arr.dtype)], -1)
    g = (arr != 0).reshape(arr.shape[:-1] + (-1, m))
    return bool((g.sum(-1) <= n).all())


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True):
    """Prune every supported Linear weight in `model` to n:m sparsity and
    register masks so optimizer steps can re-apply them (asp.py prune_model)."""
    from ...nn.layer import Layer
    from ...tensor import to_tensor

    pruned = {}
    pairs = []
    for name, layer in _iter_layers(model):
        if id(layer) in _excluded.get(id(model), set()):
            continue
        w = getattr(layer, "weight", None)
        if w is None or getattr(w, "ndim", 0) != 2:
            continue
        mask = create_mask(w, func_name=mask_algo, n=n, m=m)
        w.data = jnp.asarray(np.asarray(w.data) * mask)
        pruned[f"{name}.weight"] = mask
        pairs.append((w, jnp.asarray(mask, w.data.dtype)))
    _masks[id(model)] = pairs
    return pruned


def decorate(optimizer):
    """Wrap optimizer.step to re-apply every registered ASP mask after the
    update (asp.py decorate) — keeps pruned slots at zero through training."""
    raw_step = optimizer.step

    def step(*a, **kw):
        out = raw_step(*a, **kw)
        for pairs in _masks.values():
            for w, mask in pairs:
                w.data = w.data * mask
        return out

    optimizer.step = step
    return optimizer


def set_excluded_layers(model, layer_names):
    ex = _excluded.setdefault(id(model), set())
    lookup = dict(_iter_layers(model))
    for n in layer_names:
        if n in lookup:
            ex.add(id(lookup[n]))


def reset_excluded_layers(model=None):
    if model is None:
        _excluded.clear()
    else:
        _excluded.pop(id(model), None)


def _iter_layers(model, prefix=""):
    out = [(prefix or "model", model)]
    for name, sub in getattr(model, "_sub_layers", {}).items():
        out.extend(_iter_layers(sub, f"{prefix}.{name}" if prefix else name))
    return out
