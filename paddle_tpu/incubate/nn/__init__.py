"""incubate.nn — fused transformer layers (reference:
python/paddle/incubate/nn/layer/fused_transformer.py).

Each layer is a thin tape-aware module over paddle_tpu.kernels; on TPU the
compute lowers to Pallas flash-attention / fused-norm kernels, elsewhere to
XLA-fused jnp.  "Fused" here means one traced subgraph per layer — XLA fuses
the epilogues the reference hand-wrote as CUDA kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...nn.layer import Layer, ParameterList
from ...nn import initializer as I
from ...tensor import apply_op
from ... import kernels
from . import functional  # noqa: F401


class FusedLinear(Layer):
    """incubate.nn.FusedLinear — linear whose bias/act epilogue fuses into the
    matmul (on TPU: XLA does this natively; kept for API parity)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = ([out_features, in_features] if transpose_weight
                 else [in_features, out_features])
        self.weight = self.create_parameter(shape, attr=weight_attr,
                                            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        from . import functional as F

        return F.fused_linear(x, self.weight, self.bias,
                              transpose_weight=self.transpose_weight)


class FusedMultiHeadAttention(Layer):
    """incubate.nn.FusedMultiHeadAttention (fused_transformer.py) — pre/post-LN
    MHA block: LN -> qkv proj -> flash attention -> out proj -> residual."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.0,
                 attn_dropout_rate=0.0, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError("embed_dim must divide num_heads")
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        self.dropout_rate = dropout_rate
        self.qkv_weight = self.create_parameter(
            [embed_dim, 3 * embed_dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.qkv_bias = self.create_parameter([3 * embed_dim], attr=bias_attr,
                                              is_bias=True)
        self.out_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.out_bias = self.create_parameter([embed_dim], attr=bias_attr,
                                              is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, x, attn_mask=None):
        H = self.num_heads
        eps = self.epsilon
        pre = self.normalize_before

        def f(xv, qkvw, qkvb, ow, ob, s, b, mask=None):
            B, S, E = xv.shape
            D = E // H
            h = xv
            if pre:
                mu = h.mean(-1, keepdims=True)
                var = ((h - mu) ** 2).mean(-1, keepdims=True)
                h = (h - mu) * jax.lax.rsqrt(var + eps) * s + b
            qkv = h @ qkvw + qkvb
            q, k, v = jnp.split(qkv.reshape(B, S, 3 * H, D), 3, axis=2)
            attn = kernels.attention(q, k, v, mask=mask)
            out = attn.reshape(B, S, E) @ ow + ob
            out = xv + out
            if not pre:
                mu = out.mean(-1, keepdims=True)
                var = ((out - mu) ** 2).mean(-1, keepdims=True)
                out = (out - mu) * jax.lax.rsqrt(var + eps) * s + b
            return out

        args = [x, self.qkv_weight, self.qkv_bias, self.out_weight,
                self.out_bias, self.ln_scale, self.ln_bias]
        if attn_mask is not None:
            args.append(attn_mask)
        return apply_op("fused_multi_head_attention", f, *args)


class FusedFeedForward(Layer):
    """incubate.nn.FusedFeedForward — LN -> linear -> act -> linear -> residual."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        self.activation = activation
        self.w1 = self.create_parameter([d_model, dim_feedforward],
                                        attr=weight_attr,
                                        default_initializer=I.XavierNormal())
        self.b1 = self.create_parameter([dim_feedforward], is_bias=True)
        self.w2 = self.create_parameter([dim_feedforward, d_model],
                                        attr=weight_attr,
                                        default_initializer=I.XavierNormal())
        self.b2 = self.create_parameter([d_model], is_bias=True)
        self.ln_scale = self.create_parameter(
            [d_model], default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter([d_model], is_bias=True)

    def forward(self, x):
        eps, pre, act = self.epsilon, self.normalize_before, self.activation

        def f(xv, w1, b1, w2, b2, s, b):
            h = xv
            if pre:
                mu = h.mean(-1, keepdims=True)
                var = ((h - mu) ** 2).mean(-1, keepdims=True)
                h = (h - mu) * jax.lax.rsqrt(var + eps) * s + b
            h = kernels.fused_bias_act(h @ w1, b1, act=act)
            out = xv + (h @ w2 + b2)
            if not pre:
                mu = out.mean(-1, keepdims=True)
                var = ((out - mu) ** 2).mean(-1, keepdims=True)
                out = (out - mu) * jax.lax.rsqrt(var + eps) * s + b
            return out

        return apply_op("fused_feedforward", f, x, self.w1, self.b1, self.w2,
                        self.b2, self.ln_scale, self.ln_bias)


class FusedTransformerEncoderLayer(Layer):
    """incubate.nn.FusedTransformerEncoderLayer = fused MHA + fused FFN."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.self_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, normalize_before=normalize_before)

    def forward(self, src, src_mask=None):
        return self.ffn(self.self_attn(src, attn_mask=src_mask))


class FusedEcMoe(Layer):
    """incubate.nn.FusedEcMoe (fused_ec_moe.py) — expert-choice MoE FFN:
    experts pick their top-C tokens (capacity-perfect, drop by construction)."""

    def __init__(self, hidden_size, inter_size, num_experts, act_type="gelu",
                 capacity_per_expert=None, weight_attr=None, bias_attr=None):
        super().__init__()
        self.num_experts = num_experts
        self.act_type = act_type
        self.capacity = capacity_per_expert
        self.gate = self.create_parameter(
            [hidden_size, num_experts], default_initializer=I.Normal(std=0.02))
        self.w1 = self.create_parameter(
            [num_experts, hidden_size, inter_size],
            default_initializer=I.Normal(std=0.02))
        self.b1 = self.create_parameter([num_experts, 1, inter_size], is_bias=True)
        self.w2 = self.create_parameter(
            [num_experts, inter_size, hidden_size],
            default_initializer=I.Normal(std=0.02))
        self.b2 = self.create_parameter([num_experts, 1, hidden_size], is_bias=True)

    def forward(self, x, gate_logits=None):
        act = self.act_type
        cap = self.capacity

        def f(xv, gw, w1, b1, w2, b2):
            B, S, E = xv.shape
            N = B * S
            X = w1.shape[0]
            C = cap or max(1, (2 * N) // X)
            tok = xv.reshape(N, E)
            scores = jax.nn.softmax(tok.astype(jnp.float32) @ gw, axis=-1)  # (N, X)
            # expert choice: each expert takes its top-C tokens
            g, idx = jax.lax.top_k(scores.T, C)                  # (X, C)
            xp = jnp.take(tok, idx.reshape(-1), axis=0).reshape(X, C, E)
            h = kernels.fused_bias_act(
                jnp.einsum("xce,xef->xcf", xp, w1) + b1, act=act)
            eo = jnp.einsum("xcf,xfe->xce", h, w2) + b2
            weighted = eo * g[..., None].astype(eo.dtype)
            out = jnp.zeros((N, E), eo.dtype).at[idx.reshape(-1)].add(
                weighted.reshape(X * C, E))
            return out.reshape(B, S, E)

        return apply_op("fused_ec_moe", f, x, self.gate, self.w1, self.b1,
                        self.w2, self.b2)


class FusedDropoutAdd(Layer):
    """dropout(x) + y in one op (reference incubate/nn/layer/
    fused_dropout_add.py)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.mode = p, mode

    def forward(self, x, y):
        from . import functional as IF
        return IF.fused_dropout_add(x, y, p=self.p, training=self.training,
                                    mode=self.mode)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """(x + bias) -> dropout -> + residual -> LN (reference incubate/nn/
    layer/fused_transformer.py FusedBiasDropoutResidualLayerNorm)."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        from ...nn import initializer as I
        self._p, self._eps = dropout_rate, epsilon
        self.linear_bias = self.create_parameter(
            (embed_dim,), bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))
        self.ln_scale = self.create_parameter(
            (embed_dim,), weight_attr, default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter(
            (embed_dim,), bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))

    def forward(self, x, residual):
        from . import functional as IF
        return IF.fused_bias_dropout_residual_layer_norm(
            x, residual, self.linear_bias, self.ln_scale, self.ln_bias,
            dropout_rate=self._p, ln_epsilon=self._eps,
            training=self.training)


class FusedMultiTransformer(Layer):
    """Stacked fused transformer (reference incubate/nn/layer/
    fused_transformer.py FusedMultiTransformer): owns per-layer packed
    weights, forwards through functional.fused_multi_transformer."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 num_layers=1, epsilon=1e-5, name=None, **kwargs):
        super().__init__()
        from ...nn import initializer as I
        import math as _m
        std = 0.02
        n = I.Normal(std=std)
        z = I.Constant(0.0)
        o = I.Constant(1.0)
        self._eps, self._act = epsilon, activation
        self._pre = normalize_before
        self._nh = num_heads
        self._p = dropout_rate
        D, F_ = embed_dim, dim_feedforward
        mk = self.create_parameter
        self.ln_scales = ParameterList(
            [mk((D,), default_initializer=o) for _ in range(num_layers)])
        self.ln_biases = ParameterList(
            [mk((D,), is_bias=True) for _ in range(num_layers)])
        self.qkv_weights = ParameterList(
            [mk((D, 3 * D), default_initializer=n) for _ in range(num_layers)])
        self.qkv_biases = ParameterList(
            [mk((3 * D,), is_bias=True) for _ in range(num_layers)])
        self.linear_weights = ParameterList(
            [mk((D, D), default_initializer=n) for _ in range(num_layers)])
        self.linear_biases = ParameterList(
            [mk((D,), is_bias=True) for _ in range(num_layers)])
        self.ffn_ln_scales = ParameterList(
            [mk((D,), default_initializer=o) for _ in range(num_layers)])
        self.ffn_ln_biases = ParameterList(
            [mk((D,), is_bias=True) for _ in range(num_layers)])
        self.ffn1_weights = ParameterList(
            [mk((D, F_), default_initializer=n) for _ in range(num_layers)])
        self.ffn1_biases = ParameterList(
            [mk((F_,), is_bias=True) for _ in range(num_layers)])
        self.ffn2_weights = ParameterList(
            [mk((F_, D), default_initializer=n) for _ in range(num_layers)])
        self.ffn2_biases = ParameterList(
            [mk((D,), is_bias=True) for _ in range(num_layers)])

    def forward(self, x, attn_mask=None, caches=None, time_step=None):
        from . import functional as IF
        return IF.fused_multi_transformer(
            x, list(self.ln_scales), list(self.ln_biases),
            list(self.qkv_weights), list(self.qkv_biases),
            list(self.linear_weights), list(self.linear_biases),
            list(self.ffn_ln_scales), list(self.ffn_ln_biases),
            list(self.ffn1_weights), list(self.ffn1_biases),
            list(self.ffn2_weights), list(self.ffn2_biases),
            pre_layer_norm=self._pre, epsilon=self._eps,
            attn_mask=attn_mask, activation=self._act,
            dropout_rate=self._p, num_heads=self._nh,
            training=self.training)
