"""incubate.nn.functional — fused-op functional API.

Reference: python/paddle/incubate/nn/functional/ (fused_rotary_position_
embedding.py, fused_rms_norm.py, fused_layer_norm.py, fused_transformer.py,
swiglu, fused_linear, fused_bias_act).  Backed by paddle_tpu.kernels (Pallas
on TPU, XLA-fused jnp elsewhere); tape-aware via tensor.apply_op.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ....tensor import Tensor, apply_op, to_tensor
from .... import kernels

__all__ = [
    "fused_rotary_position_embedding", "fused_rms_norm", "fused_layer_norm",
    "fused_bias_act", "fused_linear", "fused_linear_activation", "swiglu",
    "fused_dropout_add", "fused_multi_head_attention", "fused_feedforward",
    "variable_length_memory_efficient_attention", "masked_multihead_attention",
    "fused_matmul_bias", "fused_bias_dropout_residual_layer_norm",
    "fused_ec_moe", "fused_multi_transformer",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    """Reference: incubate/nn/functional/fused_rotary_position_embedding.py
    (CUDA fused_rope_kernel.cu).  (B, S, H, D) layout."""
    args = [a for a in (q, k, v) if a is not None]
    n = len(args)
    ts = [_t(a) for a in args]
    sin_t, cos_t = _t(sin), _t(cos)
    pos = position_ids if position_ids is None else _t(position_ids)

    def f(*raw):
        qkv = raw[:n]
        s, c = raw[n], raw[n + 1]
        p = raw[n + 2] if pos is not None else None
        return kernels.fused_rotary_position_embedding(
            qkv[0], qkv[1] if n > 1 else None, qkv[2] if n > 2 else None,
            sin=s, cos=c, position_ids=p,
            use_neox_rotary_style=use_neox_rotary_style)

    extra = [sin_t, cos_t] + ([pos] if pos is not None else [])
    return apply_op("fused_rope", f, *ts, *extra,
                    nondiff=(len(ts) + 2,) if pos is not None else ())


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, **_ignored):
    """Reference: fused_rms_norm (phi fusion kernel).  Optional residual+bias
    pre-add, then RMSNorm — returns (out, residual_out) when residual given."""
    xs = [_t(x), _t(norm_weight)]
    has_b = norm_bias is not None
    has_res = residual is not None
    has_bias = bias is not None
    if has_b:
        xs.append(_t(norm_bias))
    if has_bias:
        xs.append(_t(bias))
    if has_res:
        xs.append(_t(residual))

    def f(*raw):
        i = 2
        nb = raw[i] if has_b else None
        i += has_b
        bb = raw[i] if has_bias else None
        i += has_bias
        res = raw[i] if has_res else None
        h = raw[0]
        if bb is not None:
            h = h + bb
        if res is not None:
            h = h + res
        out = kernels.rms_norm(h, raw[1], epsilon)
        if nb is not None:
            out = out + nb
        if has_res:
            return out, h
        return out

    return apply_op("fused_rms_norm", f, *xs)


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None, **_ignored):
    """Reference: fused_layernorm_kernel.cu — residual+bias add + LayerNorm."""
    xs = [_t(x), _t(norm_weight), _t(norm_bias)]
    has_res = residual is not None
    has_bias = bias is not None
    if has_bias:
        xs.append(_t(bias))
    if has_res:
        xs.append(_t(residual))

    def f(*raw):
        i = 3
        bb = raw[i] if has_bias else None
        i += has_bias
        res = raw[i] if has_res else None
        h = raw[0]
        if bb is not None:
            h = h + bb
        if res is not None:
            h = h + res
        hf = h.astype(jnp.float32)
        mu = hf.mean(-1, keepdims=True)
        var = ((hf - mu) ** 2).mean(-1, keepdims=True)
        out = ((hf - mu) * jax.lax.rsqrt(var + epsilon)).astype(h.dtype)
        out = out * raw[1] + raw[2]
        if has_res:
            return out, h
        return out

    return apply_op("fused_layer_norm", f, *xs)


def fused_bias_act(x, bias=None, act_method="gelu", **_ignored):
    xs = [_t(x)]
    if bias is not None:
        xs.append(_t(bias))

    def f(*raw):
        return kernels.fused_bias_act(raw[0], raw[1] if bias is not None else None,
                                      act=act_method)

    return apply_op("fused_bias_act", f, *xs)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    xs = [_t(x), _t(weight)]
    if bias is not None:
        xs.append(_t(bias))

    def f(*raw):
        w = raw[1].T if transpose_weight else raw[1]
        y = raw[0] @ w
        if bias is not None:
            y = y + raw[2]
        return y

    return apply_op("fused_linear", f, *xs)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu"):
    xs = [_t(x), _t(y), _t(bias)]

    def f(a, w, b):
        a = a.T if trans_x else a
        w = w.T if trans_y else w
        return kernels.fused_bias_act(a @ w, b, act=activation)

    return apply_op("fused_linear_activation", f, *xs)


def swiglu(x, y=None, name=None):
    if y is None:
        return apply_op("swiglu", lambda a: kernels.swiglu(a), _t(x))
    return apply_op("swiglu", lambda a, b: kernels.swiglu(a, b), _t(x), _t(y))


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """Reference: fused_dropout_add op — dropout(x) + y in one kernel."""
    from ....nn import functional as NF

    return NF.dropout(_t(x), p=p, training=training, mode=mode) + _t(y)


def fused_multi_head_attention(x, qkv_weight, qkv_bias, linear_weight,
                               linear_bias, num_heads=None, attn_mask=None,
                               **kwargs):
    """Functional form (fused_transformer.py fused_multi_head_attention) —
    qkv proj -> flash attention -> out proj.  qkv_weight is either the
    reference (3, H, D, E) layout (num_heads inferred) or (E, 3E) with
    `num_heads` passed explicitly."""
    if hasattr(qkv_weight, "ndim") and qkv_weight.ndim == 4:
        num_heads = qkv_weight.shape[1]
    if num_heads is None:
        raise ValueError("num_heads required for 2-D qkv_weight")
    H = num_heads
    xs = [_t(x), _t(qkv_weight), _t(qkv_bias), _t(linear_weight), _t(linear_bias)]
    if attn_mask is not None:
        xs.append(_t(attn_mask))

    def f(xv, qkvw, qkvb, ow, ob, mask=None):
        B, S, E = xv.shape
        D = E // H
        if qkvw.ndim == 4:  # reference layout (3, H, D, E)
            qkv = jnp.einsum("bse,thde->bsthd", xv, qkvw).reshape(B, S, 3 * E)
        else:               # (E, 3E), columns [q|k|v]
            qkv = xv @ qkvw
        qkv = qkv + qkvb.reshape(-1)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, H, D)
        k = k.reshape(B, S, H, D)
        v = v.reshape(B, S, H, D)
        attn = kernels.attention(q, k, v, mask=mask)
        return attn.reshape(B, S, E) @ ow.reshape(E, E) + ob

    return apply_op("fused_multi_head_attention_fn", f, *xs)


def fused_feedforward(x, linear1_weight, linear1_bias, linear2_weight,
                      linear2_bias, *args, activation="relu", **kwargs):
    xs = [_t(x), _t(linear1_weight), _t(linear1_bias), _t(linear2_weight),
          _t(linear2_bias)]

    def f(xv, w1, b1, w2, b2):
        h = kernels.fused_bias_act(xv @ w1, b1, act=activation)
        return xv + h @ w2 + b2

    return apply_op("fused_feedforward_fn", f, *xs)


def variable_length_memory_efficient_attention(query, key, value, seq_lens=None,
                                               kv_seq_lens=None, mask=None,
                                               scale=None, causal=False):
    """Reference: incubate memory_efficient_attention — maps to the same
    flash-attention kernel (padding masks express variable length)."""
    q, k, v = _t(query), _t(key), _t(value)
    xs = [q, k, v] + ([_t(mask)] if mask is not None else [])

    def f(q, k, v, m=None):
        # (B, H, S, D) reference layout -> kernels layout (B, S, H, D)
        qt, kt, vt = (jnp.swapaxes(a, 1, 2) for a in (q, k, v))
        out = kernels.attention(qt, kt, vt, mask=m, causal=causal, scale=scale)
        return jnp.swapaxes(out, 1, 2)

    return apply_op("var_len_mem_eff_attention", f, *xs)


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               sequence_lengths=None, rotary_tensor=None,
                               seq_len=1, rotary_emb_dims=0,
                               use_neox_rotary_style=False, out_scale=-1,
                               **_unsupported):
    """Decode-phase attention with KV cache — one new token per sequence.

    Reference: incubate/nn/functional/masked_multihead_attention.py wrapping
    phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu.  TPU-native:
    jnp composition (dynamic cache update + masked softmax) that XLA fuses;
    the batched-decode serving path in models/generation.py uses the same
    math with a lax.scan loop.

    x: (B, 3*H*D) fused qkv of the CURRENT step.
    cache_kv: (2, B, H, max_seq, D); slot `t` = current length (from
        `sequence_lengths` (B,) or (B,1); defaults to 0).
    bias: (3*H*D,) qkv bias; src_mask: broadcastable additive mask over the
        cache axis, e.g. (B, 1, 1, max_seq).
    rotary_tensor: (B, 1, 1, max_seq, D) [cos; sin] interleaved convention of
        the reference when rotary_emb_dims > 0 — rotary applied to q/k.
    Returns (out (B, H*D), updated cache_kv).
    """
    if out_scale != -1:
        raise NotImplementedError("quantized out_scale path not supported")
    # reference signature defaults (masked_multihead_attention.py) — passing
    # one of these AT its default changes nothing and must not raise; any
    # other value selects a quantized path we do not implement
    ref_defaults = {"compute_dtype": "default", "quant_round_type": 1,
                    "quant_max_bound": 127.0, "quant_min_bound": -127.0}

    def _at_default(k, v):
        if v is None:
            return True
        d = ref_defaults.get(k)
        return (d is not None and isinstance(v, (str, int, float))
                and v == d)

    passed = {k: v for k, v in _unsupported.items() if not _at_default(k, v)}
    if passed:
        # quant-scale tensors etc. would silently change numerics if ignored
        raise NotImplementedError(
            f"masked_multihead_attention: unsupported arguments "
            f"{sorted(passed)} (quantized cache paths are not implemented)")
    xt, ct = _t(x), _t(cache_kv)
    exts = []
    if bias is not None:
        exts.append(_t(bias))
    if src_mask is not None:
        exts.append(_t(src_mask))
    if sequence_lengths is not None:
        exts.append(_t(sequence_lengths))
    if rotary_tensor is not None:
        exts.append(_t(rotary_tensor))
    flags = (bias is not None, src_mask is not None,
             sequence_lengths is not None, rotary_tensor is not None)

    def f(xr, cr, *extra):
        it = iter(extra)
        b = next(it) if flags[0] else None
        sm = next(it) if flags[1] else None
        sl = next(it) if flags[2] else None
        rot = next(it) if flags[3] else None
        return kernels.masked_multihead_attention_reference(
            xr, cr, bias=b, src_mask=sm, sequence_lengths=sl,
            rotary_tensor=rot, rotary_emb_dims=rotary_emb_dims,
            use_neox_rotary_style=use_neox_rotary_style)

    return apply_op("masked_multihead_attention", f, xt, ct, *exts)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """matmul + bias in one op (reference incubate fused_matmul_bias;
    XLA fuses the add into the GEMM epilogue)."""
    def f(xr, yr, br):
        a = xr.T if transpose_x else xr
        b = yr.T if transpose_y else yr
        out = a @ b
        return out if br is None else out + br
    return apply_op("fused_matmul_bias", f, _t(x), _t(y),
                    _t(bias) if bias is not None else None)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True, mode="upscale_in_train",
        name=None):
    """(x + bias) -> dropout -> + residual -> LayerNorm, one fused op
    (reference incubate/nn/functional/fused_transformer.py)."""
    from ....nn.functional import dropout as _dropout
    h = _t(x)
    if bias is not None:
        h = h + _t(bias)
    h = _dropout(h, p=dropout_rate, training=training, mode=mode)
    h = h + _t(residual)

    def f(hr, sr, br):
        mu = hr.astype(jnp.float32).mean(-1, keepdims=True)
        var = hr.astype(jnp.float32).var(-1, keepdims=True)
        out = (hr.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + ln_epsilon)
        if sr is not None:
            out = out * sr
        if br is not None:
            out = out + br
        return out.astype(hr.dtype)
    return apply_op("fused_bias_dropout_residual_ln", f, h,
                    _t(ln_scale) if ln_scale is not None else None,
                    _t(ln_bias) if ln_bias is not None else None)


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu", name=None):
    """Expert-choice style fused MoE FFN (reference incubate fused_ec_moe):
    dense per-expert batched matmuls weighted by softmax(gate)."""
    def f(xr, gr, w0, b0, w1, b1):
        B, S, D = xr.shape
        probs = jax.nn.softmax(gr, axis=-1)            # (B, S, E)
        h = jnp.einsum("bsd,edf->bsef", xr, w0) + b0   # (B, S, E, F)
        h = jax.nn.gelu(h) if act_type == "gelu" else jax.nn.relu(h)
        o = jnp.einsum("bsef,efd->bsed", h, w1) + b1   # (B, S, E, D)
        return jnp.einsum("bse,bsed->bsd", probs, o)
    return apply_op("fused_ec_moe", f, _t(x), _t(gate), _t(bmm0_weight),
                    _t(bmm0_bias), _t(bmm1_weight), _t(bmm1_bias))


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0,
                            activation="gelu", training=False,
                            mode="upscale_in_train", ring_id=-1,
                            num_heads=None, name=None):
    """Stacked fused transformer blocks (reference incubate
    fused_multi_transformer): per-layer pre-LN attention + FFN over the
    packed per-layer weight lists."""
    from ....nn.functional import layer_norm as _ln
    from .... import kernels as _kernels

    from ....nn.functional import dropout as _dropout

    h = _t(x)
    L = len(qkv_weights)
    for i in range(L):
        def ln(t, s, b):
            return apply_op(
                "fused_mt_ln",
                lambda tr, sr, br: ((tr.astype(jnp.float32)
                                     - tr.astype(jnp.float32).mean(-1, keepdims=True))
                                    * jax.lax.rsqrt(
                                        tr.astype(jnp.float32).var(-1, keepdims=True)
                                        + epsilon) * sr + br).astype(tr.dtype),
                t, _t(s), _t(b))
        inp = ln(h, ln_scales[i], ln_biases[i]) if pre_layer_norm else h

        def attn(tr, wr, br, ow, ob):
            B, S, D = tr.shape
            qkv = tr @ wr + br                       # (B, S, 3D)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            nh = num_heads if num_heads else (8 if D % 8 == 0 else 1)
            if D % nh:
                raise ValueError(
                    f"embed_dim {D} not divisible by num_heads {nh}")
            hd = D // nh
            q = q.reshape(B, S, nh, hd)
            k = k.reshape(B, S, nh, hd)
            v = v.reshape(B, S, nh, hd)
            sc = jnp.einsum("bsnd,btnd->bnst", q, k) / jnp.sqrt(hd)
            w = jax.nn.softmax(sc, axis=-1)
            o = jnp.einsum("bnst,btnd->bsnd", w, v).reshape(B, S, D)
            return o @ ow + ob
        a = apply_op("fused_mt_attn", attn, inp, _t(qkv_weights[i]),
                     _t(qkv_biases[i]), _t(linear_weights[i]),
                     _t(linear_biases[i]))
        if dropout_rate:
            a = _dropout(a, p=dropout_rate, training=training, mode=mode)
        h = h + a
        if not pre_layer_norm:      # post-LN: normalize AFTER the residual
            h = ln(h, ln_scales[i], ln_biases[i])
        inp2 = ln(h, ffn_ln_scales[i], ffn_ln_biases[i]) if pre_layer_norm \
            else h

        def ffn(tr, w1, b1, w2, b2):
            m = tr @ w1 + b1
            m = jax.nn.gelu(m) if activation == "gelu" else jax.nn.relu(m)
            return m @ w2 + b2
        f = apply_op("fused_mt_ffn", ffn, inp2, _t(ffn1_weights[i]),
                     _t(ffn1_biases[i]), _t(ffn2_weights[i]),
                     _t(ffn2_biases[i]))
        if dropout_rate:
            f = _dropout(f, p=dropout_rate, training=training, mode=mode)
        h = h + f
        if not pre_layer_norm:
            h = ln(h, ffn_ln_scales[i], ffn_ln_biases[i])
    return h
