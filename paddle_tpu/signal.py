"""paddle.signal — frame / overlap_add / stft / istft.

Reference: `python/paddle/signal.py:30,145,246,425`.  The reference lowers
frame/overlap_add to dedicated kernels and stft to its fft_r2c/fft_c2c ops;
here framing is a strided gather, overlap-add is a segment-sum scatter, and
the DFT is `paddle_tpu.fft` (XLA FFT HLO).  Everything is jit-able and
differentiable; batch axes shard under GSPMD.

Shape conventions match the reference exactly:
  frame(axis=-1):   [..., seq_len]              -> [..., frame_length, n_frames]
  frame(axis=0):    [seq_len, ...]              -> [n_frames, frame_length, ...]
  overlap_add(-1):  [..., frame_length, n_frames] -> [..., seq_len]
  stft:             [B?, seq_len] -> [B?, n_fft//2+1 (or n_fft), n_frames]
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import fft as _fft
from .fft import _apply_fft_op, _device_fft
from .tensor import Tensor, apply_op, to_tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice into (overlapping) frames (reference signal.py:30)."""
    x = _t(x)
    if axis not in (0, -1):
        raise ValueError(f"Unexpected axis: {axis}. It should be 0 or -1.")
    if not 0 < frame_length:
        raise ValueError(f"frame_length should be > 0, got {frame_length}")
    if not 0 < hop_length:
        raise ValueError(f"hop_length should be > 0, got {hop_length}")
    seq_len = x.shape[-1] if axis == -1 else x.shape[0]
    if frame_length > seq_len:
        raise ValueError(
            f"Attribute frame_length should be less equal than sequence "
            f"length, but got ({frame_length}) > ({seq_len}).")
    n_frames = 1 + (seq_len - frame_length) // hop_length

    def f(a):
        starts = jnp.arange(n_frames) * hop_length
        offs = jnp.arange(frame_length)
        if axis == -1:
            # idx[t, f] -> frame f at time-offset t: output (..., L, F)
            idx = starts[None, :] + offs[:, None]
            return a[..., idx]
        idx = starts[:, None] + offs[None, :]   # (F, L): output (F, L, ...)
        return a[idx]

    return apply_op("frame", f, x)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Reconstruct from overlapping frames (reference signal.py:145)."""
    x = _t(x)
    if axis not in (0, -1):
        raise ValueError(f"Unexpected axis: {axis}. It should be 0 or -1.")
    if x.ndim < 2:
        raise ValueError("overlap_add expects an input of rank >= 2, got "
                         f"rank {x.ndim}")
    if hop_length <= 0:
        raise ValueError(f"hop_length should be > 0, got {hop_length}")
    if axis == -1:
        frame_length, n_frames = x.shape[-2], x.shape[-1]
    else:
        n_frames, frame_length = x.shape[0], x.shape[1]
    seq_len = (n_frames - 1) * hop_length + frame_length

    def f(a):
        if axis == -1:
            fr = jnp.moveaxis(a, -1, -2)            # (..., F, L)
            batch = a.shape[:-2]
        else:
            fr = jnp.moveaxis(a, (0, 1), (-2, -1))  # (..., F, L)
            batch = a.shape[2:]
        starts = jnp.arange(n_frames) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]  # (F, L)
        out = jnp.zeros(batch + (seq_len,), a.dtype)
        out = out.at[..., idx].add(fr)
        if axis == 0:
            out = jnp.moveaxis(out, -1, 0)          # (seq_len, ...)
        return out

    return apply_op("overlap_add", f, x)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform (reference signal.py:246)."""
    x = _t(x)
    if x.ndim not in (1, 2):
        raise ValueError(f"x should be a 1D or 2D tensor, got rank {x.ndim}")
    squeeze = x.ndim == 1
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if hop_length <= 0:
        raise ValueError(f"hop_length should be > 0, got {hop_length}")
    if not 0 < n_fft <= x.shape[-1] + (n_fft if center else 0):
        raise ValueError(f"n_fft should be in (0, seq_length"
                         f"({x.shape[-1]})], but got {n_fft}.")
    if not 0 < win_length <= n_fft:
        raise ValueError(f"win_length should be in (0, n_fft({n_fft})], "
                         f"but got {win_length}.")
    is_cplx = jnp.issubdtype(x._data.dtype, jnp.complexfloating)
    if is_cplx and onesided:
        raise ValueError("onesided should be False when input or window is "
                         "a complex Tensor.")
    if window is not None:
        wraw = _t(window)._data
        if wraw.ndim != 1 or wraw.shape[0] != win_length:
            raise ValueError(
                f"expected a 1D window tensor of size equal to win_length"
                f"({win_length}), but got window with shape {wraw.shape}.")
    else:
        wraw = jnp.ones((win_length,), jnp.float64
                        if x._data.dtype in (jnp.float64, jnp.complex128)
                        else jnp.float32)
    if win_length < n_fft:
        lp = (n_fft - win_length) // 2
        wraw = jnp.pad(wraw, (lp, n_fft - win_length - lp))
    if center and pad_mode not in ("constant", "reflect"):
        raise ValueError('pad_mode should be "reflect" or "constant", but '
                         f'got "{pad_mode}".')
    norm = "ortho" if normalized else "backward"

    def f(a, w):
        if squeeze:
            a = a[None, :]
        if center:
            p = n_fft // 2
            a = jnp.pad(a, [(0, 0), (p, p)], mode=pad_mode)
        n_frames = 1 + (a.shape[-1] - n_fft) // hop_length
        starts = jnp.arange(n_frames) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = a[:, idx] * w                      # (B, F, n_fft)
        if is_cplx or jnp.issubdtype(w.dtype, jnp.complexfloating):
            spec = _device_fft(
                "stft",
                lambda fr: jnp.fft.fft(fr, axis=-1, norm=norm),
                lambda h: np.fft.fft(h, axis=-1, norm=norm), frames)
            if onesided:
                spec = spec[..., : n_fft // 2 + 1]
        elif onesided:
            spec = _device_fft(
                "stft",
                lambda fr: jnp.fft.rfft(fr, axis=-1, norm=norm),
                lambda h: np.fft.rfft(h, axis=-1, norm=norm), frames)
        else:
            spec = _device_fft(
                "stft",
                lambda fr: jnp.fft.fft(_fft._promote_c(fr), axis=-1,
                                       norm=norm),
                lambda h: np.fft.fft(h, axis=-1, norm=norm), frames)
        out = jnp.swapaxes(spec, -1, -2)            # (B, fft_bins, F)
        return out[0] if squeeze else out

    return _apply_fft_op("stft", f, x, to_tensor(wraw))


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT (reference signal.py:425)."""
    x = _t(x)
    if x.ndim not in (2, 3):
        raise ValueError(f"x should be a 2D or 3D tensor, got rank {x.ndim}")
    squeeze = x.ndim == 2
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if not 0 < hop_length:
        raise ValueError(f"hop_length should be > 0, got {hop_length}")
    if not 0 < win_length <= n_fft:
        raise ValueError(f"win_length should be in (0, n_fft({n_fft})], "
                         f"but got {win_length}.")
    fft_size, n_frames = x.shape[-2], x.shape[-1]
    if onesided and fft_size != n_fft // 2 + 1:
        raise ValueError(f"fft_size should be equal to n_fft // 2 + 1"
                         f"({n_fft // 2 + 1}) when onesided is True, but got "
                         f"{fft_size}.")
    if not onesided and fft_size != n_fft:
        raise ValueError(f"fft_size should be equal to n_fft({n_fft}) when "
                         f"onesided is False, but got {fft_size}.")
    if return_complex and onesided:
        raise ValueError("onesided should be False when input(output of "
                         "istft) or window is a complex Tensor.")
    if window is not None:
        wraw = _t(window)._data
        if wraw.ndim != 1 or wraw.shape[0] != win_length:
            raise ValueError(
                f"expected a 1D window tensor of size equal to win_length"
                f"({win_length}), but got window with shape {wraw.shape}.")
    else:
        wdt = jnp.float64 if x._data.dtype == jnp.complex128 else jnp.float32
        wraw = jnp.ones((win_length,), wdt)
    if not return_complex and jnp.issubdtype(wraw.dtype,
                                             jnp.complexfloating):
        raise ValueError("Data type of window should not be complex when "
                         "return_complex is False.")
    if win_length < n_fft:
        lp = (n_fft - win_length) // 2
        wraw = jnp.pad(wraw, (lp, n_fft - win_length - lp))
    norm = "ortho" if normalized else "backward"

    def f(a, w):
        if squeeze:
            a = a[None]
        fr = jnp.swapaxes(a, -1, -2)                # (B, F, fft_bins)
        if return_complex:
            seg = _device_fft(
                "istft", lambda v: jnp.fft.ifft(v, axis=-1, norm=norm),
                lambda h: np.fft.ifft(h, axis=-1, norm=norm), fr)
        else:
            if not onesided:
                fr = fr[..., : n_fft // 2 + 1]
            seg = _device_fft(
                "istft",
                lambda v: jnp.fft.irfft(v, n=n_fft, axis=-1, norm=norm),
                lambda h: np.fft.irfft(h, n=n_fft, axis=-1, norm=norm), fr)
        seg = seg * w                               # (B, F, n_fft)
        starts = jnp.arange(n_frames) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        seq_len = (n_frames - 1) * hop_length + n_fft
        # on complex-less backends seg is a CPU-committed complex array and
        # a default-device zeros would recreate the UNIMPLEMENTED crash this
        # module routes around — build the accumulator on seg's device
        out_shape = seg.shape[:-2] + (seq_len,)
        if (jnp.issubdtype(seg.dtype, jnp.complexfloating)
                and not _fft._complex_ok()
                and not isinstance(seg, jax.core.Tracer)):
            out = jax.device_put(np.zeros(out_shape, seg.dtype),
                                 list(seg.devices())[0])
        else:
            out = jnp.zeros(out_shape, seg.dtype)
        out = out.at[..., idx].add(seg)
        env = jnp.zeros((seq_len,), w.dtype)
        env = env.at[idx].add(jnp.broadcast_to(w * w, (n_frames, n_fft)))
        if length is None:
            if center:
                out = out[..., n_fft // 2: -(n_fft // 2)]
                env = env[n_fft // 2: -(n_fft // 2)]
        else:
            start = n_fft // 2 if center else 0
            out = out[..., start: start + length]
            env = env[start: start + length]
        out = out / jnp.where(jnp.abs(env) < 1e-11, 1.0, env)
        return out[0] if squeeze else out

    return _apply_fft_op("istft", f, x, to_tensor(wraw))
