"""paddle.profiler parity (reference: python/paddle/profiler/profiler.py:349
Profiler with CLOSED/READY/RECORD/RECORD_AND_RETURN scheduler states,
RecordEvent spans, export_chrome_tracing, profiler_statistic summaries,
timer.py ips benchmark; SURVEY.md C40).

TPU-native: device tracing is jax.profiler (XPlane -> TensorBoard/Perfetto),
host spans are jax.profiler.TraceAnnotation + a light host-event recorder that
feeds the chrome-trace exporter and the summary table.  CUPTI's role is played
by XLA's built-in instrumentation — nothing to dynload.
"""

from __future__ import annotations

import contextlib
import enum
import json
import os
import threading
import time
from typing import Callable, List, Optional

from .timer import Timer  # noqa: F401

_global_timer = Timer()

from . import utils  # noqa: E402,F401
from .utils import (  # noqa: E402,F401
    RecordEvent, benchmark, static_cost, static_memory,
)


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class SortedKeys(enum.Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Reference make_scheduler: step_num -> state machine."""
    cycle = closed + ready + record

    def fn(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return fn


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready handler writing chrome://tracing JSON."""

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_time_{int(time.time())}.json")
        prof._export_chrome(path)
        prof._last_export = path

    return handler


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    # the TPU-native "protobuf" is the XPlane dump jax.profiler writes —
    # construct the Profiler with xplane=True or the dump is skipped
    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        prof._last_export = dir_name

    return handler


class Profiler:
    """Scheduler-driven profiler (profiler.py:349).

    targets are advisory (XLA traces whatever backend runs); `timer_only=True`
    reproduces the lightweight ips benchmark mode.

    `xplane=True` additionally captures a jax.profiler XPlane dump per
    RECORD window (the device timeline for export_protobuf).  Off by
    default: stop_trace serializes metadata for EVERY executable alive in
    the process, which in a long-lived session costs tens of seconds and
    the chrome export reads only the host-event ring anyway."""

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False,
                 xplane=False):
        if scheduler is None:
            self._scheduler = lambda step: ProfilerState.RECORD
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self._scheduler = (lambda step: ProfilerState.RECORD_AND_RETURN
                               if step == end - 1 else (
                                   ProfilerState.RECORD
                                   if start <= step < end
                                   else ProfilerState.CLOSED))
        else:
            self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self._xplane = bool(xplane)
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._jax_tracing = False
        self._tmpdir = None
        self._last_export = None
        self.timer = Timer()

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self.timer.begin()
        self._transition(self._scheduler(self._step))
        return self

    def stop(self):
        if self._state in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN):
            self._stop_record()
            if self._on_trace_ready:
                self._on_trace_ready(self)
        self._state = ProfilerState.CLOSED

    def step(self, num_samples: Optional[int] = None):
        self.timer.step(num_samples)
        prev = self._state
        self._step += 1
        # mark AFTER the increment: the marker opens step lane N for the
        # spans that follow, so the chrome export shows per-step lanes
        # instead of one flat track
        from .utils import _host_events

        _host_events.step_mark(self._step)
        new = self._scheduler(self._step)
        if prev in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) and \
                (prev is ProfilerState.RECORD_AND_RETURN
                 or new is ProfilerState.CLOSED):
            self._stop_record()
            if self._on_trace_ready:
                self._on_trace_ready(self)
        self._transition(new)

    def _transition(self, state: ProfilerState):
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) \
                and not self._jax_tracing and not self.timer_only:
            self._start_record()
        self._state = state

    def _start_record(self):
        from ..obs import trace as obs_trace
        from .utils import _host_events

        # remember whether someone else (engine tracing, ObsCallback) had
        # the shared spine on: leaving RECORD must restore their switch,
        # not silence them
        self._tracer_was_enabled = obs_trace.get_tracer().enabled
        if not self._tracer_was_enabled:
            _host_events.clear()    # fresh profiler session owns the ring
        _host_events.enable()
        if self._jax_tracing or not self._xplane:
            return
        try:
            import tempfile

            import jax

            self._tmpdir = tempfile.mkdtemp(prefix="paddle_tpu_prof_")
            jax.profiler.start_trace(self._tmpdir)
            self._jax_tracing = True
        except Exception:  # noqa: BLE001 — host events still collected
            self._jax_tracing = False

    def _stop_record(self):
        from .utils import _host_events

        if not getattr(self, "_tracer_was_enabled", False):
            _host_events.disable()
        if self._jax_tracing:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass
            self._jax_tracing = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- export / summary --------------------------------------------------
    def _export_chrome(self, path):
        # the obs tracer IS the host-event store: export its ring (span
        # nesting, step lanes, engine spans if serving shares the spine)
        from ..obs import trace as obs_trace

        obs_trace.get_tracer().export_chrome(path, extra={
            "note": ("device timeline lives in the jax.profiler "
                     "XPlane dump"),
            "xplane_dir": self._tmpdir})
        return path

    def export(self, path, format="json"):
        return self._export_chrome(path)

    def summary(self, sorted_by=SortedKeys.CPUTotal, op_detail=True,
                thread_sep=False, time_unit="ms"):
        """Aggregated host-span table (profiler_statistic.py analog)."""
        from .utils import _host_events

        agg = {}
        for e in _host_events.events:
            a = agg.setdefault(e.name, [0.0, 0, 0.0, float("inf")])
            dur = (e.t1 - e.t0) * 1e3
            a[0] += dur
            a[1] += 1
            a[2] = max(a[2], dur)
            a[3] = min(a[3], dur)
        rows = sorted(agg.items(), key=lambda kv: -kv[1][0])
        unit = {"ms": 1.0, "us": 1e3, "s": 1e-3}[time_unit]
        lines = [f"{'Name':40}  {'Calls':>6}  {'Total(' + time_unit + ')':>12}"
                 f"  {'Avg':>10}  {'Max':>10}  {'Min':>10}"]
        for name, (tot, n, mx, mn) in rows:
            lines.append(f"{name[:40]:40}  {n:>6}  {tot * unit:>12.3f}"
                         f"  {tot / n * unit:>10.3f}  {mx * unit:>10.3f}"
                         f"  {mn * unit:>10.3f}")
        table = "\n".join(lines)
        print(table)
        return table


def profiler_pure(*a, **k):  # pragma: no cover — reference-internal helper
    raise NotImplementedError


def load_profiler_result(filename: str):
    with open(filename) as f:
        return json.load(f)


class SummaryView:
    """Reference profiler/profiler.py SummaryView constants (which summary
    tables summary() prints)."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8



