"""RecordEvent + host event recorder (reference: profiler/utils.py:38
RecordEvent over C++ HostEventRecorder).

The recorder is an adapter over the paddle_tpu.obs span tracer — the
profiler, the LLMEngine, and the hapi ObsCallback all record into ONE
event spine, so a single chrome export interleaves training spans with
serving spans.  RecordEvent additionally opens jax.named_scope so span
names land inside the XLA HLO metadata and the device profile."""

from __future__ import annotations

import contextlib
import functools
import time

from ..obs import trace as _obs_trace


class _HostEvent:
    __slots__ = ("name", "t0", "t1", "tid")

    def __init__(self, name, t0, t1, tid):
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.tid = tid


class _HostEventRecorder:
    """Back-compat shim: the historical `_host_events` surface
    (enable/disable/clear/add/events) now delegates to the process-wide
    obs tracer.  Enabling a Profiler therefore enables the shared
    tracer — by design: one spine, one switch."""

    @property
    def _tracer(self) -> "_obs_trace.Tracer":
        return _obs_trace.get_tracer()

    def enable(self):
        self._tracer.enable()

    def disable(self):
        self._tracer.disable()

    def clear(self):
        self._tracer.clear()

    def add(self, name, t0, t1):
        self._tracer.record(name, t0, t1)

    def step_mark(self, step):
        self._tracer.step_mark(step)

    @property
    def events(self):
        """Complete ("X") spans in the legacy 4-field shape (summary()
        consumes this; step marks are instants and aggregate nowhere)."""
        return [_HostEvent(e.name, e.t0, e.t1, e.tid)
                for e in self._tracer.events() if e.ph == "X"]


_host_events = _HostEventRecorder()


class RecordEvent:
    """Context manager / decorator marking a host span (utils.py:38).

    Inside jit traces it degrades to jax.named_scope so the span name shows
    up in the XLA HLO metadata and the device profile."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._t0 = None
        self._scope = None

    def begin(self):
        self._t0 = time.perf_counter()
        try:
            import jax

            self._scope = jax.named_scope(self.name)
            self._scope.__enter__()
        except Exception:  # noqa: BLE001
            self._scope = None

    def end(self):
        if self._scope is not None:
            self._scope.__exit__(None, None, None)
            self._scope = None
        if self._t0 is not None:
            _host_events.add(self.name, self._t0, time.perf_counter())
            self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapped(*a, **k):
            with RecordEvent(self.name):
                return fn(*a, **k)

        return wrapped


@contextlib.contextmanager
def record_function(name):
    with RecordEvent(name):
        yield


def benchmark():
    """Reference utils.benchmark() — returns the global step Timer."""
    from . import _global_timer

    return _global_timer


def static_cost(fn, *args, top_k: int = 5, **kwargs):
    """Static FLOPs/bytes roll-up of `fn(*args)` from its jaxpr — the
    Graph Doctor's cost pass (analysis/cost.py) surfaced through the
    profiler: {"total_flops", "total_bytes", "top": [heaviest eqns]}.
    Nothing executes; scan trip counts are multiplied in.  Pairs with the
    runtime summary() table: this is the *per-compile* view, that one the
    *per-run* view."""
    from ..analysis import cost as cost_lib

    return cost_lib.estimate(fn, *args, top_k=top_k, **kwargs)


def static_memory(fn, *args, top_k: int = 3, **kwargs):
    """Static peak-live-bytes estimate of `fn(*args)` from its jaxpr —
    the Graph Doctor's memory-liveness walker (analysis/memory.py)
    surfaced through the profiler: {"peak_bytes", "peak_path",
    "args_bytes", "donated_bytes", "out_bytes", "top": [biggest
    liveness points]}.  Donation-aware and attributable to eqn paths;
    the compiled ground truth is `compiled.memory_analysis()`, which the
    HLO lint tier reads — this estimate lands within ~2x of it while
    telling you WHERE the peak is.  Nothing executes."""
    from ..analysis import memory as memory_lib

    return memory_lib.estimate(fn, *args, top_k=top_k, **kwargs)


def wrap_optimizers():  # pragma: no cover — reference hooks optimizer classes
    return None
