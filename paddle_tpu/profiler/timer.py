"""Throughput timer (reference: profiler/timer.py — ips/step statistics
driving the `benchmark()` API)."""

from __future__ import annotations

import time
from typing import Optional


class _Stat:
    def __init__(self):
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0
        self.samples = 0
        self._max = 0.0
        self._min = float("inf")

    def update(self, dt: float, samples: Optional[int]):
        self.total += dt
        self.count += 1
        if samples:
            self.samples += samples
        self._max = max(self._max, dt)
        self._min = min(self._min, dt)

    @property
    def avg(self):
        return self.total / max(self.count, 1)

    @property
    def ips(self):
        if self.total <= 0:
            return 0.0
        base = self.samples if self.samples else self.count
        return base / self.total


class Timer:
    def __init__(self):
        self.reader_cost = _Stat()
        self.batch_cost = _Stat()
        self._last = None
        self._reader_t0 = None

    def begin(self):
        self._last = time.perf_counter()

    def before_reader(self):
        self._reader_t0 = time.perf_counter()

    def after_reader(self):
        if self._reader_t0 is not None:
            self.reader_cost.update(time.perf_counter() - self._reader_t0, None)
            self._reader_t0 = None

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last is not None:
            self.batch_cost.update(now - self._last, num_samples)
        self._last = now

    def step_info(self, unit="samples"):
        bc = self.batch_cost
        return (f"avg batch_cost {bc.avg * 1e3:.2f} ms, "
                f"ips {bc.ips:.2f} {unit}/s")

    @property
    def ips(self):
        return self.batch_cost.ips
