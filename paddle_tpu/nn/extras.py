"""Long-tail nn layer surface (reference python/paddle/nn/layer/
{pooling,norm,activation,loss,rnn}.py remainders + seq2seq decoding)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor, apply_op, to_tensor
from . import functional as F
from .layer import Layer

__all__ = [
    "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool3D",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
    "InstanceNorm3D", "LocalResponseNorm", "Softmax2D", "RReLU", "Silu",
    "GaussianNLLLoss", "HSigmoidLoss", "MultiLabelSoftMarginLoss",
    "MultiMarginLoss", "SoftMarginLoss", "TripletMarginWithDistanceLoss",
    "RNNTLoss", "BeamSearchDecoder", "dynamic_decode",
]


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self._o, self._fmt = output_size, data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self._o, self._fmt)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._o, self._mask = output_size, return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self._o, self._mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._o, self._mask = output_size, return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._o, self._mask)


class _MaxUnPoolND(Layer):
    _nd = 2

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self._k, self._s, self._p = kernel_size, stride, padding
        self._fmt, self._o = data_format, output_size

    def forward(self, x, indices, output_size=None):
        fn = {1: F.max_unpool1d, 2: F.max_unpool2d, 3: F.max_unpool3d}[
            self._nd]
        return fn(x, indices, self._k, self._s, self._p,
                  output_size=output_size or self._o)


class MaxUnPool1D(_MaxUnPoolND):
    _nd = 1


class MaxUnPool2D(_MaxUnPoolND):
    _nd = 2


class MaxUnPool3D(_MaxUnPoolND):
    _nd = 3


class InstanceNorm3D(Layer):
    """Reference nn/layer/norm.py InstanceNorm3D (per-sample, per-channel
    normalization over D/H/W)."""

    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__()
        self._eps = epsilon
        self.scale = self.create_parameter(
            (num_features,), weight_attr,
            default_initializer=__import__(
                "paddle_tpu.nn.initializer", fromlist=["Constant"]
            ).Constant(1.0)) if weight_attr is not False else None
        self.bias = self.create_parameter(
            (num_features,), bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._eps)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self._args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        size, alpha, beta, k, fmt = self._args
        return F.local_response_norm(x, size, alpha=alpha, beta=beta, k=k,
                                     data_format=fmt)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW input (reference
    nn/layer/activation.py Softmax2D)."""

    def forward(self, x):
        if len(x.shape) != 4:
            raise ValueError("Softmax2D expects a 4-D NCHW tensor")
        return F.softmax(x, axis=1)


class RReLU(Layer):
    """Randomized leaky ReLU (reference nn/layer/activation.py RReLU):
    slope ~ U[lower, upper] in training, fixed mean slope in eval."""

    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, lower=self._lower, upper=self._upper,
                       training=self.training)


class Silu(Layer):
    """Alias spelling of SiLU kept by the reference export list."""

    def forward(self, x):
        return F.silu(x)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self._full, self._eps, self._red = full, epsilon, reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, self._full,
                                   self._eps, self._red)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if num_classes < 2:
            raise ValueError("num_classes must be >= 2")
        self._num_classes = num_classes
        from . import initializer as I
        std = 1.0 / np.sqrt(feature_size)
        self.weight = self.create_parameter(
            (num_classes - 1, feature_size), weight_attr,
            default_initializer=I.Uniform(-std, std))
        self.bias = self.create_parameter(
            (num_classes - 1, 1), bias_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self._num_classes, self.weight,
                               self.bias, path_table, path_code)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._w, self._red = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self._w,
                                              self._red)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self._p, self._m, self._w, self._red = p, margin, weight, reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, self._p, self._m, self._w,
                                   self._red)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._red = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self._red)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self._df, self._m = distance_function, margin
        self._swap, self._red = swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self._df, self._m, self._swap,
            self._red)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self._blank, self._fe, self._red = blank, fastemit_lambda, reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           self._blank, self._fe, self._red)


# ---------------------------------------------------------------------------
# seq2seq decoding (reference nn/decode.py BeamSearchDecoder +
# dynamic_decode)
# ---------------------------------------------------------------------------


class BeamSearchDecoder:
    """Beam search over an RNNCellBase (reference nn/decode.py:123).

    cell: a cell whose forward(inputs, states) -> (logits-ish output,
    new_states); output_fn maps cell output to vocab logits;
    embedding_fn maps token ids to cell inputs.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def _embed(self, ids):
        if self.embedding_fn is not None:
            return self.embedding_fn(ids)
        return ids

    def _logits(self, cell_out):
        out = self.output_fn(cell_out) if self.output_fn is not None \
            else cell_out
        return out._data if isinstance(out, Tensor) else jnp.asarray(out)

    def initialize(self, initial_cell_states):
        """Returns (initial_inputs, initial_states, init log-probs)."""
        flat, tree = jax.tree_util.tree_flatten(
            initial_cell_states,
            is_leaf=lambda v: isinstance(v, Tensor))
        B = int(flat[0].shape[0])
        K = self.beam_size
        # tile every state leaf to (B*K, ...)
        tiled = [to_tensor(jnp.repeat(
            (s._data if isinstance(s, Tensor) else jnp.asarray(s)), K,
            axis=0)) for s in flat]
        states = jax.tree_util.tree_unflatten(tree, tiled)
        ids = np.full((B, K), self.start_token, np.int64)
        # beam 0 active, others -inf so step 1 expands a single beam
        logp = np.full((B, K), -1e9, np.float32)
        logp[:, 0] = 0.0
        return ids, states, logp

    def step(self, ids, states, logp):
        """One expansion: returns (new_ids, new_states, new_logp,
        parent_idx, token)."""
        B, K = ids.shape
        inputs = self._embed(to_tensor(ids.reshape(-1)))
        out, new_states = self.cell(inputs, states)
        logits = self._logits(out).reshape(B, K, -1)
        V = logits.shape[-1]
        logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        finished = ids == self.end_token
        # finished beams only extend with end_token at no cost
        mask = jnp.full((V,), -1e9).at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[:, :, None], mask[None, None, :],
                            logprobs)
        total = jnp.asarray(logp)[:, :, None] + step_lp       # (B, K, V)
        flat = total.reshape(B, K * V)
        top_lp, top_ix = jax.lax.top_k(flat, K)
        parent = np.asarray(top_ix // V)
        token = np.asarray(top_ix % V)
        # reorder states by parent beam
        def reorder(s):
            raw = s._data if isinstance(s, Tensor) else jnp.asarray(s)
            r = raw.reshape((B, K) + raw.shape[1:])
            g = jnp.take_along_axis(
                r, jnp.asarray(parent).reshape(
                    (B, K) + (1,) * (r.ndim - 2)), axis=1)
            return to_tensor(g.reshape((-1,) + raw.shape[1:]))
        new_states = jax.tree_util.tree_map(
            reorder, new_states, is_leaf=lambda v: isinstance(v, Tensor))
        return token, new_states, np.asarray(top_lp), parent, token


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Run decoder.initialize + step until all beams emit end_token or
    max_step_num (reference nn/decode.py dynamic_decode).  Returns
    (predicted_ids (B, T, beam) int64, final log-probs) [+ lengths]."""
    if max_step_num is None:
        max_step_num = 64
    ids, states, logp = decoder.initialize(inits)
    B, K = ids.shape
    steps_tok, steps_par = [], []
    for _ in range(int(max_step_num)):
        tok, states, logp, parent, _ = decoder.step(ids, states, logp)
        steps_tok.append(tok)
        steps_par.append(parent)
        ids = tok
        if (tok == decoder.end_token).all():
            break
    T = len(steps_tok)
    # backtrace through parents
    seqs = np.zeros((T, B, K), np.int64)
    beam_idx = np.tile(np.arange(K), (B, 1))
    for t in range(T - 1, -1, -1):
        seqs[t] = np.take_along_axis(steps_tok[t], beam_idx, axis=1)
        beam_idx = np.take_along_axis(steps_par[t], beam_idx, axis=1)
    out = seqs if output_time_major else seqs.transpose(1, 0, 2)
    lengths = np.full((B, K), T, np.int64)
    for b in range(B):
        for k in range(K):
            seq = seqs[:, b, k]
            endpos = np.nonzero(seq == decoder.end_token)[0]
            if endpos.size:
                lengths[b, k] = endpos[0] + 1
    res = (to_tensor(out), to_tensor(np.asarray(logp)))
    if return_length:
        res = res + (to_tensor(lengths),)
    return res
