"""paddle.nn parity namespace."""

from __future__ import annotations

from . import functional  # noqa: F401
from . import utils  # noqa: F401
from . import initializer  # noqa: F401
from .layer import Layer, LayerDict, LayerList, ParamAttr, ParameterList, Sequential  # noqa: F401
from .common import *  # noqa: F401,F403
from .transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder, TransformerDecoderLayer,
    TransformerEncoder, TransformerEncoderLayer,
)
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .rnn import (  # noqa: F401
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN,
    SimpleRNN, LSTM, GRU,
)
# bind the functional forms over the submodule attribute of the same name
from .rnn import rnn, birnn, split_states, concat_states  # noqa: F401
from . import extras as _extras  # noqa: F401
from .extras import *  # noqa: F401,F403
from ..tensor import Parameter  # noqa: F401

from . import common as _common

__all__ = (
    ["Layer", "LayerList", "LayerDict", "ParameterList", "Sequential", "ParamAttr",
     "Parameter", "functional", "initializer", "utils",
     "MultiHeadAttention", "TransformerEncoderLayer", "TransformerEncoder",
     "TransformerDecoderLayer", "TransformerDecoder", "Transformer",
     "ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue",
     "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
     "SimpleRNN", "LSTM", "GRU",
     "rnn", "birnn", "split_states", "concat_states"]
    + list(_common.__all__) + list(_extras.__all__)
)
