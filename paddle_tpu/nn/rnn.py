"""Recurrent networks (python/paddle/nn/layer/rnn.py parity, TPU-native).

Reference surface: rnn (:42), birnn (:354), split_states (:454),
concat_states (:507), RNNCellBase (:549), SimpleRNNCell (:695), LSTMCell
(:837), GRUCell (:1001), RNN (:1160), BiRNN (:1233), RNNBase (:1319),
SimpleRNN/LSTM/GRU (:1635/:1757/:1883).

TPU-first design: the reference unrolls a Python loop over time steps
(one graph node per step, cuDNN fast path on GPU).  Here the whole
recurrence is ONE `lax.scan` recorded as a single tape op — XLA compiles
it to a fused on-device while-loop (weights stay resident in VMEM across
steps, no per-step dispatch), and the vjp is jax's scan-transpose, so a
T-step LSTM costs one tape node instead of ~6T.  Works with ANY
RNNCellBase subclass (including user cells written with eager Tensor
ops): during tracing the cell's Parameters are temporarily pointed at the
traced values, so `cell.forward` becomes a pure function of them.

Variable-length semantics match the reference (:141 _maybe_copy): states
freeze after each row's last valid step; outputs record every step
unmasked; reverse runs flip inputs+mask and flip outputs back.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

from .. import framework
from ..framework import to_jax_dtype
from ..tensor import Tensor, apply_op, to_tensor
from ..ops.manipulation import concat, stack
from . import functional as F
from . import initializer as I
from .layer import Layer, LayerList

__all__ = [
    "rnn", "birnn", "split_states", "concat_states",
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell",
    "RNN", "BiRNN", "SimpleRNN", "LSTM", "GRU",
]

_tensor_leaf = partial(jax.tree_util.tree_flatten,
                       is_leaf=lambda x: isinstance(x, Tensor))


def _flatten(struct):
    leaves, tree = _tensor_leaf(struct)
    return leaves, tree


# ---------------------------------------------------------------------------
# functional rnn / birnn
# ---------------------------------------------------------------------------


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Run `cell` over the time dimension of `inputs` as one lax.scan.

    Returns (outputs, final_states) with the reference's structure:
    outputs mirror the cell's per-step output structure with a time axis
    inserted (axis 0 if time_major else 1); final_states mirror the
    state structure.
    """
    if initial_states is None:
        initial_states = cell.get_initial_states(
            batch_ref=inputs, batch_dim_idx=1 if time_major else 0)

    in_flat, in_tree = _flatten(inputs)
    st_flat, st_tree = _flatten(initial_states)
    params = [p for p in cell.parameters() if p is not None]
    n_in, n_st, n_p = len(in_flat), len(st_flat), len(params)
    has_seq = sequence_length is not None
    if has_seq and not isinstance(sequence_length, Tensor):
        sequence_length = to_tensor(sequence_length, dtype="int32")

    out_box = []  # captured output tree + leaf count from the traced step

    def fn(*flat):
        xs = flat[:n_in]
        sts = flat[n_in:n_in + n_st]
        ps = flat[n_in + n_st:n_in + n_st + n_p]
        seq = flat[-1] if has_seq else None

        xs = [x if time_major else jnp.swapaxes(x, 0, 1) for x in xs]
        T = xs[0].shape[0]
        mask = None
        if seq is not None:
            mask = (jnp.arange(T)[:, None] < seq[None, :]).astype(xs[0].dtype)
        if is_reverse:
            xs = [jnp.flip(x, 0) for x in xs]
            if mask is not None:
                mask = jnp.flip(mask, 0)

        def step(carry, sl):
            xt, mt = sl
            in_t = jax.tree_util.tree_unflatten(
                in_tree, [Tensor(a) for a in xt])
            st_t = jax.tree_util.tree_unflatten(
                st_tree, [Tensor(a) for a in carry])
            with framework.no_grad_guard():
                o_t, ns_t = cell(in_t, st_t, **kwargs)
            o_flat, o_tree = _flatten(o_t)
            ns_flat, _ = _flatten(ns_t)
            if not out_box:
                out_box.append((o_tree, len(o_flat)))
            o_raw = [o._data if isinstance(o, Tensor) else jnp.asarray(o)
                     for o in o_flat]
            ns_raw = [s._data if isinstance(s, Tensor) else jnp.asarray(s)
                      for s in ns_flat]
            if mt is not None:
                ns_raw = [
                    m_ * n + (1 - m_) * o
                    for n, o in zip(ns_raw, carry)
                    for m_ in (mt.reshape((-1,) + (1,) * (n.ndim - 1)),)
                ]
            return tuple(ns_raw), tuple(o_raw)

        old = [p._data for p in params]
        state = framework.get_state()
        cap = state.capture_program  # only the outer "rnn" op belongs in a
        state.capture_program = None  # captured Program, not per-step cells
        try:
            for p, r in zip(params, ps):
                p._data = r
            carry, ys = jax.lax.scan(step, tuple(sts), (tuple(xs), mask))
        finally:
            state.capture_program = cap
            for p, o in zip(params, old):
                p._data = o

        outs = [jnp.flip(y, 0) if is_reverse else y for y in ys]
        outs = [y if time_major else jnp.swapaxes(y, 0, 1) for y in outs]
        return (*outs, *carry)

    args = [*in_flat, *st_flat, *params] + ([sequence_length] if has_seq else [])
    wrapped = apply_op("rnn", fn, *args)
    o_tree, n_o = out_box[0]
    outputs = jax.tree_util.tree_unflatten(o_tree, list(wrapped[:n_o]))
    final_states = jax.tree_util.tree_unflatten(st_tree, list(wrapped[n_o:]))
    return outputs, final_states


def birnn(cell_fw, cell_bw, inputs, initial_states=None, sequence_length=None,
          time_major=False, **kwargs):
    """Bidirectional rnn: concat fw/bw outputs on the last axis.

    Reference: python/paddle/nn/layer/rnn.py:354.
    """
    if initial_states is None:
        states_fw = cell_fw.get_initial_states(
            batch_ref=inputs, batch_dim_idx=1 if time_major else 0)
        states_bw = cell_bw.get_initial_states(
            batch_ref=inputs, batch_dim_idx=1 if time_major else 0)
    else:
        states_fw, states_bw = initial_states
    outputs_fw, states_fw = rnn(cell_fw, inputs, states_fw, sequence_length,
                                time_major, False, **kwargs)
    outputs_bw, states_bw = rnn(cell_bw, inputs, states_bw, sequence_length,
                                time_major, True, **kwargs)
    outputs = jax.tree_util.tree_map(
        lambda a, b: concat([a, b], axis=-1), outputs_fw, outputs_bw,
        is_leaf=lambda x: isinstance(x, Tensor))
    return outputs, (states_fw, states_bw)


# ---------------------------------------------------------------------------
# state (de)multiplexing for stacked/bidirectional nets
# ---------------------------------------------------------------------------


def split_states(states, bidirectional=False, state_components=1):
    """(L*D, B, H) packed states -> per-layer structure.

    Reference: python/paddle/nn/layer/rnn.py:454.  With one component the
    input is a single tensor; otherwise a tuple of `state_components`
    tensors.  Returns a list over layers; each element is the cell-state
    structure, wrapped in an (fw, bw) pair when bidirectional.
    """
    if state_components == 1:
        items = [states[i] for i in range(states.shape[0])]
    else:
        comps = [[c[i] for i in range(c.shape[0])] for c in states]
        items = [tuple(c[i] for c in comps) for i in range(len(comps[0]))]
    if not bidirectional:
        return items
    return [(items[2 * i], items[2 * i + 1]) for i in range(len(items) // 2)]


def concat_states(states, bidirectional=False, state_components=1):
    """Inverse of split_states.  Reference: rnn.py:507."""
    flat = []
    for st in states:
        if bidirectional:
            flat.extend([st[0], st[1]])
        else:
            flat.append(st)
    if state_components == 1:
        return stack(list(flat), axis=0)
    return tuple(stack([f[c] for f in flat], axis=0)
                 for c in range(state_components))


def _param_dtype(layer):
    for p in layer.parameters():
        if p is not None:
            return p.dtype
    return framework.get_default_dtype()


# ---------------------------------------------------------------------------
# cells
# ---------------------------------------------------------------------------


class RNNCellBase(Layer):
    """Base for single-step recurrent cells (reference rnn.py:549)."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        refs, _ = _flatten(batch_ref)
        batch = refs[0].shape[batch_dim_idx]
        shape = self.state_shape if shape is None else shape
        dtype = self.state_dtype if dtype is None else dtype
        jd = to_jax_dtype(framework.convert_dtype(dtype))

        def is_leaf_shape(s):
            return (isinstance(s, (tuple, list))
                    and all(isinstance(e, int) for e in s))

        def mk(s):
            s = list(s)
            if -1 in s:
                s[s.index(-1)] = batch
            else:
                s = [batch] + s
            return Tensor(jnp.full(tuple(s), init_value, dtype=jd),
                          stop_gradient=True)

        if is_leaf_shape(shape):
            return mk(shape)
        return jax.tree_util.tree_map(mk, shape, is_leaf=is_leaf_shape)

    @property
    def state_shape(self):
        raise NotImplementedError(
            "Please add implementation for `state_shape` in the used cell.")

    @property
    def state_dtype(self):
        return _param_dtype(self)

    def call(self, *args, **kwargs):
        return self.forward(*args, **kwargs)


class SimpleRNNCell(RNNCellBase):
    r"""h' = act(x W_ih^T + b_ih + h W_hh^T + b_hh).  Reference rnn.py:695."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError(
                f"hidden_size of {type(self).__name__} must be greater "
                f"than 0, but now equals to {hidden_size}")
        if activation not in ("tanh", "relu"):
            raise ValueError(f"activation for {type(self).__name__} should "
                             f"be tanh or relu, but got {activation}")
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            (hidden_size, input_size), weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter(
            (hidden_size, hidden_size), weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter(
            (hidden_size,), bias_ih_attr, is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter(
            (hidden_size,), bias_hh_attr, is_bias=True, default_initializer=u)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        h = F.simple_rnn_cell(inputs, states, self.weight_ih, self.weight_hh,
                              self.bias_ih, self.bias_hh,
                              activation=self.activation)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    r"""Gates [i, f, g, o]; c' = f⊙c + i⊙tanh(g); h' = o⊙tanh(c').

    Reference rnn.py:837.
    """

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError(
                f"hidden_size of {type(self).__name__} must be greater "
                f"than 0, but now equals to {hidden_size}")
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            (4 * hidden_size, input_size), weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            (4 * hidden_size, hidden_size), weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            (4 * hidden_size,), bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            (4 * hidden_size,), bias_hh_attr, is_bias=True,
            default_initializer=u)
        self.input_size = input_size
        self.hidden_size = hidden_size

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        pre_h, pre_c = states
        h, c = F.lstm_cell(inputs, pre_h, pre_c, self.weight_ih,
                           self.weight_hh, self.bias_ih, self.bias_hh)
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    r"""Gates [r, z, c]; h' = z⊙h + (1-z)⊙tanh(x_c + r⊙h_c).

    Reference rnn.py:1001.
    """

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError(
                f"hidden_size of {type(self).__name__} must be greater "
                f"than 0, but now equals to {hidden_size}")
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            (3 * hidden_size, input_size), weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            (3 * hidden_size, hidden_size), weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            (3 * hidden_size,), bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            (3 * hidden_size,), bias_hh_attr, is_bias=True,
            default_initializer=u)
        self.input_size = input_size
        self.hidden_size = hidden_size

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        h = F.gru_cell(inputs, states, self.weight_ih, self.weight_hh,
                       self.bias_ih, self.bias_hh)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


# ---------------------------------------------------------------------------
# sequence wrappers
# ---------------------------------------------------------------------------


class RNN(Layer):
    """Wrap a cell into a sequence layer (reference rnn.py:1160)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        return rnn(self.cell, inputs, initial_states, sequence_length,
                   self.time_major, self.is_reverse, **kwargs)


class BiRNN(Layer):
    """Forward + backward cells, outputs concatenated (reference rnn.py:1233)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        if cell_fw.input_size != cell_bw.input_size:
            raise ValueError(
                "input size of forward and backward cells should be equal, "
                f"but got {cell_fw.input_size} and {cell_bw.input_size}")
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if isinstance(initial_states, (list, tuple)) \
                and len(initial_states) != 2:
            raise ValueError("initial_states should be a (fw, bw) pair")
        return birnn(self.cell_fw, self.cell_bw, inputs, initial_states,
                     sequence_length, self.time_major, **kwargs)


# ---------------------------------------------------------------------------
# multi-layer nets
# ---------------------------------------------------------------------------


class RNNBase(LayerList):
    """Stacked (optionally bidirectional) recurrent net (reference rnn.py:1319).

    The reference has a cuDNN fast path + a Python composition fallback;
    on TPU there is one path: each layer is a scan (see `rnn` above) and
    XLA fuses the stack.
    """

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        bidirectional_list = ["bidirectional", "bidirect"]
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.dropout = dropout
        self.num_directions = 2 if direction in bidirectional_list else 1
        self.time_major = time_major
        self.num_layers = num_layers
        self.state_components = 2 if mode == "LSTM" else 1

        kwargs = {
            "weight_ih_attr": weight_ih_attr,
            "weight_hh_attr": weight_hh_attr,
            "bias_ih_attr": bias_ih_attr,
            "bias_hh_attr": bias_hh_attr,
        }
        if mode == "LSTM":
            rnn_cls = LSTMCell
        elif mode == "GRU":
            rnn_cls = GRUCell
        elif mode in ("RNN_TANH", "RNN_RELU"):
            rnn_cls = partial(SimpleRNNCell,
                              activation=mode[4:].lower())
        else:
            raise ValueError(f"Unknown mode {mode!r}")

        if direction == "forward":
            for i in range(num_layers):
                in_sz = input_size if i == 0 else hidden_size
                cell = rnn_cls(in_sz, hidden_size, **kwargs)
                self.append(RNN(cell, time_major=time_major))
        elif direction in bidirectional_list:
            for i in range(num_layers):
                in_sz = input_size if i == 0 else 2 * hidden_size
                cell_fw = rnn_cls(in_sz, hidden_size, **kwargs)
                cell_bw = rnn_cls(in_sz, hidden_size, **kwargs)
                self.append(BiRNN(cell_fw, cell_bw, time_major=time_major))
        else:
            raise ValueError(
                "direction should be forward or bidirect (or bidirectional), "
                f"received direction = {direction}")

    def forward(self, inputs, initial_states=None, sequence_length=None):
        batch_index = 1 if self.time_major else 0
        batch = inputs.shape[batch_index]
        dtype = self.state_dtype
        jd = to_jax_dtype(framework.convert_dtype(dtype))
        packed_shape = (self.num_layers * self.num_directions, batch,
                        self.hidden_size)
        if initial_states is None:
            zeros = [Tensor(jnp.zeros(packed_shape, dtype=jd),
                            stop_gradient=True)
                     for _ in range(self.state_components)]
            initial_states = zeros[0] if self.state_components == 1 \
                else tuple(zeros)
        states = split_states(initial_states, self.num_directions == 2,
                              self.state_components)
        out = inputs
        final = []
        for i, layer in enumerate(self):
            if i > 0 and self.dropout > 0.0:
                out = F.dropout(out, self.dropout, training=self.training)
            out, st = layer(out, states[i], sequence_length)
            final.append(st)
        final_states = concat_states(final, self.num_directions == 2,
                                     self.state_components)
        return out, final_states

    @property
    def state_dtype(self):
        return _param_dtype(self)


class SimpleRNN(RNNBase):
    """Multi-layer Elman RNN (reference rnn.py:1635)."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        if activation == "tanh":
            mode = "RNN_TANH"
        elif activation == "relu":
            mode = "RNN_RELU"
        else:
            raise ValueError(f"Unknown activation '{activation}'")
        self.activation = activation
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)


class LSTM(RNNBase):
    """Multi-layer LSTM (reference rnn.py:1757)."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class GRU(RNNBase):
    """Multi-layer GRU (reference rnn.py:1883)."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)
