"""Gradient clipping (python/paddle/nn/clip.py parity)."""

from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError

    # functional form used by the jitted train step: grads is a flat list of raw
    # arrays; returns clipped raws. Eager path wraps this.
    def clip_raw(self, raw_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def clip_raw(self, raw_grads):
        return [None if g is None else jnp.clip(g, self.min, self.max) for g in raw_grads]

    def __call__(self, params_grads):
        raws = self.clip_raw([g._data if g is not None else None for _, g in params_grads])
        return [(p, None if r is None else Tensor(r)) for (p, _), r in zip(params_grads, raws)]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def clip_raw(self, raw_grads):
        out = []
        for g in raw_grads:
            if g is None:
                out.append(None)
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((g.astype(jnp.float32) * scale).astype(g.dtype))
        return out

    def __call__(self, params_grads):
        raws = self.clip_raw([g._data if g is not None else None for _, g in params_grads])
        return [(p, None if r is None else Tensor(r)) for (p, _), r in zip(params_grads, raws)]


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip.  In hybrid-parallel runs the per-axis partial norms are
    combined by the distributed optimizer (HybridParallelOptimizer analog,
    fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py) — under
    GSPMD this falls out automatically because grads are global arrays."""

    def __init__(self, clip_norm=1.0, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = clip_norm

    def clip_raw(self, raw_grads):
        sq = [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in raw_grads if g is not None]
        if not sq:
            return raw_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [None if g is None else (g.astype(jnp.float32) * scale).astype(g.dtype) for g in raw_grads]

    def __call__(self, params_grads):
        raws = self.clip_raw([g._data if g is not None else None for _, g in params_grads])
        return [(p, None if r is None else Tensor(r)) for (p, _), r in zip(params_grads, raws)]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = [p for p in (parameters if isinstance(parameters, (list, tuple)) else [parameters]) if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros(()))
    norms = [jnp.linalg.norm(p.grad._data.reshape(-1).astype(jnp.float32), ord=norm_type) for p in params]
    total = jnp.linalg.norm(jnp.stack(norms), ord=norm_type)
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        p.grad._data = (p.grad._data.astype(jnp.float32) * scale).astype(p.grad._data.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    for p in (parameters if isinstance(parameters, (list, tuple)) else [parameters]):
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)
