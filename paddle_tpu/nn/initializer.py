"""Weight initializers (python/paddle/nn/initializer/ parity)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..framework import to_jax_dtype


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(shape), self.value, dtype=to_jax_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        k = framework.next_rng_key()
        return jax.random.normal(k, tuple(shape), dtype=to_jax_dtype(dtype)) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        k = framework.next_rng_key()
        return (
            jax.random.truncated_normal(k, -2.0, 2.0, tuple(shape), dtype=to_jax_dtype(dtype)) * self.std
            + self.mean
        )


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        k = framework.next_rng_key()
        return jax.random.uniform(k, tuple(shape), dtype=to_jax_dtype(dtype), minval=self.low, maxval=self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        k = framework.next_rng_key()
        return jax.random.normal(k, tuple(shape), dtype=to_jax_dtype(dtype)) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        k = framework.next_rng_key()
        return jax.random.uniform(k, tuple(shape), dtype=to_jax_dtype(dtype), minval=-limit, maxval=limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope = fan_in, negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        std = gain / math.sqrt(fi)
        k = framework.next_rng_key()
        return jax.random.normal(k, tuple(shape), dtype=to_jax_dtype(dtype)) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope = fan_in, negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        limit = gain * math.sqrt(3.0 / fi)
        k = framework.next_rng_key()
        return jax.random.uniform(k, tuple(shape), dtype=to_jax_dtype(dtype), minval=-limit, maxval=limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ..tensor import Tensor

        v = self.value._data if isinstance(self.value, Tensor) else jnp.asarray(self.value)
        return v.astype(to_jax_dtype(dtype)).reshape(tuple(shape))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        k = framework.next_rng_key()
        return jax.nn.initializers.orthogonal(scale=self.gain)(k, tuple(shape), to_jax_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        return jax.nn.initializers.delta_orthogonal()(framework.next_rng_key(), tuple(shape), to_jax_dtype(dtype))


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0, "conv3d": 1.0,
        "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    return gains[nonlinearity]


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


_global_weight_init = None
_global_bias_init = None


class Bilinear(Initializer):
    """Bilinear-upsample kernel init for transposed conv (reference
    nn/initializer/Bilinear.py): weight shape (C_out, C_in, k, k)."""

    def __call__(self, shape, dtype="float32"):
        import numpy as np
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D shape")
        k = shape[-1]
        if shape[-2] != k:
            raise ValueError("Bilinear initializer expects square kernels")
        f = int(np.ceil(k / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        og = np.ogrid[:k, :k]
        filt = ((1 - np.abs(og[0] / f - c))
                * (1 - np.abs(og[1] / f - c))).astype(np.float32)
        w = np.zeros(shape, np.float32)
        for i in range(shape[0]):
            for j in range(shape[1]):
                w[i, j] = filt
        from .. import framework
        return jnp.asarray(w).astype(
            framework.to_jax_dtype(framework.convert_dtype(dtype)))



