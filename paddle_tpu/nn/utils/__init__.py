"""paddle.nn.utils (reference python/paddle/nn/utils/): weight/spectral
norm reparameterizations, grad clipping helpers, parameter<->vector."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...tensor import Parameter, Tensor, apply_op, to_tensor

__all__ = [
    "weight_norm", "remove_weight_norm", "spectral_norm",
    "clip_grad_norm_", "clip_grad_value_",
    "parameters_to_vector", "vector_to_parameters",
]


def _norm_except(w, dim):
    """L2 norm over all dims except `dim` (paddle weight_norm convention)."""
    if dim is None:
        return jnp.sqrt((w * w).sum())
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt((w * w).sum(axes, keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize `layer.name` as g * v/||v|| (reference
    nn/utils/weight_norm_hook.py).  Registers `name`_g / `name`_v
    Parameters and a pre-forward hook that rebuilds `name` from them."""
    w = getattr(layer, name)
    raw = w._data
    g = Parameter(np.asarray(_norm_except(raw, dim)))
    v = Parameter(np.asarray(raw))
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    # the base weight is now derived — drop it from the parameter store
    layer._parameters.pop(name, None)

    def hook(lyr, inputs):
        # taped op: grads flow to g and v through the derived weight
        derived = apply_op(
            "weight_norm",
            lambda vr, gr: vr * (gr / jnp.maximum(_norm_except(vr, dim),
                                                  1e-12)),
            v, g)
        object.__setattr__(lyr, name, derived)
        return None

    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_state = (name, dim, handle)
    hook(layer, None)       # make `name` available immediately
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g * v/||v|| back into a plain Parameter (reference
    remove_weight_norm)."""
    state = getattr(layer, "_weight_norm_state", None)
    if state is None or state[0] != name:
        raise ValueError(f"weight_norm was not applied to '{name}'")
    _, dim, handle = state
    g = getattr(layer, name + "_g")
    v = getattr(layer, name + "_v")
    n = _norm_except(v._data, dim)
    w = Parameter(np.asarray(v._data * (g._data / jnp.maximum(n, 1e-12))))
    handle.remove()
    layer._parameters.pop(name + "_g", None)
    layer._parameters.pop(name + "_v", None)
    object.__delattr__(layer, name) if name in layer.__dict__ else None
    layer.add_parameter(name, w)
    del layer._weight_norm_state
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Divide the weight by its largest singular value, estimated by power
    iteration on each forward (reference nn/utils/spectral_norm_hook.py)."""
    w = getattr(layer, name)
    raw = w._data
    if dim is None:
        dim = 1 if type(layer).__name__.endswith("Transpose") else 0
    mat = jnp.moveaxis(raw, dim, 0).reshape(raw.shape[dim], -1)
    rng = np.random.default_rng(0)
    u0 = rng.standard_normal(mat.shape[0]).astype(np.float32)
    layer.register_buffer(name + "_u",
                          to_tensor(u0 / (np.linalg.norm(u0) + eps)))
    orig = Parameter(np.asarray(raw))
    layer.add_parameter(name + "_orig", orig)
    layer._parameters.pop(name, None)

    def hook(lyr, inputs):
        # power iteration on raw values (buffer update, no grad) ...
        wv = orig._data
        m = jnp.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
        u = getattr(lyr, name + "_u")._data
        # vvec must exist even with n_power_iterations=0 (frozen estimate)
        vvec = m.T @ u
        vvec = vvec / jnp.maximum(jnp.linalg.norm(vvec), eps)
        for _ in range(n_power_iterations):
            u = m @ vvec
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
            vvec = m.T @ u
            vvec = vvec / jnp.maximum(jnp.linalg.norm(vvec), eps)
        getattr(lyr, name + "_u")._data = u
        # ... then a taped division so grads flow to the orig weight
        derived = apply_op(
            "spectral_norm",
            lambda w_: w_ / jnp.maximum(
                u @ jnp.moveaxis(w_, dim, 0).reshape(w_.shape[dim], -1)
                @ vvec, eps),
            orig)
        object.__setattr__(lyr, name, derived)
        return None

    layer.register_forward_pre_hook(hook)
    hook(layer, None)
    return layer


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """Clip the GLOBAL grad norm in place; returns the pre-clip norm
    (reference nn/utils/clip_grad_norm_.py)."""
    params = [p for p in (parameters if isinstance(parameters, (list, tuple))
                          else [parameters])
              if isinstance(p, Tensor) and p.grad is not None]
    if not params:
        return to_tensor(np.float32(0.0))
    grads = [p.grad._data.astype(jnp.float32) for p in params]
    if norm_type == float("inf"):
        total = jnp.max(jnp.asarray([jnp.abs(g).max() for g in grads]))
    else:
        total = jnp.sum(jnp.asarray(
            [jnp.sum(jnp.abs(g) ** norm_type) for g in grads])) \
            ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"the total norm of order {norm_type} is non-finite")
    coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        p.grad._data = (p.grad._data.astype(jnp.float32)
                        * coef).astype(p.grad._data.dtype)
    return to_tensor(total)


def clip_grad_value_(parameters, clip_value):
    """Clamp every grad element into [-clip_value, clip_value] in place
    (reference nn/utils/clip_grad_value_.py)."""
    clip_value = float(clip_value)
    for p in (parameters if isinstance(parameters, (list, tuple))
              else [parameters]):
        if isinstance(p, Tensor) and p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)


def parameters_to_vector(parameters, name=None):
    """Flatten parameters into one vector (reference
    transform_parameters.py)."""
    ps = list(parameters)
    return to_tensor(jnp.concatenate(
        [p._data.reshape(-1) for p in ps]))


def vector_to_parameters(vec, parameters, name=None):
    """Write a flat vector back into the parameter list (in place)."""
    raw = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p._data.shape)) if p._data.ndim else 1
        p._data = raw[off:off + n].reshape(p._data.shape).astype(
            p._data.dtype)
        off += n
