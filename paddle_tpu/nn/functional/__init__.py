"""paddle.nn.functional parity (python/paddle/nn/functional/).

All functions are thin pure-JAX ops dispatched through apply_op (tape + AMP).
The attention entry points (flash_attention / scaled_dot_product_attention)
route to the Pallas kernels in paddle_tpu.kernels on TPU (reference analog:
phi/kernels/gpu/flash_attn_kernel.cu:324 wrapping third_party/flashattn).
"""

from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from ... import framework
from ...framework import convert_dtype, to_jax_dtype
from ...tensor import Tensor, apply_op, to_tensor

__all__ = [
    # activations
    "relu", "relu6", "relu_", "gelu", "silu", "swish", "sigmoid", "tanh",
    "softmax", "log_softmax", "softplus", "softsign", "softshrink", "hardshrink",
    "leaky_relu", "elu", "selu", "celu", "prelu", "rrelu", "hardsigmoid",
    "hardswish", "hardtanh", "mish", "tanhshrink", "thresholded_relu", "glu",
    "gumbel_softmax", "maxout", "log_sigmoid",
    # linear & embedding
    "linear", "embedding", "one_hot", "bilinear",
    # norm
    "layer_norm", "rms_norm", "batch_norm", "instance_norm", "group_norm",
    "normalize", "local_response_norm",
    # dropout
    "dropout", "dropout2d", "dropout3d", "alpha_dropout", "feature_alpha_dropout",
    # conv & pool
    "conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
    "conv3d_transpose", "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d",
    "max_pool2d", "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_max_pool2d", "interpolate", "upsample", "pixel_shuffle", "unfold", "pad",
    # losses
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "cosine_embedding_loss",
    "ctc_loss", "hinge_embedding_loss", "poisson_nll_loss", "triplet_margin_loss",
    "sigmoid_focal_loss", "square_error_cost", "log_loss",
    # attention
    "scaled_dot_product_attention", "flash_attention", "sdp_kernel",
    # misc
    "cosine_similarity", "pairwise_distance", "label_smooth", "sequence_mask",
    "temporal_shift", "pixel_unshuffle", "channel_shuffle", "fold",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------


def relu(x, name=None):
    return apply_op("relu", jax.nn.relu, _t(x))


def relu_(x, name=None):
    out = relu(x)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    return x


def relu6(x, name=None):
    return apply_op("relu6", jax.nn.relu6, _t(x))


def gelu(x, approximate=False, name=None):
    return apply_op("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), _t(x))


def silu(x, name=None):
    return apply_op("silu", jax.nn.silu, _t(x))


def swish(x, name=None):
    return silu(x)


def sigmoid(x, name=None):
    return apply_op("sigmoid", jax.nn.sigmoid, _t(x))


def log_sigmoid(x, name=None):
    return apply_op("log_sigmoid", jax.nn.log_sigmoid, _t(x))


def tanh(x, name=None):
    return apply_op("tanh", jnp.tanh, _t(x))


def softmax(x, axis=-1, dtype=None, name=None):
    x = _t(x)
    if dtype is not None:
        x = apply_op("cast", lambda a: a.astype(to_jax_dtype(convert_dtype(dtype))), x)
    return apply_op("softmax", lambda a: jax.nn.softmax(a, axis=axis), x)


def log_softmax(x, axis=-1, dtype=None, name=None):
    return apply_op("log_softmax", lambda a: jax.nn.log_softmax(a, axis=axis), _t(x))


def softplus(x, beta=1, threshold=20, name=None):
    return apply_op("softplus", lambda a: jnp.where(a * beta > threshold, a, jax.nn.softplus(a * beta) / beta), _t(x))


def softsign(x, name=None):
    return apply_op("softsign", jax.nn.soft_sign, _t(x))


def softshrink(x, threshold=0.5, name=None):
    return apply_op("softshrink", lambda a: jnp.where(a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)), _t(x))


def hardshrink(x, threshold=0.5, name=None):
    return apply_op("hardshrink", lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), _t(x))


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op("leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope), _t(x))


def elu(x, alpha=1.0, name=None):
    return apply_op("elu", lambda a: jax.nn.elu(a, alpha), _t(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op("selu", lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), _t(x))


def celu(x, alpha=1.0, name=None):
    return apply_op("celu", lambda a: jax.nn.celu(a, alpha), _t(x))


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = _t(x), _t(weight)

    def f(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)

    return apply_op("prelu", f, x, weight)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    x = _t(x)
    if training:
        k = framework.next_rng_key()
        slope = jax.random.uniform(k, tuple(x.shape), minval=lower, maxval=upper)
        return apply_op("rrelu", lambda a: jnp.where(a >= 0, a, slope.astype(a.dtype) * a), x)
    mid = (lower + upper) / 2.0
    return apply_op("rrelu", lambda a: jnp.where(a >= 0, a, mid * a), x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply_op("hardsigmoid", lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), _t(x))


def hardswish(x, name=None):
    return apply_op("hardswish", lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, _t(x))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op("hardtanh", lambda a: jnp.clip(a, min, max), _t(x))


def mish(x, name=None):
    return apply_op("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)), _t(x))


def tanhshrink(x, name=None):
    return apply_op("tanhshrink", lambda a: a - jnp.tanh(a), _t(x))


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_op("thresholded_relu", lambda a: jnp.where(a > threshold, a, value), _t(x))


def glu(x, axis=-1, name=None):
    def f(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)
    return apply_op("glu", f, _t(x))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    x = _t(x)
    k = framework.next_rng_key()

    def f(a):
        g = jax.random.gumbel(k, a.shape, dtype=a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            y_hard = jax.nn.one_hot(jnp.argmax(y, axis=axis), a.shape[axis], axis=axis, dtype=a.dtype)
            y = y_hard + y - jax.lax.stop_gradient(y)
        return y

    return apply_op("gumbel_softmax", f, x)


def maxout(x, groups, axis=1, name=None):
    def f(a):
        shape = list(a.shape)
        ch = shape[axis]
        shape[axis:axis + 1] = [groups, ch // groups]
        return jnp.max(a.reshape(shape), axis=axis + 1 if axis >= 0 else axis)
    return apply_op("maxout", f, _t(x))


# ---------------------------------------------------------------------------
# linear / embedding
# ---------------------------------------------------------------------------


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b); W stored [in, out] like the reference (nn/layer/common.py Linear)."""
    x, weight = _t(x), _t(weight)
    if bias is not None:
        bias = _t(bias)
        return apply_op("linear", lambda a, w, b: jnp.matmul(a, w) + b, x, weight, bias)
    return apply_op("linear", lambda a, w: jnp.matmul(a, w), x, weight)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    x, weight = _t(x), _t(weight)

    def f(i, w):
        out = jnp.take(w, i, axis=0)
        if padding_idx is not None:
            mask = (i == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    return apply_op("embedding", lambda i, w: f(i, w), x, weight, nondiff=(0,))


def one_hot(x, num_classes, name=None):
    x = _t(x)
    return apply_op("one_hot", lambda i: jax.nn.one_hot(i, num_classes, dtype=to_jax_dtype(framework.get_default_dtype())), x, nondiff=(0,))


def bilinear(x1, x2, weight, bias=None, name=None):
    x1, x2, weight = _t(x1), _t(x2), _t(weight)

    def f(a, b, w, *bb):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bb:
            out = out + bb[0]
        return out

    if bias is not None:
        return apply_op("bilinear", f, x1, x2, weight, _t(bias))
    return apply_op("bilinear", f, x1, x2, weight)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    x = _t(x)
    ns = (normalized_shape,) if isinstance(normalized_shape, int) else tuple(normalized_shape)
    axes = tuple(range(x.ndim - len(ns), x.ndim))

    def f(a, *wb):
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [x]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return apply_op("layer_norm", f, *args)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """Fused RMSNorm (reference: phi/kernels/fusion/gpu/fused_layernorm + rms);
    routes to the Pallas kernel on TPU via paddle_tpu.kernels."""
    from ...kernels import rms_norm as _kernel_rms_norm

    x = _t(x)
    if weight is not None:
        return apply_op("rms_norm", lambda a, w: _kernel_rms_norm(a, w, epsilon), x, _t(weight))
    return apply_op("rms_norm", lambda a: _kernel_rms_norm(a, None, epsilon), x)


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-05, data_format="NCHW", use_global_stats=None, name=None):
    x = _t(x)
    rm, rv = _t(running_mean), _t(running_var)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]

    use_batch_stats = training and not use_global_stats

    def f(a, *wb):
        if use_batch_stats:
            # stats computed INSIDE the recorded op so the vjp includes the
            # d(mean)/dx and d(var)/dx terms (true batch-norm gradient)
            mean_use = jnp.mean(a, axis=reduce_axes)
            var_use = jnp.var(a, axis=reduce_axes)
        else:
            stat_t = a.dtype if jnp.issubdtype(a.dtype, jnp.floating) else jnp.float32
            mean_use = rm._data.astype(stat_t)
            var_use = rv._data.astype(stat_t)
        out = (a - mean_use.reshape(bshape)) * jax.lax.rsqrt(var_use.reshape(bshape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape)
        return out, mean_use, var_use

    args = [x]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    out, mean_t, var_t = apply_op("batch_norm", f, *args)
    if use_batch_stats:
        # update running stats in place (stateful buffer semantics), detached
        rm._data = momentum * rm._data + (1 - momentum) * mean_t._data
        rv._data = momentum * rv._data + (1 - momentum) * var_t._data
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW", name=None):
    x = _t(x)
    axes = tuple(range(2, x.ndim))

    def f(a, *wb):
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + eps)
        i = 0
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [x]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return apply_op("instance_norm", f, *args)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None, data_format="NCHW", name=None):
    x = _t(x)

    def f(a, *wb):
        if data_format == "NHWC":
            a = jnp.moveaxis(a, -1, 1)
        n, c = a.shape[0], a.shape[1]
        g = num_groups
        spatial = a.shape[2:]
        ar = a.reshape(n, g, c // g, *spatial)
        axes = tuple(range(2, ar.ndim))
        mean = jnp.mean(ar, axis=axes, keepdims=True)
        var = jnp.var(ar, axis=axes, keepdims=True)
        out = ((ar - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape)
        shape = [1, c] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [x]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return apply_op("group_norm", f, *args)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return apply_op(
        "normalize",
        lambda a: a / jnp.maximum(jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p), epsilon),
        _t(x),
    )


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    x = _t(x)

    def f(a):
        sq = jnp.square(a)
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        c = a.shape[ch_axis]
        sq_m = jnp.moveaxis(sq, ch_axis, -1)
        pad = [(0, 0)] * (sq_m.ndim - 1) + [(size // 2, (size - 1) // 2)]
        padded = jnp.pad(sq_m, pad)
        win = sum(jax.lax.slice_in_dim(padded, i, i + c, axis=-1) for i in range(size))
        # reference (nn/functional/norm.py:601-615) zero-pads then avg-pools,
        # so every window divides by `size` — the torch alpha/n convention
        denom = (k + alpha * win / size) ** beta
        return a / jnp.moveaxis(denom, -1, ch_axis)

    return apply_op("local_response_norm", f, x)


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None, key=None):
    x = _t(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and p > 0.0:
            return apply_op("dropout_infer", lambda a: a * (1.0 - p), x)
        return apply_op("dropout_id", lambda a: a, x)
    if key is None:
        key = framework.next_rng_key()

    def f(a):
        shape = list(a.shape)
        if axis is not None:
            axes = (axis,) if isinstance(axis, int) else tuple(axis)
            for i in range(len(shape)):
                if i not in axes:
                    shape[i] = 1
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return apply_op("dropout", f, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = (2, 3) if data_format == "NCHW" else (1, 2)
    # drop whole channels: mask over (N, C)
    keep_axes = (0, 1) if data_format == "NCHW" else (0, 3)
    drop_axis = [i for i in range(4) if i not in keep_axes]
    return dropout(x, p=p, axis=list(keep_axes), training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    keep_axes = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p=p, axis=list(keep_axes), training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = _t(x)
    if not training or p == 0.0:
        return apply_op("dropout_id", lambda a: a, x)
    key = framework.next_rng_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def f(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p**2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)

    return apply_op("alpha_dropout", f, x)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    return alpha_dropout(x, p, training)


# ---------------------------------------------------------------------------
# conv / pool — MXU path: lowered to lax.conv_general_dilated
# ---------------------------------------------------------------------------


def _pair(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, data_format, nd,
             transpose=False, output_padding=0):
    x, weight = _t(x), _t(weight)
    stride = _pair(stride, nd)
    dilation = _pair(dilation, nd)
    out_pad = _pair(output_padding, nd)

    channel_first = data_format.startswith("NC")
    spatial = {1: "H", 2: "HW", 3: "DHW"}[nd]
    # paddle weights are [out, in/g, *k] (OI layout) for every data_format
    if channel_first:
        dn = (f"NC{spatial}", f"OI{spatial}", f"NC{spatial}")
    else:
        dn = (f"N{spatial}C", f"OI{spatial}", f"N{spatial}C")

    if isinstance(padding, str):
        padding_lax = padding.upper()  # "SAME" / "VALID"
        pad_pairs = None
    else:
        p = _pair(padding, nd)
        if len(p) == nd:
            pad_pairs = [(int(pp), int(pp)) for pp in p]
        else:
            pad_pairs = [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(nd)]
        padding_lax = pad_pairs

    def f(a, w, *b):
        if transpose:
            # Transposed conv as input-dilated conv (the VJP formulation —
            # exact control over output_padding + groups).  Paddle weight
            # layout is [in, out/groups, *k]; regroup to OIHW with O=out.
            if pad_pairs is None:
                raise ValueError("string padding unsupported for conv_transpose")
            k_spatial = w.shape[2:]
            cin, cog = w.shape[0], w.shape[1]
            wg = w.reshape((groups, cin // groups, cog) + k_spatial)
            wg = jnp.swapaxes(wg, 1, 2)  # (g, out/g, in/g, *k)
            w_oihw = wg.reshape((groups * cog, cin // groups) + k_spatial)
            w_oihw = jnp.flip(w_oihw, axis=tuple(range(2, 2 + nd)))
            tp = []
            for i in range(nd):
                k_eff = dilation[i] * (k_spatial[i] - 1) + 1
                lo, hi = pad_pairs[i]
                tp.append((k_eff - 1 - lo, k_eff - 1 - hi + out_pad[i]))
            lhs = a if channel_first else jnp.moveaxis(a, -1, 1)
            out = jax.lax.conv_general_dilated(
                lhs, w_oihw, window_strides=(1,) * nd, padding=tp,
                lhs_dilation=stride, rhs_dilation=dilation,
                dimension_numbers=(f"NC{spatial}", f"OI{spatial}", f"NC{spatial}"),
                feature_group_count=groups,
            )
            if not channel_first:
                out = jnp.moveaxis(out, 1, -1)
        else:
            out = jax.lax.conv_general_dilated(
                a, w, window_strides=stride, padding=padding_lax,
                rhs_dilation=dilation, dimension_numbers=dn,
                feature_group_count=groups,
                preferred_element_type=jnp.float32 if a.dtype == jnp.bfloat16 else None,
            )
            out = out.astype(a.dtype)
        if b:
            ch_axis = dn[2].index("C")
            shape = [1] * out.ndim
            shape[ch_axis] = b[0].shape[0]
            out = out + b[0].reshape(shape)
        return out

    args = [x, weight]
    if bias is not None:
        args.append(_t(bias))
    return apply_op("conv%dd" % nd, f, *args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, data_format, 1)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, data_format, 2)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, data_format, 3)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, data_format="NCL", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, data_format, 1, transpose=True, output_padding=output_padding)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, data_format, 2, transpose=True, output_padding=output_padding)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, data_format, 3, transpose=True, output_padding=output_padding)


def _pool_out_extra(in_sizes, kernel, stride, pad, ceil_mode):
    """Per-dim (out_size, extra_right_pad).  ceil_mode keeps the trailing
    partial window (reference pooling.cc convention: the last window must
    start inside input+left-pad)."""
    outs, extras = [], []
    for S, k, s, p in zip(in_sizes, kernel, stride, pad):
        if ceil_mode:
            o = -(-(S + 2 * p - k) // s) + 1
            if (o - 1) * s >= S + p:
                o -= 1
        else:
            o = (S + 2 * p - k) // s + 1
        outs.append(o)
        extras.append(max((o - 1) * s + k - S - 2 * p, 0))
    return outs, extras


def _max_pool_with_mask(a, kernel, stride, pad, outs):
    """Gather-based max pool returning (out, mask); mask uses the reference's
    flattened row-major index over the UNPADDED spatial dims (torch-equal).
    Memory O(prod(kernel)) x output — the eager return_mask path only; the
    plain pool stays on reduce_window."""
    nd = len(kernel)
    S = a.shape[2:]
    pos_d, valid_d = [], []
    for d in range(nd):
        pos = (np.arange(outs[d])[:, None] * stride[d] - pad[d]
               + np.arange(kernel[d])[None, :])          # (O_d, k_d)
        valid_d.append((pos >= 0) & (pos < S[d]))
        pos_d.append(np.clip(pos, 0, S[d] - 1))
    vals = a
    for d in range(nd):
        vals = jnp.take(vals, jnp.asarray(pos_d[d]), axis=2 + 2 * d)
    # (N, C, O1, k1, O2, k2, ...) -> (N, C, O..., prod(k))
    perm = (0, 1) + tuple(2 + 2 * d for d in range(nd)) + \
        tuple(3 + 2 * d for d in range(nd))
    vals = vals.transpose(perm).reshape(
        a.shape[:2] + tuple(outs) + (int(np.prod(kernel)),))
    strides_flat = [int(np.prod(S[d + 1:])) for d in range(nd)]
    flat = np.zeros([1] * (2 * nd), np.int64)
    valid = np.ones([1] * (2 * nd), bool)
    for d in range(nd):
        sh = [1] * (2 * nd)
        sh[2 * d], sh[2 * d + 1] = pos_d[d].shape
        flat = flat + pos_d[d].reshape(sh) * strides_flat[d]
        valid = valid & valid_d[d].reshape(sh)
    perm2 = tuple(2 * d for d in range(nd)) + \
        tuple(2 * d + 1 for d in range(nd))
    flat = flat.transpose(perm2).reshape(tuple(outs) + (-1,))
    valid = valid.transpose(perm2).reshape(tuple(outs) + (-1,))
    vals = jnp.where(jnp.asarray(valid), vals, -jnp.inf)
    wi = jnp.argmax(vals, axis=-1)
    out = jnp.take_along_axis(vals, wi[..., None], axis=-1)[..., 0]
    mask = jnp.take_along_axis(
        jnp.broadcast_to(jnp.asarray(flat), vals.shape),
        wi[..., None], axis=-1)[..., 0]
    return out.astype(a.dtype), mask.astype(jnp.int32)


def _pool_nd(x, kernel, stride, padding, nd, mode, ceil_mode=False,
             exclusive=True, data_format="NCHW", return_mask=False,
             divisor_override=None):
    x = _t(x)
    kernel = _pair(kernel, nd)
    stride = _pair(stride if stride is not None else kernel, nd)
    pad = _pair(padding, nd)
    channel_first = data_format.startswith("NC")

    def to_cf(a):
        return a if channel_first else jnp.moveaxis(a, -1, 1)

    def f(a):
        acf = to_cf(a)
        outs, extras = _pool_out_extra(acf.shape[2:], kernel, stride, pad,
                                       ceil_mode)
        # ceil_mode's trailing partial window = asymmetric extra right pad
        sp_pads = tuple((p, p + e) for p, e in zip(pad, extras))
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        pads = ((0, 0), (0, 0)) + sp_pads
        if mode == "max":
            out = jax.lax.reduce_window(acf, -jnp.inf, jax.lax.max, window,
                                        strides, pads)
        else:
            summed = jax.lax.reduce_window(acf, 0.0, jax.lax.add, window,
                                           strides, pads)
            if divisor_override is not None:
                out = summed / float(divisor_override)
            elif exclusive and (any(p > 0 for p in pad)
                                or any(e > 0 for e in extras)):
                ones = jnp.ones_like(acf)
                counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add,
                                               window, strides, pads)
                out = summed / counts
            elif any(e > 0 for e in extras):
                # exclusive=False with ceil_mode overhang: the reference
                # clips each window end to input+pad before the divisor
                # (pooling.cc:74-84 hend=min(hstart+k, H+pad)), so trailing
                # partial windows divide by kernel volume minus the
                # overhang — padding still counts, the overhang does not
                div = np.float32(1.0)
                for d, (S, k, s, p, o) in enumerate(zip(
                        acf.shape[2:], kernel, stride, pad, outs)):
                    c = np.minimum(k, S + 2 * p
                                   - np.arange(o) * s).astype(np.float32)
                    div = div * c.reshape((o,) + (1,) * (len(outs) - 1 - d))
                out = summed / jnp.asarray(div)[None, None]
            else:
                out = summed / float(np.prod(kernel))
        out = out.astype(a.dtype)
        return out if channel_first else jnp.moveaxis(out, 1, -1)

    if return_mask and mode == "max":
        # value through the differentiable reduce_window path; the int32
        # mask as a separate non-diff op (nondiff -> stop_gradient output)
        def f_mask(a):
            acf = to_cf(a)
            outs, _ = _pool_out_extra(acf.shape[2:], kernel, stride, pad,
                                      ceil_mode)
            _, mask = _max_pool_with_mask(acf, kernel, stride, pad, outs)
            return mask if channel_first else jnp.moveaxis(mask, 1, -1)

        out = apply_op(f"max_pool{nd}d", f, x)
        mask = apply_op(f"max_pool{nd}d_mask", f_mask, x, nondiff=(0,))
        return out, mask
    return apply_op(f"{mode}_pool{nd}d", f, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCL", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, "max", ceil_mode,
                    data_format="NCL", return_mask=return_mask)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 2, "max", ceil_mode,
                    data_format=data_format, return_mask=return_mask)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, "max", ceil_mode,
                    data_format=data_format, return_mask=return_mask)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, "avg", ceil_mode, exclusive, data_format="NCL")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 2, "avg", ceil_mode,
                    exclusive, data_format=data_format,
                    divisor_override=divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, "avg", ceil_mode,
                    exclusive, data_format=data_format,
                    divisor_override=divisor_override)


def adaptive_avg_pool1d(x, output_size, name=None):
    x = _t(x)
    out_l = output_size if isinstance(output_size, int) else output_size[0]

    def f(a):
        l = a.shape[-1]
        return jnp.mean(a.reshape(*a.shape[:-1], out_l, l // out_l), axis=-1)

    return apply_op("adaptive_avg_pool1d", f, x)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    x = _t(x)
    oh, ow = _pair(output_size, 2)

    def f(a):
        if data_format == "NHWC":
            a = jnp.moveaxis(a, -1, 1)
        n, c, h, w = a.shape
        if oh is None or (h % oh == 0 and w % ow == 0):
            out = jnp.mean(a.reshape(n, c, oh, h // oh, ow, w // ow), axis=(3, 5))
        else:
            # general adaptive pooling via interpolation-style bucketing
            out = jnp.stack([
                jnp.stack([
                    jnp.mean(a[:, :, int(np.floor(i * h / oh)):int(np.ceil((i + 1) * h / oh)),
                              int(np.floor(j * w / ow)):int(np.ceil((j + 1) * w / ow))], axis=(2, 3))
                    for j in range(ow)], axis=-1)
                for i in range(oh)], axis=-2)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply_op("adaptive_avg_pool2d", f, x)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    x = _t(x)
    oh, ow = _pair(output_size, 2)

    def f(a):
        n, c, h, w = a.shape
        return jnp.max(a.reshape(n, c, oh, h // oh, ow, w // ow), axis=(3, 5))

    return apply_op("adaptive_max_pool2d", f, x)


def _src_coords(S, O, align_corners, align_mode, scale=None):
    """Reference coordinate conventions (interpolate_kernel.h): align_corners
    -> endpoints map exactly; else align_mode 0 = half-pixel (the torch
    align_corners=False convention), align_mode 1 = asymmetric src=dst*ratio.
    A user-provided scale_factor sets ratio = 1/scale directly (the torch
    default / reference behavior) instead of recomputing S/O."""
    i = np.arange(O, dtype=np.float64)
    if align_corners:
        return i * (S - 1) / max(O - 1, 1)
    ratio = (S / O) if scale is None else (1.0 / scale)
    if align_mode == 1:
        return i * ratio
    # half-pixel; NOT clipped here — linear clamps (reference/torch), cubic
    # keeps negative src and border-replicates its taps instead
    return (i + 0.5) * ratio - 0.5


def _resize_axis(a, axis, O, mode, align_corners, align_mode, scale=None):
    """Separable 1-D resize along `axis` (weights are static numpy)."""
    S = a.shape[axis]
    if mode == "nearest":
        if align_corners:
            # round-half-UP: the reference casts ratio*i + 0.5 (np.round's
            # half-to-even would pick the other pixel at every tie)
            idx = np.floor(np.arange(O) * (S - 1) / max(O - 1, 1) + 0.5)
        else:
            # legacy asymmetric floor — torch 'nearest' (not nearest-exact)
            ratio = (S / O) if scale is None else (1.0 / scale)
            idx = np.minimum(np.floor(np.arange(O) * ratio), S - 1)
        return jnp.take(a, jnp.asarray(idx.astype(np.int64)), axis=axis)
    if mode == "area":
        # adaptive-average windows [floor(i*S/O), ceil((i+1)*S/O))
        starts = np.floor(np.arange(O) * S / O).astype(np.int64)
        ends = np.ceil((np.arange(O) + 1) * S / O).astype(np.int64)
        cs = jnp.cumsum(a, axis=axis)
        zero = jnp.take(cs, jnp.asarray([0]), axis=axis) * 0
        cs = jnp.concatenate([zero, cs], axis=axis)
        hi = jnp.take(cs, jnp.asarray(ends), axis=axis)
        lo = jnp.take(cs, jnp.asarray(starts), axis=axis)
        shape = [1] * a.ndim
        shape[axis] = O
        n = jnp.asarray((ends - starts).astype(np.float32)).reshape(shape)
        return (hi - lo) / n
    src = _src_coords(S, O, align_corners, align_mode, scale)
    if mode == "linear":
        src = np.clip(src, 0.0, S - 1)
        lo = np.clip(np.floor(src), 0, S - 1).astype(np.int64)
        hi = np.minimum(lo + 1, S - 1)
        w = (src - lo).astype(np.float32)
        shape = [1] * a.ndim
        shape[axis] = O
        wj = jnp.asarray(w).reshape(shape).astype(a.dtype)
        return (jnp.take(a, jnp.asarray(lo), axis=axis) * (1 - wj)
                + jnp.take(a, jnp.asarray(hi), axis=axis) * wj)
    if mode == "cubic":
        # Keys cubic-convolution kernel, A=-0.75 (cubic_interp1d in the
        # reference's interpolate_kernel.h; torch matches)
        A = -0.75
        base = np.floor(src).astype(np.int64)
        t = (src - base).astype(np.float64)
        w = [
            ((A * (t + 1) - 5 * A) * (t + 1) + 8 * A) * (t + 1) - 4 * A,
            ((A + 2) * t - (A + 3)) * t * t + 1,
            ((A + 2) * (1 - t) - (A + 3)) * (1 - t) * (1 - t) + 1,
            ((A * (2 - t) - 5 * A) * (2 - t) + 8 * A) * (2 - t) - 4 * A,
        ]
        out = 0
        shape = [1] * a.ndim
        shape[axis] = O
        for k in range(4):
            idx = np.clip(base + k - 1, 0, S - 1)
            wk = jnp.asarray(w[k].astype(np.float32)).reshape(shape)
            out = out + jnp.take(a, jnp.asarray(idx), axis=axis) * wk
        return out.astype(a.dtype)
    raise ValueError(f"unsupported interpolate mode {mode!r}")


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    x = _t(x)
    per_dim = {"nearest": "nearest", "linear": "linear", "bilinear": "linear",
               "trilinear": "linear", "bicubic": "cubic", "area": "area"}
    if mode not in per_dim:
        raise ValueError(f"unsupported interpolate mode {mode!r}")

    def f(a):
        channel_first = data_format.startswith("NC")
        spatial = a.shape[2:] if channel_first else a.shape[1:-1]
        if size is not None:
            new_spatial = tuple(int(s) for s in (size if isinstance(size, (list, tuple)) else [size]))
        scales = [None] * len(spatial)
        if size is None:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * len(spatial)
            new_spatial = tuple(int(s * f_) for s, f_ in zip(spatial, sf))
            # the given scale drives the coordinate ratio (1/scale), NOT a
            # recomputed S/O — torch default / reference behavior
            scales = [float(f_) for f_ in sf]
        if len(new_spatial) != len(spatial):
            raise ValueError(
                f"interpolate size/scale_factor must cover all "
                f"{len(spatial)} spatial dims, got {len(new_spatial)}")
        out = a
        for d, O in enumerate(new_spatial):
            axis = (2 + d) if channel_first else (1 + d)
            if out.shape[axis] != O or per_dim[mode] != "nearest":
                out = _resize_axis(out, axis, O, per_dim[mode],
                                   align_corners, align_mode, scales[d])
        return out.astype(a.dtype)

    return apply_op("interpolate", f, x)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    x = _t(x)
    r = upscale_factor

    def f(a):
        n, c, h, w = a.shape
        out = a.reshape(n, c // (r * r), r, r, h, w)
        out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
        return out.reshape(n, c // (r * r), h * r, w * r)

    return apply_op("pixel_shuffle", f, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    x = _t(x)
    r = downscale_factor

    def f(a):
        n, c, h, w = a.shape
        out = a.reshape(n, c, h // r, r, w // r, r)
        out = jnp.transpose(out, (0, 1, 3, 5, 2, 4))
        return out.reshape(n, c * r * r, h // r, w // r)

    return apply_op("pixel_unshuffle", f, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    x = _t(x)

    def f(a):
        n, c, h, w = a.shape
        return a.reshape(n, groups, c // groups, h, w).swapaxes(1, 2).reshape(n, c, h, w)

    return apply_op("channel_shuffle", f, x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    x = _t(x)
    k = _pair(kernel_sizes, 2)
    s = _pair(strides, 2)
    p = _pair(paddings, 2)
    d = _pair(dilations, 2)

    def f(a):
        n, c, h, w = a.shape
        a_p = jnp.pad(a, ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])))
        oh = (h + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (w + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        patches = []
        for i in range(k[0]):
            for j in range(k[1]):
                patches.append(a_p[:, :, i * d[0]:i * d[0] + oh * s[0]:s[0], j * d[1]:j * d[1] + ow * s[1]:s[1]])
        out = jnp.stack(patches, axis=2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * k[0] * k[1], oh * ow)

    return apply_op("unfold", f, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    x = _t(x)
    oh, ow = _pair(output_sizes, 2)
    k = _pair(kernel_sizes, 2)
    s = _pair(strides, 2)
    p = _pair(paddings, 2)

    def f(a):
        n, ckk, l = a.shape
        c = ckk // (k[0] * k[1])
        nh = (oh + 2 * p[0] - k[0]) // s[0] + 1
        nw = (ow + 2 * p[1] - k[1]) // s[1] + 1
        a_r = a.reshape(n, c, k[0], k[1], nh, nw)
        out = jnp.zeros((n, c, oh + 2 * p[0], ow + 2 * p[1]), dtype=a.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                out = out.at[:, :, i:i + nh * s[0]:s[0], j:j + nw * s[1]:s[1]].add(a_r[:, :, i, j])
        return out[:, :, p[0]:p[0] + oh, p[1]:p[1] + ow]

    return apply_op("fold", f, x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = _t(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = list(pad)

    def f(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # paddle semantics: pad applies to last len(pad)//2 spatial dims
            # in data_format order, innermost-last order like torch
            n_spatial = len(pad) // 2
            pairs = [(0, 0)] * nd
            if data_format.startswith("NC"):
                dims = list(range(2, 2 + n_spatial))
            else:
                dims = list(range(1, 1 + n_spatial))
            for idx, dim in enumerate(reversed(dims)):
                pairs[dim] = (pad[2 * idx], pad[2 * idx + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, pairs, mode="constant", constant_values=value)
        return jnp.pad(a, pairs, mode=jmode)

    return apply_op("pad", f, x)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------


def _reduce_loss(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    input, label = _t(input), _t(label)

    def f(logits, *rest):
        i = 0
        if soft_label:
            lbl = rest[i]; i += 1
        else:
            lbl = label._data
        w = rest[i] if weight is not None else None
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(jnp.maximum(logits, 1e-30))
        n_class = logits.shape[axis]
        if soft_label:
            soft = lbl
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_class
            out = -jnp.sum(soft * logp, axis=axis)
        else:
            lbl_i = lbl.astype(jnp.int32)
            squeeze = lbl_i.ndim == logits.ndim and lbl_i.shape[axis] == 1
            if squeeze:
                lbl_i = jnp.squeeze(lbl_i, axis=axis)
            valid = lbl_i != ignore_index
            lbl_safe = jnp.where(valid, lbl_i, 0)
            picked = jnp.take_along_axis(logp, lbl_safe[..., None], axis=axis)[..., 0] if axis in (-1, logits.ndim - 1) else \
                jnp.take_along_axis(logp, jnp.expand_dims(lbl_safe, axis), axis=axis).squeeze(axis)
            if label_smoothing > 0:
                smooth = -jnp.mean(logp, axis=axis)
                out = -(1 - label_smoothing) * picked + label_smoothing * smooth
            else:
                out = -picked
            if w is not None:
                wt = jnp.take(w, lbl_safe)
                out = out * wt
                out = jnp.where(valid, out, 0.0)
                if reduction == "mean":
                    # normalize by the sum of applied weights (reference semantics)
                    denom = jnp.maximum(jnp.sum(jnp.where(valid, wt, 0.0)), 1e-12)
                    return jnp.sum(out) / denom
            else:
                out = jnp.where(valid, out, 0.0)
                if reduction == "mean":
                    denom = jnp.maximum(jnp.sum(valid.astype(out.dtype)), 1.0)
                    return jnp.sum(out) / denom
        return _reduce_loss(out, reduction)

    args = [input]
    if soft_label:
        args.append(label)
    if weight is not None:
        args.append(_t(weight))
    return apply_op("cross_entropy", f, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index,
                         reduction="none", axis=axis)
    loss = apply_op("unsqueeze", lambda a: jnp.expand_dims(a, axis), loss)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    input, label = _t(input), _t(label)

    def f(p, y, *w):
        eps = 1e-12
        out = -(y * jnp.log(jnp.maximum(p, eps)) + (1 - y) * jnp.log(jnp.maximum(1 - p, eps)))
        if w:
            out = out * w[0]
        return _reduce_loss(out, reduction)

    args = [input, label] + ([_t(weight)] if weight is not None else [])
    return apply_op("binary_cross_entropy", f, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    logit, label = _t(logit), _t(label)

    def f(z, y, *rest):
        i = 0
        w = rest[i] if weight is not None else None
        if weight is not None:
            i += 1
        pw = rest[i] if pos_weight is not None else None
        log_sig = jax.nn.log_sigmoid(z)
        log_sig_neg = jax.nn.log_sigmoid(-z)
        if pw is not None:
            out = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        else:
            out = -(y * log_sig + (1 - y) * log_sig_neg)
        if w is not None:
            out = out * w
        return _reduce_loss(out, reduction)

    args = [logit, label]
    if weight is not None:
        args.append(_t(weight))
    if pos_weight is not None:
        args.append(_t(pos_weight))
    return apply_op("bce_with_logits", f, *args)


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op("mse_loss", lambda a, b: _reduce_loss(jnp.square(a - b), reduction), _t(input), _t(label))


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op("l1_loss", lambda a, b: _reduce_loss(jnp.abs(a - b), reduction), _t(input), _t(label))


def square_error_cost(input, label):
    return apply_op("square_error_cost", lambda a, b: jnp.square(a - b), _t(input), _t(label))


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply_op(
        "log_loss",
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        _t(input), _t(label),
    )


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    input, label = _t(input), _t(label)

    def f(logp, *w):
        lbl = label._data.astype(jnp.int32)
        valid = lbl != ignore_index
        lbl_safe = jnp.where(valid, lbl, 0)
        out = -jnp.take_along_axis(logp, lbl_safe[:, None], axis=1)[:, 0]
        if w:
            wt = jnp.take(w[0], lbl_safe)
            out = out * wt
            if reduction == "mean":
                return jnp.sum(jnp.where(valid, out, 0.0)) / jnp.maximum(jnp.sum(jnp.where(valid, wt, 0.0)), 1e-12)
        out = jnp.where(valid, out, 0.0)
        if reduction == "mean":
            return jnp.sum(out) / jnp.maximum(jnp.sum(valid.astype(out.dtype)), 1.0)
        return _reduce_loss(out, reduction)

    args = [input] + ([_t(weight)] if weight is not None else [])
    return apply_op("nll_loss", f, *args)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        out = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta) * delta
        # paddle: huber-style with delta; matches smooth_l1 when delta=1
        out = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce_loss(out, reduction)

    return apply_op("smooth_l1_loss", f, _t(input), _t(label))


def kl_div(input, label, reduction="mean", name=None):
    def f(logp, y):
        out = y * (jnp.log(jnp.maximum(y, 1e-12)) - logp)
        if reduction == "batchmean":
            return jnp.sum(out) / logp.shape[0]
        return _reduce_loss(out, reduction)

    return apply_op("kl_div", f, _t(input), _t(label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, y):
        return _reduce_loss(jnp.maximum(0.0, -y * (a - b) + margin), reduction)

    return apply_op("margin_ranking_loss", f, _t(input), _t(other), _t(label))


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean", name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / (jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12)
        out = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce_loss(out, reduction)

    return apply_op("cosine_embedding_loss", f, _t(input1), _t(input2), _t(label))


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def f(a, y):
        out = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce_loss(out, reduction)

    return apply_op("hinge_embedding_loss", f, _t(input), _t(label))


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8, reduction="mean", name=None):
    def f(a, y):
        if log_input:
            out = jnp.exp(a) - y * a
        else:
            out = a - y * jnp.log(a + epsilon)
        if full:
            # Stirling correction, applied only where label > 1 (loss.py:1591)
            safe = jnp.where(y > 1, y, 2.0)
            stirling = (safe * jnp.log(safe) - safe
                        + 0.5 * jnp.log(2 * _math.pi * safe))
            out = out + jnp.where(y > 1, stirling, 0.0)
        return _reduce_loss(out, reduction)

    return apply_op("poisson_nll_loss", f, _t(input), _t(label))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        return _reduce_loss(jnp.maximum(0.0, dp - dn + margin), reduction)

    return apply_op("triplet_margin_loss", f, _t(input), _t(positive), _t(negative))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    def f(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        out = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            out = out / n[0]
        return _reduce_loss(out, reduction)

    args = [_t(logit), _t(label)] + ([_t(normalizer)] if normalizer is not None else [])
    return apply_op("sigmoid_focal_loss", f, *args)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    # log_probs: (T, B, C) paddle layout
    lp = _t(log_probs)
    lbl = _t(labels)
    il = _t(input_lengths)
    ll = _t(label_lengths)

    def f(logits):
        import optax

        # optax expects (B, T, C) with logit inputs and padded labels (B, S)
        x = jnp.transpose(logits, (1, 0, 2))
        b, t, c = x.shape
        labels_arr = lbl._data
        if labels_arr.ndim == 1:
            labels_arr = labels_arr[None]
        logit_pad = (jnp.arange(t)[None, :] >= il._data[:, None]).astype(x.dtype)
        label_pad = (jnp.arange(labels_arr.shape[1])[None, :] >= ll._data[:, None]).astype(x.dtype)
        per_seq = optax.ctc_loss(x, logit_pad, labels_arr, label_pad, blank_id=blank)
        return _reduce_loss(per_seq / jnp.maximum(ll._data.astype(per_seq.dtype), 1.0) if reduction == "mean" else per_seq, reduction)

    return apply_op("ctc_loss", f, lp)


# ---------------------------------------------------------------------------
# attention — TPU hot path
# ---------------------------------------------------------------------------


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """(B, S, H, D) layout like the reference (nn/functional/flash_attention.py:410)."""
    from ...kernels import attention as _attn

    q, k, v = _t(query), _t(key), _t(value)
    args = [q, k, v]
    if attn_mask is not None:
        args.append(_t(attn_mask))

        def f(qq, kk, vv, mm):
            return _attn(qq, kk, vv, mask=mm, causal=is_causal)
    else:
        def f(qq, kk, vv):
            return _attn(qq, kk, vv, mask=None, causal=is_causal)

    out = apply_op("scaled_dot_product_attention", f, *args)
    if dropout_p > 0.0 and training:
        out = dropout(out, p=dropout_p, training=training)
    return out


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False,
                    fixed_seed_offset=None, rng_name="", training=True, name=None):
    out = scaled_dot_product_attention(query, key, value, None, dropout, causal, training)
    return out, None


class sdp_kernel:
    def __init__(self, **kwargs):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)

    return apply_op("cosine_similarity", f, _t(x1), _t(x2))


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    return apply_op(
        "pairwise_distance",
        lambda a, b: jnp.linalg.norm(a - b + epsilon, ord=p, axis=-1, keepdims=keepdim),
        _t(x), _t(y),
    )


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = _t(label)

    def f(y, *pd):
        n = y.shape[-1]
        if pd:
            return (1 - epsilon) * y + epsilon * pd[0]
        return (1 - epsilon) * y + epsilon / n

    args = [label] + ([_t(prior_dist)] if prior_dist is not None else [])
    return apply_op("label_smooth", f, *args)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    x = _t(x)
    ml = maxlen if maxlen is not None else int(np.asarray(x._data).max())
    return apply_op(
        "sequence_mask",
        lambda l: (jnp.arange(ml)[None, :] < l[..., None]).astype(to_jax_dtype(convert_dtype(dtype))),
        x, nondiff=(0,),
    )


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    x = _t(x)

    def f(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        ar = a.reshape(n, seg_num, c, h, w)
        fold_ = int(c * shift_ratio)
        left = jnp.concatenate([ar[:, 1:, :fold_], jnp.zeros_like(ar[:, :1, :fold_])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(ar[:, :1, fold_:2 * fold_]), ar[:, :-1, fold_:2 * fold_]], axis=1)
        mid = ar[:, :, 2 * fold_:]
        return jnp.concatenate([left, right, mid], axis=2).reshape(nt, c, h, w)

    return apply_op("temporal_shift", f, x)


from .rnn import simple_rnn_cell, lstm_cell, gru_cell  # noqa: F401,E402
from .vision import affine_grid, grid_sample  # noqa: F401,E402
from . import extras as _extras  # noqa: E402
from .extras import *  # noqa: F401,E402,F403

__all__ += ["simple_rnn_cell", "lstm_cell", "gru_cell",
            "affine_grid", "grid_sample"] + list(_extras.__all__)
