"""Spatial-transformer functionals: affine_grid + grid_sample.

Reference: python/paddle/nn/functional/vision.py:26 (affine_grid), :130
(grid_sample) — there they dispatch to cuDNN/CPU kernels; here both are
pure jnp gather/FMA compositions, so XLA fuses the interpolation weights
into the gathers and the whole sampler differentiates through x AND grid.

Conventions (verified against the reference docstring examples):
  * grid coords are (x, y[, z]) in [-1, 1], x indexes width.
  * align_corners=True maps -1/+1 to pixel CENTERS of the corner pixels;
    False treats pixels as 1-wide cells (-1/+1 are the outer edges).
  * padding_mode: zeros (OOB reads contribute 0), border (clamp),
    reflection (mirror, then clamp).
"""

from __future__ import annotations

import jax.numpy as jnp

from ...tensor import apply_op

__all__ = ["affine_grid", "grid_sample"]


def _base_coords(size, align_corners, dtype):
    if align_corners:
        return jnp.linspace(-1.0, 1.0, size, dtype=dtype)
    step = 2.0 / size
    return jnp.arange(size, dtype=dtype) * step + (step * 0.5 - 1.0)


def _affine_grid_impl(theta, out_shape, align_corners):
    dt = theta.dtype
    if theta.ndim != 3 or theta.shape[1:] not in ((2, 3), (3, 4)):
        raise ValueError(
            f"theta should be of shape [N, 2, 3] or [N, 3, 4], got "
            f"{tuple(theta.shape)}")
    if theta.shape[1] == 2:
        _, _, H, W = out_shape
        xs = _base_coords(W, align_corners, dt)
        ys = _base_coords(H, align_corners, dt)
        gx, gy = jnp.meshgrid(xs, ys, indexing="xy")      # (H, W)
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # (H, W, 3)
        return jnp.einsum("hwi,nki->nhwk", base, theta)
    _, _, D, H, W = out_shape
    xs = _base_coords(W, align_corners, dt)
    ys = _base_coords(H, align_corners, dt)
    zs = _base_coords(D, align_corners, dt)
    gz, gy, gx = jnp.meshgrid(zs, ys, xs, indexing="ij")  # (D, H, W)
    base = jnp.stack([gx, gy, gz, jnp.ones_like(gx)], axis=-1)
    return jnp.einsum("dhwi,nki->ndhwk", base, theta)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta (N, 2, 3) + out_shape [N, C, H, W] -> sampling grid (N, H, W, 2);
    theta (N, 3, 4) + [N, C, D, H, W] -> (N, D, H, W, 3).

    Reference: nn/functional/vision.py:26.
    """
    if hasattr(out_shape, "numpy"):
        out_shape = [int(v) for v in out_shape.numpy().tolist()]
    else:
        out_shape = [int(v) for v in out_shape]
    return apply_op("affine_grid", _affine_grid_impl, theta,
                    out_shape=out_shape, align_corners=bool(align_corners))


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) * 0.5 * (size - 1)
    return ((coord + 1.0) * size - 1.0) * 0.5


def _reflect(x, lo, hi):
    rng = hi - lo
    if rng <= 0:
        return jnp.full_like(x, lo)
    period = 2.0 * rng
    x = jnp.abs((x - lo) % period)
    return jnp.where(x > rng, period - x, x) + lo


def _resolve_coord(g, size, align_corners, padding_mode):
    """Unnormalized, padding-resolved coordinate + in-bounds flag source."""
    c = _unnormalize(g, size, align_corners)
    if padding_mode == "border":
        c = jnp.clip(c, 0, size - 1)
    elif padding_mode == "reflection":
        if align_corners:
            c = _reflect(c, 0.0, float(size - 1))
        else:
            c = _reflect(c, -0.5, size - 0.5)
        c = jnp.clip(c, 0, size - 1)
    return c


def _gather_nd(x, idxs, sizes):
    """x (N, C, *sizes); idxs: list of (N, *out) int arrays (one per spatial
    dim) -> (N, C, *out) with OOB indices pre-masked by the caller."""
    flat = idxs[0]
    for i, sz in zip(idxs[1:], sizes[1:]):
        flat = flat * sz + i
    N = x.shape[0]
    C = x.shape[1]
    xf = x.reshape(N, C, -1)
    ff = flat.reshape(N, 1, -1)
    out = jnp.take_along_axis(xf, jnp.broadcast_to(ff, (N, C, ff.shape[-1])),
                              axis=2)
    return out.reshape((N, C) + idxs[0].shape[1:])


def _grid_sample_impl(x, grid, mode, padding_mode, align_corners):
    nd = x.ndim - 2                       # spatial dims: 2 or 3
    sizes = x.shape[2:]                   # (H, W) or (D, H, W)
    # grid channels are (x, y[, z]) = (W, H[, D]) — reverse to match dims
    coords = [grid[..., nd - 1 - d] for d in range(nd)]  # per-dim, out shape
    zeros = padding_mode == "zeros"

    rs = [_resolve_coord(c, sizes[d], align_corners, padding_mode)
          for d, c in enumerate(coords)]

    if mode == "nearest":
        idxs, mask = [], None
        for d, c in enumerate(rs):
            i = jnp.round(c)
            ib = (i >= 0) & (i <= sizes[d] - 1)
            mask = ib if mask is None else (mask & ib)
            idxs.append(jnp.clip(i, 0, sizes[d] - 1).astype(jnp.int32))
        v = _gather_nd(x, idxs, sizes)
        if zeros:
            v = v * mask[:, None].astype(x.dtype)
        return v

    # bilinear/trilinear: 2^nd corners
    lo, wlo = [], []
    for c in rs:
        f = jnp.floor(c)
        lo.append(f)
        wlo.append(1.0 - (c - f))         # weight of the low corner
    out = None
    for corner in range(1 << nd):
        idxs, w, mask = [], None, None
        for d in range(nd):
            hi = (corner >> d) & 1
            i = lo[d] + hi
            wd = (1.0 - wlo[d]) if hi else wlo[d]
            ib = (i >= 0) & (i <= sizes[d] - 1)
            mask = ib if mask is None else (mask & ib)
            w = wd if w is None else w * wd
            idxs.append(jnp.clip(i, 0, sizes[d] - 1).astype(jnp.int32))
        v = _gather_nd(x, idxs, sizes)
        if zeros:
            w = w * mask.astype(w.dtype)
        out = v * w[:, None].astype(x.dtype) if out is None \
            else out + v * w[:, None].astype(x.dtype)
    return out


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x (N, C, H, W) at grid (N, Ho, Wo, 2) -> (N, C, Ho, Wo);
    5-D x (N, C, D, H, W) + grid (N, Do, Ho, Wo, 3) -> (N, C, Do, Ho, Wo).

    Reference: nn/functional/vision.py:130.  Differentiable in x and grid.
    """
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"mode should be 'bilinear' or 'nearest', got {mode}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(
            "padding_mode should be 'zeros', 'border' or 'reflection', "
            f"got {padding_mode}")
    nd = len(x.shape) - 2
    if len(grid.shape) != nd + 2 or grid.shape[-1] != nd:
        raise ValueError(
            f"grid shape {tuple(grid.shape)} does not match x shape "
            f"{tuple(x.shape)}: expected (N, *out_sizes, {nd})")
    return apply_op("grid_sample", _grid_sample_impl, x, grid, mode=mode,
                    padding_mode=padding_mode,
                    align_corners=bool(align_corners))
