"""Long-tail nn.functional surface (reference python/paddle/nn/functional/
{pooling,loss,common,vision,activation}.py remainders).

Everything here is a jnp composition through apply_op — same dispatch,
tape, AMP and registry treatment as the core functionals.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ... import framework
from ...tensor import Tensor, apply_op, to_tensor
from . import _pair

__all__ = [
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool3d",
    "max_unpool1d", "max_unpool2d", "max_unpool3d",
    "diag_embed", "zeropad2d", "gather_tree", "sparse_attention",
    "class_center_sample", "margin_cross_entropy", "hsigmoid_loss",
    "gaussian_nll_loss", "soft_margin_loss", "multi_label_soft_margin_loss",
    "multi_margin_loss", "dice_loss", "npair_loss",
    "triplet_margin_with_distance_loss", "rnnt_loss",
    "elu_", "hardtanh_", "leaky_relu_", "softmax_", "tanh_",
    "thresholded_relu_",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    if reduction == "none":
        return v
    raise ValueError(f"reduction should be mean|sum|none, got {reduction}")


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------


def _adaptive_buckets(a, out_sizes, reduce_fn, spatial_start):
    """General adaptive pooling: bucket boundaries floor/ceil like the
    reference kernels."""
    for d, o in enumerate(out_sizes):
        ax = spatial_start + d
        size = a.shape[ax]
        pieces = [
            reduce_fn(jax.lax.slice_in_dim(
                a, int(np.floor(i * size / o)),
                int(np.ceil((i + 1) * size / o)), axis=ax), axis=ax,
                keepdims=True)
            for i in range(o)]
        a = jnp.concatenate(pieces, axis=ax)
    return a


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    """Reference nn/functional/pooling.py adaptive_avg_pool3d."""
    x = _t(x)
    o = (output_size,) * 3 if isinstance(output_size, int) \
        else tuple(output_size)

    def f(a):
        if data_format == "NDHWC":
            a = jnp.moveaxis(a, -1, 1)
        a = _adaptive_buckets(a, o, jnp.mean, 2)
        if data_format == "NDHWC":
            a = jnp.moveaxis(a, 1, -1)
        return a
    return apply_op("adaptive_avg_pool3d", f, x)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    x = _t(x)
    o = output_size if isinstance(output_size, int) else output_size[0]

    def f(a):
        return _adaptive_buckets(a, (o,), jnp.max, 2)
    out = apply_op("adaptive_max_pool1d", f, x)
    if return_mask:
        def fi(a):
            size = a.shape[2]
            idx = []
            for i in range(o):
                lo = int(np.floor(i * size / o))
                hi = int(np.ceil((i + 1) * size / o))
                idx.append(lo + jnp.argmax(a[:, :, lo:hi], axis=2,
                                           keepdims=True))
            return jnp.concatenate(idx, axis=2).astype(jnp.int32)
        return out, apply_op("adaptive_max_pool1d_mask", fi, x)
    return out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    x = _t(x)
    o = (output_size,) * 3 if isinstance(output_size, int) \
        else tuple(output_size)

    def f(a):
        return _adaptive_buckets(a, o, jnp.max, 2)
    out = apply_op("adaptive_max_pool3d", f, x)
    if return_mask:
        def fi(a):
            n, c = a.shape[:2]
            D, H, W = a.shape[2:]
            flat = a.reshape(n, c, -1)
            bounds = [[(int(np.floor(i * s / oo)),
                        int(np.ceil((i + 1) * s / oo)))
                       for i in range(oo)]
                      for s, oo in zip((D, H, W), o)]
            cells = []
            for bd in bounds[0]:
                for bh in bounds[1]:
                    for bw in bounds[2]:
                        win = a[:, :, bd[0]:bd[1], bh[0]:bh[1], bw[0]:bw[1]]
                        wf = win.reshape(n, c, -1)
                        am = jnp.argmax(wf, axis=2)
                        dd, rem = jnp.divmod(
                            am, (bh[1] - bh[0]) * (bw[1] - bw[0]))
                        hh, ww = jnp.divmod(rem, bw[1] - bw[0])
                        cells.append(((bd[0] + dd) * H + bh[0] + hh) * W
                                     + bw[0] + ww)
            del flat
            return jnp.stack(cells, 2).reshape(
                (n, c) + tuple(o)).astype(jnp.int32)
        return out, apply_op("adaptive_max_pool3d_mask", fi, x)
    return out


def _unpool(x, indices, nd, output_size, data_format, name,
            kernel_size=None, stride=None, padding=0):
    """Scatter pooled values back to their argmax positions.  `indices`
    are flat positions within each (N, C) spatial plane (the reference's
    max_poolXd(return_mask=True) convention).  When output_size is None it
    is inferred as (in-1)*stride + kernel - 2*pad per dim (the reference's
    _unpool_output_size, pooling.py:695)."""
    x, indices = _t(x), _t(indices)
    if not data_format.startswith("NC"):
        # the scatter body assumes (N, C, *spatial); the reference rejects
        # channels-last here too (pooling.py:974 "should be 'NCHW'")
        raise ValueError(
            f"max_unpool{nd}d supports channels-first data_format only, "
            f"got {data_format!r}")
    if output_size is None:
        k = _pair(kernel_size, nd)
        st = _pair(stride if stride is not None else kernel_size, nd)
        pd = _pair(padding, nd)
        sp = x.shape[-nd:]
        output_size = [(int(sp[d]) - 1) * st[d] + k[d] - 2 * pd[d]
                       for d in range(nd)]
    out_sp = tuple(int(s) for s in output_size[-nd:])

    def f(a, idx):
        n, c = a.shape[:2]
        flat = a.reshape(n, c, -1)
        fidx = idx.reshape(n, c, -1)
        size = 1
        for s in out_sp:
            size *= s
        out = jnp.zeros((n, c, size), a.dtype)
        out = jax.vmap(jax.vmap(
            lambda o, i, v: o.at[i].add(v)))(out, fidx, flat)
        return out.reshape((n, c) + out_sp)

    return apply_op(f"max_unpool{nd}d", f, x, indices, nondiff=(1,))


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _unpool(x, indices, 1, output_size, data_format, name,
                   kernel_size, stride, padding)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _unpool(x, indices, 2, output_size, data_format, name,
                   kernel_size, stride, padding)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _unpool(x, indices, 3, output_size, data_format, name,
                   kernel_size, stride, padding)


# ---------------------------------------------------------------------------
# shaping / decoding helpers
# ---------------------------------------------------------------------------


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):  # noqa: A002
    """Alias of the tensor-level diag_embed (ops/manipulation.py) — the
    reference also exports it under nn.functional."""
    from ...ops.manipulation import diag_embed as _de
    return _de(input, offset=offset, dim1=dim1, dim2=dim2)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """Zero-pad H/W (reference nn/functional/common.py zeropad2d);
    padding = [left, right, top, bottom]."""
    x = _t(x)
    l, r, t, b = [int(p) for p in padding]

    def f(a):
        if data_format == "NCHW":
            cfg = [(0, 0), (0, 0), (t, b), (l, r)]
        else:
            cfg = [(0, 0), (t, b), (l, r), (0, 0)]
        return jnp.pad(a, cfg)
    return apply_op("zeropad2d", f, x)


def gather_tree(ids, parents):
    """Backtrace beam-search chains (reference nn/functional/common.py
    gather_tree): ids/parents (T, B, beam) -> full sequences."""
    ids, parents = _t(ids), _t(parents)

    def f(i, p):
        T = i.shape[0]

        def step(beam_idx, t):
            sel = jnp.take_along_axis(p[t], beam_idx, axis=-1)
            tok = jnp.take_along_axis(i[t], beam_idx, axis=-1)
            return sel, tok

        init = jnp.broadcast_to(jnp.arange(i.shape[2]), i.shape[1:])
        _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return toks[::-1]

    return apply_op("gather_tree", f, ids, parents, nondiff=(0, 1))


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-sparse attention over a CSR connectivity pattern (reference
    incubate sparse_attention, exported under nn.functional): each query
    position attends only to its CSR row's columns."""
    q, k, v = _t(query), _t(key), _t(value)
    off = np.asarray(_t(sparse_csr_offset)._data)
    cols = np.asarray(_t(sparse_csr_columns)._data)

    def f(qr, kr, vr):
        B, H, S, D = qr.shape
        scale = 1.0 / math.sqrt(D)
        rows = np.repeat(np.arange(S), np.diff(off[0, 0]))
        cc = cols[0, 0]
        scores = jnp.einsum("bhd,bhd->bh",
                            qr[:, :, rows].reshape(B, H, -1, D)
                            .transpose(2, 0, 1, 3).reshape(-1, B * H, D)
                            .swapaxes(0, 1).reshape(B * H, -1, D),
                            kr[:, :, cc].reshape(B, H, -1, D)
                            .transpose(2, 0, 1, 3).reshape(-1, B * H, D)
                            .swapaxes(0, 1).reshape(B * H, -1, D)
                            ).reshape(B, H, -1) * scale
        # segment softmax per row
        seg = jnp.asarray(rows)
        smax = jax.ops.segment_max(scores.reshape(B * H, -1).T, seg,
                                   num_segments=S)
        e = jnp.exp(scores.reshape(B * H, -1).T - smax[seg])
        den = jax.ops.segment_sum(e, seg, num_segments=S)
        w = (e / den[seg]).T.reshape(B, H, -1)
        out = jnp.zeros_like(qr)
        out = out.at[:, :, rows].add(w[..., None] * vr[:, :, cc])
        return out

    return apply_op("sparse_attention", f, q, k, v)


# ---------------------------------------------------------------------------
# classification losses
# ---------------------------------------------------------------------------


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample class centers: all positive classes + random negatives up to
    num_samples (reference nn/functional/common.py class_center_sample).
    Returns (remapped_label, sampled_class_index)."""
    lab = np.asarray(_t(label)._data).reshape(-1)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        # fresh negatives each call, seeded off the global stream
        rng = np.random.default_rng(
            np.asarray(framework.next_rng_key(), np.uint32))
        neg = np.setdiff1d(np.arange(num_classes), pos)
        extra = rng.choice(neg, size=num_samples - len(pos), replace=False)
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = {int(c): i for i, c in enumerate(sampled)}
    new_lab = np.array([remap[int(v)] for v in lab], lab.dtype)
    return to_tensor(new_lab), to_tensor(sampled.astype(lab.dtype))


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-family margin softmax (reference nn/functional/loss.py
    margin_cross_entropy): target logit cos(m1*t + m2) - m3, scaled."""
    logits, label = _t(logits), _t(label)

    def f(lg, lb):
        theta = jnp.arccos(jnp.clip(lg, -1.0 + 1e-7, 1.0 - 1e-7))
        oh = jax.nn.one_hot(lb, lg.shape[-1], dtype=lg.dtype)
        adj = jnp.cos(margin1 * theta + margin2) - margin3
        z = scale * jnp.where(oh > 0, adj, lg)
        logp = jax.nn.log_softmax(z, axis=-1)
        loss = -(oh * logp).sum(-1)
        return loss, jax.nn.softmax(z, axis=-1)

    loss, sm = apply_op("margin_cross_entropy", f, logits, label,
                        nondiff=(1,))
    from ...ops import mean as _mean, sum as _sum
    red = {"mean": _mean, "sum": _sum, "none": lambda v: v}[reduction]
    out = red(loss)
    return (out, sm) if return_softmax else out


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid over a complete binary tree (reference
    nn/functional/loss.py hsigmoid_loss; custom trees via
    path_table/path_code)."""
    x, lab = _t(input), _t(label)
    w = _t(weight)
    b = _t(bias) if bias is not None else None
    if path_table is None:
        # complete binary tree with num_classes leaves: internal node ids
        # 0..num_classes-2; leaf c sits at tree index num_classes-1+c
        depth = max(1, int(np.ceil(np.log2(max(num_classes, 2)))))
        tables, codes = [], []
        for c in range(num_classes):
            node = num_classes - 1 + c
            pt, pc = [], []
            while node > 0:
                parent = (node - 1) // 2
                pt.append(parent)
                pc.append(node == 2 * parent + 2)   # right child -> 1
                node = parent
            pt = pt[::-1][:depth] + [-1] * (depth - len(pt))
            pc = pc[::-1][:depth] + [False] * (depth - len(pc))
            tables.append(pt)
            codes.append(pc)
        path_table = to_tensor(np.asarray(tables, np.int64))
        path_code = to_tensor(np.asarray(codes, np.bool_))
    pt, pc = _t(path_table), _t(path_code)

    def f(xr, lr, wr, br, ptr, pcr):
        nodes = ptr[lr]                              # (B, depth)
        code = pcr[lr].astype(xr.dtype)              # (B, depth)
        valid = (nodes >= 0).astype(xr.dtype)
        safe = jnp.maximum(nodes, 0)
        wn = wr[safe]                                # (B, depth, D)
        z = jnp.einsum("bd,bkd->bk", xr, wn)
        if br is not None:
            z = z + br.reshape(-1)[safe]
        # sigmoid CE against the path code at each internal node
        ce = jnp.maximum(z, 0) - z * code + jnp.log1p(jnp.exp(-jnp.abs(z)))
        return (ce * valid).sum(-1, keepdims=True)

    args = [x, lab, w, b, pt, pc]
    return apply_op("hsigmoid_loss", f, *args, nondiff=(1, 4, 5))


# ---------------------------------------------------------------------------
# regression / metric losses
# ---------------------------------------------------------------------------


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """Reference nn/functional/loss.py gaussian_nll_loss."""
    x, y, var = _t(input), _t(label), _t(variance)

    def f(xr, yr, vr):
        v = jnp.maximum(vr, epsilon)
        loss = 0.5 * (jnp.log(v) + (xr - yr) ** 2 / v)
        if full:
            loss = loss + 0.5 * math.log(2 * math.pi)
        return _reduce(loss, reduction)
    return apply_op("gaussian_nll_loss", f, x, y, var)


def soft_margin_loss(input, label, reduction="mean", name=None):
    """log(1 + exp(-label * input)), label in {-1, 1}."""
    x, y = _t(input), _t(label)

    def f(xr, yr):
        return _reduce(jnp.log1p(jnp.exp(-yr.astype(xr.dtype) * xr)),
                       reduction)
    return apply_op("soft_margin_loss", f, x, y)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    x, y = _t(input), _t(label)
    w = _t(weight) if weight is not None else None

    def f(xr, yr, wr):
        yt = yr.astype(xr.dtype)
        per = -(yt * jax.nn.log_sigmoid(xr)
                + (1 - yt) * jax.nn.log_sigmoid(-xr))
        if wr is not None:
            per = per * wr
        return _reduce(per.mean(-1), reduction)
    return apply_op("multi_label_soft_margin_loss", f, x, y, w)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    x, y = _t(input), _t(label)
    w = _t(weight) if weight is not None else None

    def f(xr, yr, wr):
        C = xr.shape[-1]
        tgt = jnp.take_along_axis(xr, yr[:, None], axis=-1)
        m = jnp.maximum(margin - tgt + xr, 0) ** p
        if wr is not None:
            m = m * wr.reshape(-1)[yr][:, None]
        oh = jax.nn.one_hot(yr, C, dtype=xr.dtype)
        return _reduce(((1 - oh) * m).sum(-1) / C, reduction)
    return apply_op("multi_margin_loss", f, x, y, w, nondiff=(1,))


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Reference nn/functional/loss.py dice_loss: input (..., C) softmaxed
    probs, label (..., 1) int."""
    x, y = _t(input), _t(label)

    def f(xr, yr):
        oh = jax.nn.one_hot(yr.squeeze(-1), xr.shape[-1], dtype=xr.dtype)
        red = tuple(range(1, xr.ndim))
        inter = (xr * oh).sum(red)
        union = xr.sum(red) + oh.sum(red)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply_op("dice_loss", f, x, y, nondiff=(1,))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Reference nn/functional/loss.py npair_loss."""
    a, p, lab = _t(anchor), _t(positive), _t(labels)

    def f(ar, pr, lr):
        B = ar.shape[0]
        sim = ar @ pr.T
        same = (lr[:, None] == lr[None, :]).astype(ar.dtype)
        tgt = same / same.sum(-1, keepdims=True)
        xent = (-tgt * jax.nn.log_softmax(sim, axis=-1)).sum(-1).mean()
        reg = l2_reg * ((ar * ar).sum(-1) + (pr * pr).sum(-1)).mean() * 0.25
        return xent + reg
    return apply_op("npair_loss", f, a, p, lab, nondiff=(2,))


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    x, pos, neg = _t(input), _t(positive), _t(negative)

    def dist(a, b):
        if distance_function is not None:
            d = distance_function(a, b)
            return d._data if isinstance(d, Tensor) else d
        return jnp.sqrt(((a - b) ** 2).sum(-1) + 1e-12)

    def f(ar, pr, nr):
        dp = dist(ar, pr)
        dn = dist(ar, nr)
        if swap:
            dn = jnp.minimum(dn, dist(pr, nr))
        return _reduce(jnp.maximum(dp - dn + margin, 0), reduction)

    if distance_function is not None:
        # user distance may be an eager Tensor fn: compute eagerly
        dp = distance_function(x, pos)
        dn = distance_function(x, neg)
        if swap:
            from ...ops import minimum
            dn = minimum(dn, distance_function(pos, neg))
        from ...ops import clip, mean as _mean, sum as _sum
        val = clip(dp - dn + margin, min=0.0)
        red = {"mean": _mean, "sum": _sum, "none": lambda v: v}[reduction]
        return red(val)
    return apply_op("triplet_margin_with_distance_loss", f, x, pos, neg)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-Transducer loss (reference nn/functional/loss.py rnnt_loss;
    the reference binds warprnnt).  input: (B, T, U+1, V) log-probs or
    logits (log-softmaxed here); label: (B, U) int.  Forward-variable DP
    over the (T, U) lattice as a lax.scan over T — differentiable, static
    shapes, fastemit regularization applied like the reference."""
    x, y = _t(input), _t(label)
    tl, ul = _t(input_lengths), _t(label_lengths)

    def f(xr, yr, tlr, ulr):
        B, T, U1, V = xr.shape
        U = U1 - 1
        logp = jax.nn.log_softmax(xr.astype(jnp.float32), axis=-1)
        # per (t, u): blank prob and emit prob of the next label
        lp_blank = logp[..., blank]                        # (B, T, U+1)
        idx = jnp.minimum(yr, V - 1)                       # (B, U)
        lp_emit = jnp.take_along_axis(
            logp[:, :, :U, :], idx[:, None, :, None], axis=-1)[..., 0]
        # forward recursion: alpha[t, u] =
        #   logaddexp(alpha[t-1, u] + blank(t-1, u),
        #             alpha[t, u-1] + emit(t, u-1))
        # t = 0 row: only emissions from (0, 0)
        def init_emit(prev, u):
            cur = prev + lp_emit[:, 0, u - 1]
            return cur, cur
        f0 = jnp.zeros((B,), jnp.float32)
        _, r0 = jax.lax.scan(init_emit, f0, jnp.arange(1, U1))
        alpha = jnp.concatenate([f0[:, None], r0.T], axis=1)

        def scan_t(alpha, t):
            a_t_base = alpha + lp_blank[:, t - 1]
            def inner(prev, u):
                cur = jnp.logaddexp(a_t_base[:, u],
                                    prev + lp_emit[:, t, u - 1])
                return cur, cur
            first = a_t_base[:, 0]
            _, rest = jax.lax.scan(inner, first, jnp.arange(1, U1))
            new = jnp.concatenate([first[:, None], rest.T], axis=1)
            return new, new

        _, all_alpha = jax.lax.scan(scan_t, alpha, jnp.arange(1, T))
        all_alpha = jnp.concatenate([alpha[None], all_alpha], axis=0)
        # total log prob: alpha[T_b - 1, U_b] + blank at (T_b - 1, U_b)
        tb = jnp.clip(tlr.astype(jnp.int32) - 1, 0, T - 1)
        ub = jnp.clip(ulr.astype(jnp.int32), 0, U)
        batch = jnp.arange(B)
        ll = all_alpha[tb, batch, ub] + lp_blank[batch, tb, ub]
        loss = -ll
        if fastemit_lambda:
            loss = loss * (1.0 + fastemit_lambda)
        return _reduce(loss, reduction)

    return apply_op("rnnt_loss", f, x, y, tl, ul, nondiff=(1, 2, 3))


# ---------------------------------------------------------------------------
# inplace activations
# ---------------------------------------------------------------------------


def _act_inplace(base_name):
    def op_(x, *args, **kwargs):
        from . import __dict__ as _fns
        from ...ops import _inplace
        base = _fns[base_name]
        if (framework.is_grad_enabled() and isinstance(x, Tensor)
                and not x.stop_gradient and x._node is None):
            raise RuntimeError(
                f"{base_name}_: in-place operation on a leaf Tensor that "
                "requires grad is not allowed")
        return _inplace(x, base(x, *args, **kwargs))
    op_.__name__ = base_name + "_"
    op_.__doc__ = f"In-place variant of nn.functional.{base_name}."
    return op_


elu_ = _act_inplace("elu")
hardtanh_ = _act_inplace("hardtanh")
leaky_relu_ = _act_inplace("leaky_relu")
softmax_ = _act_inplace("softmax")
tanh_ = _act_inplace("tanh")
thresholded_relu_ = _act_inplace("thresholded_relu")
