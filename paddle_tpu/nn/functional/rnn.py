"""Functional single-step RNN cell recurrences.

Reference math: python/paddle/nn/layer/rnn.py:813 (SimpleRNNCell.forward),
:966 (LSTMCell.forward), :1125 (GRUCell.forward).  Exposed as functionals so
(a) the op-registry dtype/grad sweeps cover the cell math like any other op,
and (b) the eager cells and nn.rnn's lax.scan recurrence share ONE
implementation — the scan traces these same pure steps, so per-step eager
results and the compiled sequence are bit-identical.

Gate conventions (matching the reference exactly):
  * simple:  h' = act(x W_ih^T + b_ih + h W_hh^T + b_hh)
  * lstm:    gates split 4 -> [i, f, g, o];  c' = sig(f)c + sig(i)tanh(g);
             h' = sig(o) tanh(c')
  * gru:     x/h gates split 3 -> [r, z, c];  r = sig(x_r+h_r);
             z = sig(x_z+h_z);  c = tanh(x_c + r*h_c);  h' = z*h + (1-z)*c
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor import apply_op

__all__ = ["simple_rnn_cell", "lstm_cell", "gru_cell"]


def _simple_rnn_step(x, h, w_ih, w_hh, b_ih, b_hh, activation="tanh"):
    g = x @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        g = g + b_ih
    if b_hh is not None:
        g = g + b_hh
    return jnp.tanh(g) if activation == "tanh" else jax.nn.relu(g)


def _lstm_step(x, h, c, w_ih, w_hh, b_ih, b_hh):
    gates = x @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        gates = gates + b_ih
    if b_hh is not None:
        gates = gates + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
    return h2, c2


def _gru_step(x, h, w_ih, w_hh, b_ih, b_hh):
    xg = x @ w_ih.T
    if b_ih is not None:
        xg = xg + b_ih
    hg = h @ w_hh.T
    if b_hh is not None:
        hg = hg + b_hh
    x_r, x_z, x_c = jnp.split(xg, 3, axis=-1)
    h_r, h_z, h_c = jnp.split(hg, 3, axis=-1)
    r = jax.nn.sigmoid(x_r + h_r)
    z = jax.nn.sigmoid(x_z + h_z)
    c = jnp.tanh(x_c + r * h_c)
    return (h - c) * z + c


def simple_rnn_cell(x, h, weight_ih, weight_hh, bias_ih=None, bias_hh=None,
                    activation="tanh"):
    """One vanilla-RNN step: returns the new hidden state (batch, hidden)."""
    return apply_op("simple_rnn_cell", _simple_rnn_step, x, h, weight_ih,
                    weight_hh, bias_ih, bias_hh, activation=activation)


def lstm_cell(x, h, c, weight_ih, weight_hh, bias_ih=None, bias_hh=None):
    """One LSTM step: returns (new_h, new_c)."""
    return apply_op("lstm_cell", _lstm_step, x, h, c, weight_ih, weight_hh,
                    bias_ih, bias_hh)


def gru_cell(x, h, weight_ih, weight_hh, bias_ih=None, bias_hh=None):
    """One GRU step: returns the new hidden state (batch, hidden)."""
    return apply_op("gru_cell", _gru_step, x, h, weight_ih, weight_hh,
                    bias_ih, bias_hh)
