"""Core nn layers (python/paddle/nn/layer/{common,norm,conv,pooling,activation,loss}.py parity)."""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from .. import framework
from ..tensor import Parameter, Tensor, to_tensor
from . import functional as F
from . import initializer as I
from .layer import Layer, ParamAttr

__all__ = [
    "Linear", "Embedding", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout",
    "LayerNorm", "RMSNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
    "SyncBatchNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D", "SpectralNorm",
    "Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose",
    "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D", "AvgPool2D", "AvgPool3D",
    "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveMaxPool2D",
    "ReLU", "ReLU6", "GELU", "SiLU", "Swish", "Sigmoid", "Tanh", "Softmax", "LogSoftmax",
    "LeakyReLU", "ELU", "SELU", "CELU", "PReLU", "Hardsigmoid", "Hardswish", "Hardtanh",
    "Mish", "Softplus", "Softsign", "Softshrink", "Hardshrink", "Tanhshrink",
    "ThresholdedReLU", "Maxout", "GLU", "LogSigmoid", "Identity",
    "CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss", "BCEWithLogitsLoss",
    "SmoothL1Loss", "KLDivLoss", "MarginRankingLoss", "CTCLoss", "CosineSimilarity",
    "PairwiseDistance", "Upsample", "UpsamplingBilinear2D", "UpsamplingNearest2D",
    "PixelShuffle", "PixelUnshuffle", "ChannelShuffle", "Pad1D", "Pad2D", "Pad3D",
    "ZeroPad2D", "Flatten", "Unflatten", "Bilinear", "CosineEmbeddingLoss",
    "TripletMarginLoss", "PoissonNLLLoss", "HingeEmbeddingLoss", "Unfold", "Fold",
]


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """Weight stored as [in_features, out_features] — matches the reference
    (python/paddle/nn/layer/common.py Linear) AND is the MXU-friendly layout
    (x @ W with no transpose)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in={self.in_features}, out={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False,
                 weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if padding_idx is not None:
            self.weight._data = self.weight._data.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training, mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


# ---------------------------------------------------------------------------
# Norm layers
# ---------------------------------------------------------------------------


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(self._normalized_shape, attr=weight_attr,
                                                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(self._normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)


class RMSNorm(Layer):
    """TPU-first fused norm (reference: incubate fused_rms_norm)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter([hidden_size], attr=weight_attr,
                                            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter([num_features], attr=weight_attr,
                                            default_initializer=I.Constant(1.0)) if weight_attr is not False else None
        self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True) if bias_attr is not False else None
        # explicit f32: under jax_enable_x64 bare zeros() would be f64 and
        # poison activation dtypes through the eval path
        self.register_buffer("_mean", to_tensor(jnp.zeros(num_features, jnp.float32)))
        self.register_buffer("_variance", to_tensor(jnp.ones(num_features, jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, "NCL" if data_format == "NCL" else data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch stats sync falls out of GSPMD when batch is sharded; the
    eager path behaves like BatchNorm (reference: nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter([num_channels], attr=weight_attr,
                                            default_initializer=I.Constant(1.0)) if weight_attr is not False else None
        self.bias = self.create_parameter([num_channels], attr=bias_attr, is_bias=True) if bias_attr is not False else None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.scale = self.create_parameter([num_features], attr=weight_attr,
                                           default_initializer=I.Constant(1.0)) if weight_attr is not False else None
        self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True) if bias_attr is not False else None

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias, eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr, data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12, name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter([h], default_initializer=I.Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter([w], default_initializer=I.Normal(0, 1))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ..ops import manipulation as M
        from ..tensor import apply_op
        w_mat = weight.reshape([weight.shape[self._dim], -1]) if self._dim == 0 else \
            weight.transpose([self._dim] + [i for i in range(weight.ndim) if i != self._dim]).reshape([weight.shape[self._dim], -1])
        u, v = self.weight_u._data, self.weight_v._data
        wm = w_mat._data
        for _ in range(self._power_iters):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + self._epsilon)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + self._epsilon)
        self.weight_u._data, self.weight_v._data = u, v
        sigma = u @ wm @ v
        return apply_op("spectral_norm", lambda W: W / sigma, weight)


# ---------------------------------------------------------------------------
# Conv / pool layers
# ---------------------------------------------------------------------------


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW", transpose=False, output_padding=0):
        super().__init__()
        self._nd = nd
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self._transpose = transpose
        self._output_padding = output_padding
        k = (kernel_size,) * nd if isinstance(kernel_size, int) else tuple(kernel_size)
        if transpose:
            w_shape = [in_channels, out_channels // groups, *k]
        else:
            w_shape = [out_channels, in_channels // groups, *k]
        fan_in = in_channels * int(np.prod(k))
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in, negative_slope=math.sqrt(5)))
        bound = 1 / math.sqrt(fan_in)
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-bound, bound)) if bias_attr is not False else None


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr, data_format,
                         transpose=True, output_padding=output_padding)

    def forward(self, x):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride, self._padding,
                                  self._output_padding, self._groups, self._dilation, self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr, data_format,
                         transpose=True, output_padding=output_padding)

    def forward(self, x):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride, self._padding,
                                  self._output_padding, self._groups, self._dilation, self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr, data_format,
                         transpose=True, output_padding=output_padding)

    def forward(self, x):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride, self._padding,
                                  self._output_padding, self._groups, self._dilation, self._data_format)


class _PoolNd(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kw):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.kw = kw


class MaxPool1D(_PoolNd):
    def forward(self, x):
        return F.max_pool1d(x, self.k, self.s, self.p, **self.kw)


class MaxPool2D(_PoolNd):
    def forward(self, x):
        return F.max_pool2d(x, self.k, self.s, self.p, **self.kw)


class MaxPool3D(_PoolNd):
    def forward(self, x):
        return F.max_pool3d(x, self.k, self.s, self.p, **self.kw)


class AvgPool1D(_PoolNd):
    def forward(self, x):
        return F.avg_pool1d(x, self.k, self.s, self.p, **self.kw)


class AvgPool2D(_PoolNd):
    def forward(self, x):
        return F.avg_pool2d(x, self.k, self.s, self.p, **self.kw)


class AvgPool3D(_PoolNd):
    def forward(self, x):
        return F.avg_pool3d(x, self.k, self.s, self.p, **self.kw)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


# ---------------------------------------------------------------------------
# Activation layers
# ---------------------------------------------------------------------------


def _act_layer(name, fn, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._args = args
            self._kwargs = {**fixed, **kwargs}

        def forward(self, x):
            return fn(x, *self._args, **self._kwargs)

    _Act.__name__ = name
    return _Act


ReLU = _act_layer("ReLU", F.relu)
ReLU6 = _act_layer("ReLU6", F.relu6)
GELU = _act_layer("GELU", F.gelu)
SiLU = _act_layer("SiLU", F.silu)
Swish = _act_layer("Swish", F.swish)
Sigmoid = _act_layer("Sigmoid", F.sigmoid)
Tanh = _act_layer("Tanh", F.tanh)
Softmax = _act_layer("Softmax", F.softmax)
LogSoftmax = _act_layer("LogSoftmax", F.log_softmax)
LeakyReLU = _act_layer("LeakyReLU", F.leaky_relu)
ELU = _act_layer("ELU", F.elu)
SELU = _act_layer("SELU", F.selu)
CELU = _act_layer("CELU", F.celu)
Hardsigmoid = _act_layer("Hardsigmoid", F.hardsigmoid)
Hardswish = _act_layer("Hardswish", F.hardswish)
Hardtanh = _act_layer("Hardtanh", F.hardtanh)
Mish = _act_layer("Mish", F.mish)
Softplus = _act_layer("Softplus", F.softplus)
Softsign = _act_layer("Softsign", F.softsign)
Softshrink = _act_layer("Softshrink", F.softshrink)
Hardshrink = _act_layer("Hardshrink", F.hardshrink)
Tanhshrink = _act_layer("Tanhshrink", F.tanhshrink)
ThresholdedReLU = _act_layer("ThresholdedReLU", F.thresholded_relu)
Maxout = _act_layer("Maxout", F.maxout)
GLU = _act_layer("GLU", F.glu)
LogSigmoid = _act_layer("LogSigmoid", F.log_sigmoid)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter([num_parameters], attr=weight_attr,
                                            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


# ---------------------------------------------------------------------------
# Loss layers
# ---------------------------------------------------------------------------


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", soft_label=False,
                 axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight, ignore_index=self.ignore_index,
                               reduction=self.reduction, soft_label=self.soft_label,
                               axis=self.axis, use_softmax=self.use_softmax,
                               label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index, self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.weight, self.reduction, self.pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths, norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths, self.blank, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin, self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
        super().__init__()
        self.margin, self.p, self.epsilon, self.swap, self.reduction = margin, p, epsilon, swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, self.margin, self.p, self.epsilon, self.swap, self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8, reduction="mean", name=None):
        super().__init__()
        self.log_input, self.full, self.epsilon, self.reduction = log_input, full, epsilon, reduction

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, self.log_input, self.full, self.epsilon, self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


# ---------------------------------------------------------------------------
# Misc layers
# ---------------------------------------------------------------------------


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False,
                 align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor, self.mode = size, scale_factor, mode
        self.align_corners, self.align_mode, self.data_format = align_corners, align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, data_format=data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, data_format=data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.r = upscale_factor

    def forward(self, x):
        return F.pixel_shuffle(x, self.r)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.r = downscale_factor

    def forward(self, x):
        return F.pixel_unshuffle(x, self.r)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups

    def forward(self, x):
        return F.channel_shuffle(x, self.groups)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding, self.mode, self.value, self.data_format = padding, mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    pass


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(_PadNd):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ..ops import manipulation as M
        return M.flatten(x, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        from ..ops import manipulation as M
        new_shape = x.shape[:self.axis] + list(self.shape) + x.shape[self.axis + 1:]
        return M.reshape(x, new_shape)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter([out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([1, out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.k, self.s, self.p, self.d = kernel_sizes, strides, paddings, dilations

    def forward(self, x):
        return F.unfold(x, self.k, self.s, self.p, self.d)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.o, self.k, self.s, self.p, self.d = output_sizes, kernel_sizes, strides, paddings, dilations

    def forward(self, x):
        return F.fold(x, self.o, self.k, self.s, self.p, self.d)
