"""nn.Layer base (python/paddle/nn/layer/layers.py:340 Layer parity).

A Layer owns Parameters (trainable Tensors) and sub-layers; it is the stateful
veneer over JAX's functional core.  The jit engine (paddle_tpu/jit) can lift any
Layer into a pure (params, buffers, inputs) -> outputs function for XLA
compilation — that lift is what replaces the reference's dygraph→static
ProgramTranslator (jit/dy2static/program_translator.py:313).
"""

from __future__ import annotations

import collections
from typing import Callable, Iterator

import jax.numpy as jnp
import numpy as np

from .. import framework
from ..framework import convert_dtype, to_jax_dtype
from ..tensor import Parameter, Tensor, to_tensor
from . import initializer as I

__all__ = ["Layer", "ParamAttr", "Sequential", "LayerList", "ParameterList", "LayerDict"]


class ParamAttr:
    """python/paddle/base/param_attr.py parity."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0, regularizer=None,
                 trainable=True, do_model_average=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        raise TypeError(f"Invalid param attr {attr!r}")


class HookRemoveHelper:
    def __init__(self, hooks, key):
        self._hooks, self._key = hooks, key

    def remove(self):
        self._hooks.pop(self._key, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        self._dtype = convert_dtype(dtype) if dtype else framework.get_default_dtype()
        self.training = True
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_counter = 0
        self._name_scope = name_scope or type(self).__name__.lower()

    # -- attribute plumbing ------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            layers.pop(name, None) if layers else None
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
            params.pop(name, None) if params else None
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    params.pop(name)
                    object.__setattr__(self, name, None)
                    return
                params[name] = value
                return
            if buffers is not None and name in buffers:
                buffers[name] = value if value is None or isinstance(value, Tensor) else to_tensor(value)
                return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._sub_layers) + list(self._buffers)

    # -- parameter creation -----------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) if dtype else self._dtype
        init = attr.initializer or default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else (I._global_weight_init or I.XavierNormal())
        data = init(shape, dtype)
        p = Parameter(data, name=attr.name, trainable=attr.trainable)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = getattr(attr, "need_clip", True)
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = to_tensor(tensor)
        if tensor is not None:
            tensor.persistable = persistable
        self._buffers[name] = tensor
        return tensor

    # -- traversal ---------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b
            if not include_sublayers:
                break

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from l.named_sublayers(prefix=sub_prefix, include_self=True)

    def apply(self, fn: Callable):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- modes -------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.children():
            l.train()
        return self

    def eval(self):
        self.training = False
        for l in self.children():
            l.eval()
        return self

    # -- state dict --------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True,
                   structured_name_prefix=""):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix.rstrip(".")):
            if b is not None and b.persistable:
                dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, t in own.items():
            if name in state_dict:
                v = state_dict[name]
                raw = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                if tuple(raw.shape) != tuple(t._data.shape):
                    raise ValueError(f"shape mismatch for {name}: {raw.shape} vs {t._data.shape}")
                t._data = raw.astype(t._data.dtype)
            else:
                missing.append(name)
        for k in state_dict:
            if k not in own:
                unexpected.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dtype = convert_dtype(dtype)
            jd = to_jax_dtype(dtype)
            for p in self.parameters():
                if framework.is_floating_dtype(p.dtype):
                    p._data = p._data.astype(jd)
            for b in self.buffers():
                if b is not None and framework.is_floating_dtype(b.dtype):
                    b._data = b._data.astype(jd)
            self._dtype = dtype
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # -- hooks -------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_counter += 1
        self._forward_pre_hooks[self._hook_counter] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_counter)

    def register_forward_post_hook(self, hook):
        self._hook_counter += 1
        self._forward_post_hooks[self._hook_counter] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_counter)

    # -- call --------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def __repr__(self):
        lines = [f"{type(self).__name__}("]
        for name, l in self._sub_layers.items():
            sub = repr(l).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub}")
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else f"{type(self).__name__}()"

    def full_name(self):
        return self._name_scope


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, l in layers[0].items():
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                if isinstance(l, tuple):
                    self.add_sublayer(l[0], l[1])
                else:
                    self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for l in self._sub_layers.values():
            x = l(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self._parameters[str(i)] = p

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self._parameters[str(len(self._parameters))] = parameter
        return self


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, (dict, collections.OrderedDict, LayerDict)) else sublayers
        for k, v in items:
            self.add_sublayer(k, v)
        return self
