"""paddle.text — sequence decoding utilities (SURVEY C48; reference
python/paddle/text/viterbi_decode.py).

TPU-native: the Viterbi forward pass is a `lax.scan` over time with a
batched max-plus recurrence — jittable, static shapes, on the VPU.  The
reference's dataset downloaders (text/datasets) are out of scope for an
offline build; load corpora through paddle_tpu.io datasets instead.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor, to_tensor

__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets",
           "Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16",
           "Conll05st"]

from . import datasets  # noqa: E402,F401
from .datasets import (  # noqa: E402,F401
    Conll05st, Imdb, Imikolov, Movielens, WMT14, WMT16,
)
from .datasets import UciHousing as UCIHousing  # noqa: E402 — ref spelling


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """Highest-scoring tag path under a linear-chain CRF.

    potentials: (B, S, T) emissions; transition_params: (T, T);
    lengths: (B,).  Returns (scores (B,), paths (B, S) int64) — positions
    at or past each sequence's length are 0, like the reference kernel
    (phi/kernels/cpu/viterbi_decode_kernel.cc).  With
    include_bos_eos_tag, the last tag is BOS and the second-to-last is EOS
    (transition row/column convention of the reference).
    """
    em = _raw(potentials).astype(jnp.float32)
    trans = _raw(transition_params).astype(jnp.float32)
    lens = _raw(lengths).astype(jnp.int32)
    B, S, T = em.shape

    alpha0 = em[:, 0]
    if include_bos_eos_tag:
        alpha0 = alpha0 + trans[-1][None, :]
    ident = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))

    def step(alpha, t):
        scores = alpha[:, :, None] + trans[None, :, :]   # (B, from, to)
        best_prev = jnp.argmax(scores, axis=1).astype(jnp.int32)
        alpha_new = jnp.max(scores, axis=1) + em[:, t]
        active = (t < lens)[:, None]
        # finished sequences freeze their alpha; their backpointer is the
        # identity so the backtrace carries the final tag through unchanged
        return (jnp.where(active, alpha_new, alpha),
                jnp.where(active, best_prev, ident))

    if S > 1:
        alpha, bps = jax.lax.scan(
            lambda a, t: step(a, t), alpha0, jnp.arange(1, S))
    else:
        alpha, bps = alpha0, jnp.zeros((0, B, T), jnp.int32)

    if include_bos_eos_tag:
        alpha = alpha + trans[:, -2][None, :]

    scores = jnp.max(alpha, axis=1)
    last_tag = jnp.argmax(alpha, axis=1).astype(jnp.int32)

    def backtrace(tag, bp_t):
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        return prev, tag  # carry: tag at t-1; emit: tag at t

    if S > 1:
        first_tag, emitted = jax.lax.scan(backtrace, last_tag, bps,
                                          reverse=True)
        paths = jnp.concatenate(
            [first_tag[:, None], jnp.swapaxes(emitted, 0, 1)], axis=1)
    else:
        paths = last_tag[:, None]

    pos = jnp.arange(S)[None, :]
    paths = jnp.where(pos < lens[:, None], paths, 0).astype(jnp.int64)
    return to_tensor(scores), to_tensor(paths)


class ViterbiDecoder:
    """Layer form (reference text/viterbi_decode.py ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
