"""paddle.text.datasets — Imdb / Conll05st / Imikolov / UciHousing
(reference python/paddle/text/datasets/{imdb.py,conll05.py,imikolov.py,
uci_housing.py}).

The reference datasets download public corpora at construction time; this
environment has zero egress, so every dataset here is FILE-BASED first
(`data_file=` points at a local corpus in a simple documented format) with
a deterministic synthetic fallback (`data_file=None`) sized like the real
corpus splits — the data-pipeline shape (vocab build, tokenization,
__getitem__ tuples) matches the reference exactly, so swapping in the real
files is a path change.
"""

from __future__ import annotations

import gzip
import os
import re
import tarfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Conll05st", "Imikolov", "UciHousing",
           "WMT14", "WMT16", "Movielens"]


def _synth_rng(seed):
    return np.random.default_rng(seed)


class Imdb(Dataset):
    """IMDB sentiment dataset (reference text/datasets/imdb.py:1).

    data_file: directory with pos/*.txt and neg/*.txt (or a .tar.gz with
    train/pos etc. like the real aclImdb tarball); None -> synthetic
    reviews with a class-correlated vocabulary.  Items: (ids int64[seq],
    label int64) with label 0=positive, 1=negative (reference order).
    """

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150, n_synthetic: int = 200):
        if mode not in ("train", "test"):
            raise ValueError(f"mode must be train|test, got {mode}")
        self.mode = mode
        docs: List[Tuple[str, int]] = []
        if data_file is None:
            rng = _synth_rng(0 if mode == "train" else 1)
            pos_w = ["great", "superb", "moving", "classic", "brilliant"]
            neg_w = ["awful", "boring", "wooden", "mess", "forgettable"]
            common = ["the", "movie", "plot", "acting", "scene", "it",
                      "was", "and", "a", "of"]
            for i in range(n_synthetic):
                lab = i % 2          # 0 pos, 1 neg
                themed = pos_w if lab == 0 else neg_w
                n = int(rng.integers(8, 40))
                words = rng.choice(common + themed * 2, size=n)
                docs.append((" ".join(words), lab))
        else:
            docs = self._read_corpus(data_file, mode)
        self._build(docs, cutoff)

    @staticmethod
    def _read_corpus(path, mode):
        docs = []
        if os.path.isdir(path):
            for lab, sub in ((0, "pos"), (1, "neg")):
                d = os.path.join(path, sub)
                for fn in sorted(os.listdir(d)):
                    with open(os.path.join(d, fn), errors="ignore") as f:
                        docs.append((f.read(), lab))
        else:  # aclImdb-style tarball
            pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
            with tarfile.open(path) as tf:
                for m in tf.getmembers():
                    g = pat.match(m.name)
                    if g:
                        lab = 0 if g.group(1) == "pos" else 1
                        docs.append(
                            (tf.extractfile(m).read().decode(
                                errors="ignore"), lab))
        return docs

    def _build(self, docs, cutoff):
        freq: Dict[str, int] = {}
        tokenized = []
        for text, lab in docs:
            toks = re.findall(r"[a-z']+", text.lower())
            tokenized.append((toks, lab))
            for t in toks:
                freq[t] = freq.get(t, 0) + 1
        vocab = sorted([w for w, c in freq.items()], key=lambda w: (-freq[w], w))
        if cutoff:
            vocab = vocab[:cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.asarray([self.word_idx.get(t, unk) for t in toks],
                                np.int64) for toks, _ in tokenized]
        self.labels = [np.int64(lab) for _, lab in tokenized]

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]

    def __len__(self):
        return len(self.docs)


class Conll05st(Dataset):
    """CoNLL-2005 SRL dataset (reference text/datasets/conll05.py:1).

    Items mirror the reference's 9-column SRL tuple: word ids, 6 predicate
    context windows, mark flags, label ids.  data_file: a whitespace
    "word label" sentence-per-block file; None -> synthetic sentences.
    """

    PRED_WINDOW = 5

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 n_synthetic: int = 60):
        sents: List[Tuple[List[str], List[str]]] = []
        if data_file is None:
            rng = _synth_rng(2 if mode == "train" else 3)
            verbs = ["run", "take", "give", "see"]
            nouns = ["dog", "cat", "man", "ball", "park"]
            for _ in range(n_synthetic):
                n = int(rng.integers(4, 10))
                words, labels = [], []
                vpos = int(rng.integers(0, n))
                for j in range(n):
                    if j == vpos:
                        words.append(str(rng.choice(verbs)))
                        labels.append("B-V")
                    else:
                        words.append(str(rng.choice(nouns)))
                        labels.append("B-A0" if j < vpos else "B-A1")
                sents.append((words, labels))
        else:
            with open(data_file, errors="ignore") as f:
                words, labels = [], []
                for line in f:
                    line = line.strip()
                    if not line:
                        if words:
                            sents.append((words, labels))
                        words, labels = [], []
                        continue
                    w, lab = line.split()[:2]
                    words.append(w)
                    labels.append(lab)
                if words:
                    sents.append((words, labels))

        words_v = sorted({w for ws, _ in sents for w in ws})
        labels_v = sorted({l for _, ls in sents for l in ls})
        self.word_dict = {w: i for i, w in enumerate(words_v)}
        self.label_dict = {l: i for i, l in enumerate(labels_v)}
        self.predicate_dict = dict(self.word_dict)
        self._items = []
        for ws, ls in sents:
            if "B-V" not in ls:
                continue
            vpos = ls.index("B-V")
            ids = np.asarray([self.word_dict[w] for w in ws], np.int64)
            # 5-token predicate context window (reference ctx_n2..ctx_p2)
            ctx = []
            for off in range(-2, 3):
                j = min(max(vpos + off, 0), len(ws) - 1)
                ctx.append(np.full_like(ids, ids[j]))
            mark = np.zeros_like(ids)
            mark[vpos] = 1
            lab = np.asarray([self.label_dict[l] for l in ls], np.int64)
            pred = np.full_like(ids, ids[vpos])
            self._items.append((ids, pred, *ctx, mark, lab))

    def __getitem__(self, i):
        return self._items[i]

    def __len__(self):
        return len(self._items)


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset (reference text/datasets/imikolov.py).

    data_type='NGRAM' yields window tuples; 'SEQ' yields (src, trg)
    shifted sequences.  data_file: one sentence per line; None ->
    synthetic sentences.
    """

    def __init__(self, data_file: Optional[str] = None, data_type="NGRAM",
                 window_size: int = 5, mode: str = "train",
                 min_word_freq: int = 1, n_synthetic: int = 100):
        if data_type not in ("NGRAM", "SEQ"):
            raise ValueError("data_type must be NGRAM or SEQ")
        self.data_type = data_type
        self.window_size = window_size
        if data_file is None:
            rng = _synth_rng(4 if mode == "train" else 5)
            base = ["one", "two", "three", "four", "five", "six", "seven"]
            lines = [" ".join(rng.choice(base, size=int(rng.integers(6, 14))))
                     for _ in range(n_synthetic)]
        else:
            opener = gzip.open if data_file.endswith(".gz") else open
            with opener(data_file, "rt", errors="ignore") as f:
                lines = [l.strip() for l in f if l.strip()]

        freq: Dict[str, int] = {}
        toks_per_line = []
        for l in lines:
            toks = l.split()
            toks_per_line.append(toks)
            for t in toks:
                freq[t] = freq.get(t, 0) + 1
        vocab = sorted([w for w, c in freq.items() if c >= min_word_freq])
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        self.word_idx["<s>"] = len(self.word_idx)
        self.word_idx["<e>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self._items = []
        for toks in toks_per_line:
            ids = ([self.word_idx["<s>"]]
                   + [self.word_idx.get(t, unk) for t in toks]
                   + [self.word_idx["<e>"]])
            if data_type == "NGRAM":
                if len(ids) < window_size:
                    continue
                for j in range(window_size, len(ids) + 1):
                    self._items.append(
                        np.asarray(ids[j - window_size:j], np.int64))
            else:
                self._items.append((np.asarray(ids[:-1], np.int64),
                                    np.asarray(ids[1:], np.int64)))

    def __getitem__(self, i):
        return self._items[i]

    def __len__(self):
        return len(self._items)


class UciHousing(Dataset):
    """Boston-housing regression dataset (reference
    text/datasets/uci_housing.py).  13 normalized features -> price.
    data_file: whitespace-delimited 14-column file; None -> synthetic
    linear data with noise (deterministic)."""

    FEATURES = 13

    def __init__(self, data_file: Optional[str] = None, mode: str = "train"):
        if data_file is None:
            rng = _synth_rng(6)
            n = 506
            x = rng.normal(size=(n, self.FEATURES)).astype(np.float32)
            w = rng.normal(size=(self.FEATURES,)).astype(np.float32)
            y = (x @ w + 0.1 * rng.normal(size=n)).astype(np.float32)
            data = np.concatenate([x, y[:, None]], axis=1)
        else:
            data = np.loadtxt(data_file).astype(np.float32)
            if data.shape[1] != self.FEATURES + 1:
                raise ValueError(
                    f"expected {self.FEATURES + 1} columns, got "
                    f"{data.shape[1]}")
        # reference normalization: feature-wise max/min scaling on train
        mx, mn, avg = data.max(0), data.min(0), data.mean(0)
        span = np.where(mx - mn == 0, 1, mx - mn)
        data[:, :-1] = (data[:, :-1] - avg[:-1]) / span[:-1]
        split = int(len(data) * 0.8)
        self.data = data[:split] if mode == "train" else data[split:]

    def __getitem__(self, i):
        row = self.data[i]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


BOS, EOS, UNK = "<s>", "<e>", "<unk>"
BOS_IDX, EOS_IDX, UNK_IDX = 0, 1, 2


class _WMTBase(Dataset):
    """Shared machinery for WMT14/WMT16 (reference text/datasets/wmt14.py:40,
    wmt16.py).  data_file: a plain text file of "src<TAB>trg" sentence pairs
    (one per line); None -> synthetic parallel corpus (target = reversed
    source over a shared toy vocabulary).  Items: (src_ids, trg_ids,
    trg_ids_next) int64 arrays; ids 0/1/2 are <s>/<e>/<unk>.
    """

    def _build(self, pairs, src_dict_size, trg_dict_size):
        def vocab(sents, size):
            from collections import Counter
            cnt = Counter(w for s in sents for w in s)
            words = [w for w, _ in cnt.most_common()]
            if size > 0:
                words = words[:max(0, size - 3)]
            d = {BOS: BOS_IDX, EOS: EOS_IDX, UNK: UNK_IDX}
            for w in words:
                d[w] = len(d)
            return d

        srcs = [p[0] for p in pairs]
        trgs = [p[1] for p in pairs]
        self.src_dict = vocab(srcs, src_dict_size)
        self.trg_dict = vocab(trgs, trg_dict_size)
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        for s, t in zip(srcs, trgs):
            si = [self.src_dict.get(w, UNK_IDX) for w in s]
            ti = [self.trg_dict.get(w, UNK_IDX) for w in t]
            self.src_ids.append(np.array(si, np.int64))
            self.trg_ids.append(np.array([BOS_IDX] + ti, np.int64))
            self.trg_ids_next.append(np.array(ti + [EOS_IDX], np.int64))

    def _load_pairs(self, data_file, mode, n_synthetic, seed):
        pairs = []
        if data_file is None:
            rng = _synth_rng(seed)
            vocab = ["ich", "du", "haus", "hund", "buch", "rot", "blau",
                     "geht", "sieht", "klein"]
            for _ in range(n_synthetic):
                n = int(rng.integers(3, 9))
                src = [str(w) for w in rng.choice(vocab, size=n)]
                pairs.append((src, src[::-1]))
        else:
            with open(data_file, errors="ignore") as f:
                for line in f:
                    if "\t" not in line:
                        continue
                    s, t = line.rstrip("\n").split("\t")[:2]
                    pairs.append((s.split(), t.split()))
        return pairs

    def __getitem__(self, idx):
        return (self.src_ids[idx], self.trg_ids[idx], self.trg_ids_next[idx])

    def __len__(self):
        return len(self.src_ids)


class WMT14(_WMTBase):
    """Reference text/datasets/wmt14.py:40."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 dict_size: int = -1, n_synthetic: int = 80):
        if mode not in ("train", "test", "gen"):
            raise ValueError(f"mode must be train|test|gen, got {mode}")
        self.mode = mode
        seed = {"train": 10, "test": 11, "gen": 12}[mode]
        pairs = self._load_pairs(data_file, mode, n_synthetic, seed)
        self._build(pairs, dict_size, dict_size)

    def get_dict(self, reverse=False):
        """(src_dict, trg_dict); reverse -> id-to-word maps."""
        if reverse:
            return ({v: k for k, v in self.src_dict.items()},
                    {v: k for k, v in self.trg_dict.items()})
        return self.src_dict, self.trg_dict


class WMT16(_WMTBase):
    """Reference text/datasets/wmt16.py (en-de, separate dict sizes)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 src_dict_size: int = -1, trg_dict_size: int = -1,
                 lang: str = "en", n_synthetic: int = 80):
        if mode not in ("train", "test", "val"):
            raise ValueError(f"mode must be train|test|val, got {mode}")
        self.mode = mode
        self.lang = lang
        seed = {"train": 20, "test": 21, "val": 22}[mode]
        pairs = self._load_pairs(data_file, mode, n_synthetic, seed)
        self._build(pairs, src_dict_size, trg_dict_size)

    def get_dict(self, lang="en", reverse=False):
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else d


class Movielens(Dataset):
    """MovieLens-1M ratings (reference text/datasets/movielens.py:106).

    data_file: a directory (or .tar-style layout) holding users.dat,
    movies.dat, ratings.dat in the `::`-separated MovieLens format; None ->
    synthetic users/movies/ratings.  Items match the reference tuple:
    (user_id, gender, age, job, movie_id, categories, title, rating) —
    each a np.array, category/title entries variable-length.
    """

    AGES = [1, 18, 25, 35, 45, 50, 56]

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 test_ratio: float = 0.1, rand_seed: int = 0,
                 n_synthetic: int = 120):
        if mode not in ("train", "test"):
            raise ValueError(f"mode must be train|test, got {mode}")
        rng = _synth_rng(rand_seed)
        if data_file is None:
            cats = ["Action", "Comedy", "Drama", "Horror", "Sci-Fi"]
            words = ["the", "of", "night", "return", "story", "city",
                     "dream", "last"]
            users = [(u + 1, rng.choice(["M", "F"]),
                      int(rng.choice(self.AGES)), int(rng.integers(0, 21)))
                     for u in range(16)]
            movies = []
            for m in range(24):
                n_c = int(rng.integers(1, 3))
                n_w = int(rng.integers(1, 4))
                movies.append((m + 1,
                               list(rng.choice(cats, n_c, replace=False)),
                               " ".join(rng.choice(words, n_w))))
            ratings = [(int(rng.integers(0, 16)) + 1,
                        int(rng.integers(0, 24)) + 1,
                        float(rng.integers(1, 6)))
                       for _ in range(n_synthetic)]
        else:
            users, movies, ratings = self._parse_dir(data_file)

        cat_dict: Dict[str, int] = {}
        title_dict: Dict[str, int] = {}
        for _, cs, title in movies:
            for c in cs:
                cat_dict.setdefault(c, len(cat_dict))
            for w in title.split():
                title_dict.setdefault(w.lower(), len(title_dict))
        self.categories_dict = cat_dict
        self.movie_title_dict = title_dict
        user_info = {u[0]: u for u in users}
        movie_info = {m[0]: m for m in movies}
        self.max_movie_id = max(movie_info) if movie_info else 0
        self.max_user_id = max(user_info) if user_info else 0

        data = []
        for uid, mid, rating in ratings:
            if uid not in user_info or mid not in movie_info:
                continue
            _, gender, age, job = user_info[uid]
            _, cs, title = movie_info[mid]
            data.append((
                np.array([uid], np.int64),
                np.array([0 if gender == "M" else 1], np.int64),
                np.array([self.AGES.index(age)], np.int64),
                np.array([job], np.int64),
                np.array([mid], np.int64),
                np.array([cat_dict[c] for c in cs], np.int64),
                np.array([title_dict[w.lower()] for w in title.split()],
                         np.int64),
                np.array([rating], np.float32),
            ))
        is_test = rng.random(len(data)) < test_ratio
        self.data = [d for d, t in zip(data, is_test)
                     if t == (mode == "test")]

    @staticmethod
    def _parse_dir(path):
        def rows(name):
            with open(os.path.join(path, name), errors="ignore") as f:
                return [line.rstrip("\n").split("::") for line in f if line.strip()]
        users = [(int(r[0]), r[1], int(r[2]), int(r[3]))
                 for r in rows("users.dat")]
        movies = [(int(r[0]), r[2].split("|"), r[1]) for r in rows("movies.dat")]
        ratings = [(int(r[0]), int(r[1]), float(r[2]))
                   for r in rows("ratings.dat")]
        return users, movies, ratings

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)
