"""Benchmark: Llama training throughput on the available device.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.
Metric follows BASELINE.json ("PaddleNLP Llama tokens/sec/chip"); vs_baseline is
achieved-MFU / 0.40 (the north-star 40% MFU target), so 1.0 == target met.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def _probe_backend(timeout: float = 240.0) -> str:
    """Ask a subprocess what platform jax lands on.  The axon TPU plugin can
    block indefinitely when the tunnel is down — probing in a child process
    with a timeout keeps this process un-wedged and able to fall back to CPU."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout)
        if out.returncode == 0:
            return out.stdout.strip().splitlines()[-1]
        return f"error: rc={out.returncode} {out.stderr.strip()[-300:]}"
    except subprocess.TimeoutExpired:
        return "error: backend probe timed out"
    except Exception as e:  # noqa: BLE001
        return f"error: {e!r}"


try:
    _PROBE_TIMEOUT = float(os.environ.get("BENCH_PROBE_TIMEOUT", "240"))
except ValueError:
    _PROBE_TIMEOUT = 240.0
# --sub children inherit the parent's probe result instead of re-probing
_BACKEND = os.environ.get("BENCH_BACKEND") or _probe_backend(_PROBE_TIMEOUT)
if _BACKEND != "tpu":
    # fall back to CPU before the first in-process jax import/device touch
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

if _BACKEND != "tpu":
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def _peak_flops(device) -> float:
    # one chip table, one truth: paddle_tpu.obs.mfu owns it (0.0 on CPU)
    from paddle_tpu.obs import mfu as obs_mfu

    return obs_mfu.device_peak_flops(device)



def _run_with_unroll(run, cfg, on_tpu):
    """Time `run(cfg')` with unrolled blocks, falling back to lax.scan.

    Unrolling the stacked blocks for the timed run lets XLA schedule across
    block boundaries (measured on v5e: llama 19,880 vs 19,809 tok/s, DiT
    140.9 vs 139.0 img/s, MoE 40.6k vs 40.4k).  Returns (dt, loss,
    layers_note).  The fallback executes AFTER the except block so the
    failed attempt's exception/traceback no longer pins its ~10 GB of
    device buffers — two full train states cannot coexist in 16 GB HBM.
    """
    import dataclasses
    import gc

    if not on_tpu:
        dt, loss = run(cfg)
        return dt, loss, "scan"
    note = None
    try:
        dt, loss = run(dataclasses.replace(cfg, scan_layers=False))
        return dt, loss, "unrolled"
    except Exception as e:  # noqa: BLE001 — long unrolled compile may die
        note = f"scan (unroll failed: {e!r:.120})"
    gc.collect()
    dt, loss = run(cfg)
    return dt, loss, note


def _timed_steps(st, params, opt_state, batch, steps, on_warm=None):
    """Compile+warm once, then time `steps` steps.  Completion is forced via
    a host transfer (float(loss)), NOT block_until_ready — remote-execution
    backends (axon tunnel) can report ready before the computation finishes.
    `on_warm` fires between the warmup step and the clock (the recompile
    sentinel baselines its cache-size snapshot there).  Returns
    (dt_seconds, final_loss)."""
    params, opt_state, m = st.step(params, opt_state, batch)
    float(m["loss"])
    if on_warm is not None:
        on_warm()
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, m = st.step(params, opt_state, batch)
    final_loss = float(m["loss"])
    dt = time.perf_counter() - t0
    return dt, final_loss


def bench_dit(dev, on_tpu):
    """DiT diffusion training throughput (BASELINE config 4: conv +
    attention).  Returns the sub-benchmark dict merged into extra."""
    import dataclasses

    from paddle_tpu.models import dit
    from paddle_tpu.models.dit import DiTConfig
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.distributed.parallelize import ShardedTrainState
    from paddle_tpu.optimizer.functional import AdamW

    if on_tpu:
        # DiT-XL/2 on the 32x32x4 SD latent grid (~675M params): the same
        # class as the reference's SD3/DiT capability target.  TPU-tuned
        # head layout: 9 heads x 128 = 1152 (head_dim 128 rides the Pallas
        # flash kernel + MXU tiling; 16x72 measured 44.0% MFU, 9x128 45.9%).
        # Full remat: measured B=32..64 without remat OOM 16G HBM.
        # attn_impl="xla": at N=256 tokens the (B,H,N,N) scores are small
        # and XLA's fused softmax beats the flash kernel's grid overhead —
        # chip A/B measured 138.4 img/s (xla) vs 134.4 (flash); fused_qkv
        # measured SLOWER (125/116) — the per-layer weight concat isn't free.
        cfg = dataclasses.replace(DiTConfig.XL_2(), num_heads=9,
                                  attn_impl="xla")
        # B sweep on chip: 128 -> 138.4 img/s, 160 -> 139.0 (50.2% MFU),
        # 192 -> 134.2, 224 OOM
        B, steps = 160, 10
    else:
        cfg = DiTConfig.tiny()
        B, steps = 4, 3

    mesh = mesh_lib.make_mesh(data=1)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.standard_normal(
        (B, cfg.in_channels, cfg.image_size, cfg.image_size)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, cfg.num_classes, (B,)), jnp.int32)

    def run(c, n_steps):
        # fresh state per run, freed before the next one: two XL/2 states
        # (params + AdamW each ~9.5 GB) cannot coexist in 16 GB HBM, so the
        # A/B pays a recompile per leg instead of holding both
        import gc
        st = ShardedTrainState(
            c, dit, mesh, AdamW(learning_rate=1e-4, grad_clip_norm=1.0))
        params, opt_state = st.init(jax.random.PRNGKey(0))
        batch = st.shard_batch(
            dit.dit_batch(images, labels, jax.random.PRNGKey(1), c))
        out = _timed_steps(st, params, opt_state, batch, n_steps)
        del st, params, opt_state, batch
        gc.collect()
        return out

    fused_note = "off"
    if on_tpu:
        # A/B the fused-adaLN Pallas path vs the XLA-fused composition on
        # the real chip (short trials), keep the winner for the timed run.
        # Mosaic lowering failures surface at jit-compile time (outside the
        # kernel dispatcher's fallback), so contain them here.
        dt_plain, _ = run(cfg, 3)
        try:
            dt_fused, _ = run(dataclasses.replace(cfg, fused_adaln=True), 3)
        except Exception as e:  # noqa: BLE001
            dt_fused, fused_note = float("inf"), f"error: {e!r:.120}"
        if dt_fused < dt_plain:
            cfg = dataclasses.replace(cfg, fused_adaln=True)
            fused_note = "on"
        elif not fused_note.startswith("error"):
            fused_note = f"off (fused was {dt_fused / dt_plain:.2f}x)"
    # final timed run unrolls the 28 blocks; the A/B legs above stay
    # scanned (fast compiles)
    dt, final_loss, layers_note = _run_with_unroll(
        lambda c: run(c, steps), cfg, on_tpu)
    img_per_sec = B * steps / dt
    peak = _peak_flops(dev)
    mfu = (img_per_sec * 3 * dit.flops_per_image(cfg) / peak) if peak else 0.0
    return {
        "metric": "dit_train_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec/chip",
        "mfu": round(mfu, 4),
        "model": "DiT-XL/2" if on_tpu else "tiny",
        "model_params": dit.num_params(cfg),
        "fused_adaln": fused_note,
        "layers": layers_note,
        "batch": B, "steps": steps, "loss": final_loss,
        "latent": f"{cfg.image_size}x{cfg.image_size}x{cfg.in_channels}",
    }


def bench_moe(dev, on_tpu):
    """MoE Llama training throughput (BASELINE config 5: expert-parallel
    MoE).  Single-chip: experts colocated, same GShard dispatch path that
    shards over the `expert` mesh axis multi-chip.

    Headline: the dropless "gmm" dispatch (Pallas grouped matmul — no
    capacity padding, no token drops).  The capacity-based scatter mode
    runs as a comparison leg; its dropped_fraction and both throughputs
    land in the extra dict."""
    import dataclasses
    from paddle_tpu.models import llama, moe_llama
    from paddle_tpu.models.moe_llama import MoELlamaConfig
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.distributed.parallelize import ShardedTrainState
    from paddle_tpu.optimizer.functional import AdamW

    if on_tpu:
        # Mixtral-style 8-expert top-2 slice (~640M params incl. experts).
        # Head layout 8x128 (not 16x64): same H*D, but head_dim 128 rides
        # the flash kernel's lane tile natively — chip A/B measured 40.4k
        # tok/s / 40.6% MFU vs 31.8k / 32.1% for 16x64 (whose D=64 pays the
        # pad-to-128 attention overhead).
        cfg = MoELlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=8, num_attention_heads=8,
            num_key_value_heads=4, max_position_embeddings=8192,
            dtype=jnp.bfloat16, remat=True, num_experts=8, moe_top_k=2,
            moe_dispatch="gmm")
        # gmm is dropless: compute scales with the actual per-expert load
        # instead of capacity padding (scatter at cf=1.25 pays ~25% extra
        # expert FLOPs and still drops overflow).  Same headline shape
        # B2/S8192 the scatter mode unlocked (no (N,X,C) one-hot tensors).
        B, S, steps = 2, 8192, 10
    else:
        cfg = dataclasses.replace(MoELlamaConfig.tiny(), moe_dispatch="gmm")
        B, S, steps = 4, 64, 3

    mesh = mesh_lib.make_mesh(data=1)
    tokens = np.random.default_rng(0).integers(0, cfg.vocab_size, (B, S + 1))

    def run(c):
        import gc
        st = ShardedTrainState(c, moe_llama, mesh,
                               AdamW(learning_rate=1e-4, grad_clip_norm=1.0))
        params, opt_state = st.init(jax.random.PRNGKey(0))
        batch = st.shard_batch(llama.lm_batch_from_tokens(
            jnp.asarray(tokens, dtype=jnp.int32)))
        out = _timed_steps(st, params, opt_state, batch, steps)
        del st, params, opt_state, batch
        gc.collect()
        return out

    # comparison leg: capacity-based scatter dispatch, same everything else
    scatter_cfg = dataclasses.replace(cfg, moe_dispatch="scatter")
    dt_scatter, _, _ = _run_with_unroll(run, scatter_cfg, on_tpu)
    dt, final_loss, layers_note = _run_with_unroll(run, cfg, on_tpu)
    tok_per_sec = B * S * steps / dt
    peak = _peak_flops(dev)
    mfu = (tok_per_sec * moe_llama.flops_per_token(cfg, S) / peak) \
        if peak else 0.0

    # dropped_fraction of the capacity-based mode at this shape (init
    # params; gmm drops nothing by construction)
    try:
        ids = jnp.asarray(tokens[:, :-1], jnp.int32)
        stats = jax.jit(lambda p, i: moe_llama.routing_stats(
            p, i, scatter_cfg))(moe_llama.init_params(scatter_cfg, seed=0),
                                ids)
        dropped = round(float(stats["dropped_fraction"]), 4)
    except Exception as e:  # noqa: BLE001 — stats must not kill the bench
        dropped = f"error: {e!r:.80}"
    return {
        "metric": "moe_train_tokens_per_sec_per_chip",
        "value": round(tok_per_sec, 2),
        "unit": "tokens/sec/chip",
        # ACTIVE-params 6N convention (top_k experts + router per token)
        "mfu": round(mfu, 4),
        "dispatch": cfg.moe_dispatch or "auto",
        "dispatch_compare": {
            "gmm": round(tok_per_sec, 2),
            "scatter": round(B * S * steps / dt_scatter, 2),
        },
        "scatter_dropped_fraction": dropped,
        "layers": layers_note,
        "experts": cfg.num_experts, "top_k": cfg.moe_top_k,
        "batch": B, "seq": S, "steps": steps, "loss": final_loss,
    }


def bench_decode(dev, on_tpu):
    """Serving decode throughput: paged (block-paged KV + Pallas paged
    attention) vs the dense static-cache decode, same model/batch/steps.
    Prefill runs ONCE outside the clock for both paths — the timed loop is
    greedy decode steps only, so the headline `decode_tokens_per_sec` is
    the LLMEngine's per-token cost with a full batch."""
    import jax as _jax
    from paddle_tpu.models import generation, llama
    from paddle_tpu.models.llama import LlamaConfig

    if on_tpu:
        # the training flagship's shape (~700M, head_dim 128 rides the
        # kernels' lane tile); decode-heavy split: short prompt, long tail
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=12, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=8192,
            dtype=jnp.bfloat16, remat=False)
        B, S, new_tokens, page_size = 8, 128, 128, 64
    else:
        cfg = LlamaConfig.tiny()
        B, S, new_tokens, page_size = 2, 8, 4, 4

    params = llama.init_params(cfg, _jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (B, S)), jnp.int32)
    total = S + new_tokens

    # shared prefill (outside the clock): dense cache + pages scattered
    cache = generation.PagedKVCache(
        cfg, num_pages=1 + B * (-(-total // page_size)),
        page_size=page_size, max_slots=B,
        pages_per_seq=-(-total // page_size))
    for _ in range(B):
        cache.ensure_capacity(cache.acquire_slot(), total)
    dense0 = generation.init_kv_cache(cfg, B, total)
    logits0, dense0 = generation.forward_with_cache(params, ids, cfg,
                                                    dense0, 0)
    pools0 = generation.scatter_prefill_into_pages(
        {"k": dense0["k"][:, :, :S], "v": dense0["v"][:, :, :S]},
        cache.pools, cache.page_table, S)
    tok0 = jnp.argmax(logits0[:, -1], -1).astype(jnp.int32)

    paged_step = _jax.jit(lambda tok, ctx, k, v: generation.forward_paged_decode(
        params, tok, cfg, {"k": k, "v": v}, cache.page_table, ctx))
    dense_step = _jax.jit(lambda tok, c_k, c_v, pos: generation.forward_with_cache(
        params, tok[:, None], cfg, {"k": c_k, "v": c_v}, pos))

    def run_paged():
        tok, k, v = tok0, pools0["k"], pools0["v"]
        for i in range(new_tokens):
            ctx = jnp.full((B,), S + i, jnp.int32)
            lg, p = paged_step(tok, ctx, k, v)
            k, v = p["k"], p["v"]
            tok = jnp.argmax(lg, -1).astype(jnp.int32)
        return tok

    def run_dense():
        tok, ck, cv = tok0, dense0["k"], dense0["v"]
        for i in range(new_tokens):
            lg, c = dense_step(tok, ck, cv, jnp.int32(S + i))
            ck, cv = c["k"], c["v"]
            tok = jnp.argmax(lg[:, -1], -1).astype(jnp.int32)
        return tok

    def timed(fn):
        np.asarray(fn())          # compile + warm; host transfer = complete
        t0 = time.perf_counter()
        np.asarray(fn())
        return time.perf_counter() - t0

    dt_paged = timed(run_paged)
    dt_dense = timed(run_dense)
    paged_tps = B * new_tokens / dt_paged
    dense_tps = B * new_tokens / dt_dense
    lifecycle, latency = _engine_lifecycle_counters()
    return {
        "metric": "decode_tokens_per_sec",
        "value": round(paged_tps, 2),
        "unit": "tokens/sec",
        "paged_tokens_per_sec": round(paged_tps, 2),
        "dense_tokens_per_sec": round(dense_tps, 2),
        "paged_vs_dense": round(paged_tps / dense_tps, 3),
        "batch": B, "prompt": S, "new_tokens": new_tokens,
        "page_size": page_size,
        "model_params": llama.num_params(cfg),
        "engine_lifecycle": lifecycle,
        # per-request latency percentiles (TTFT / inter-token) from the
        # same forced-preemption engine run — the router/placement
        # signals the ROADMAP's multi-tenant item needs
        "request_latency": latency,
    }


def bench_ragged(dev, on_tpu):
    """extra.ragged: the unified ragged step's A/B — decode tokens/sec
    and inter-token p99 for streaming requests while a LONG prompt
    prefills concurrently, three ways:

      * decode_only — no long prompt; the baseline the acceptance bound
        pins (chunked p99 under prefill must stay <= 1.5x this).
      * chunked     — the long prompt enters as bounded chunks riding the
        SAME ragged dispatch as the decode spans (the shipped default).
      * one_shot    — chunk budget >= the prompt, so the whole prefill
        lands in one step: the old two-dispatch world's head-of-line
        stall, reproduced inside the unified step for the A/B.

    All three run ONE attention dispatch per step — there is no bucket
    menu and no separate prefill executable to compile.

    Plus the fused-decode A/B: a SAMPLED decode-only workload
    (temperature/top-k/top-p on — the epilogue the fusion folds into the
    dispatch) with `fused_decode` on (sampling inside the dispatch,
    token ids cross the host boundary) vs off (logits pulled, the eager
    filter+categorical chain runs as a second hop per step).  Paired
    alternating trials, median of the per-pair ratios, so load drift
    cannot fake the verdict either way.  `itl_fused_p50_ms` /
    `itl_unfused_p50_ms` and their ratio are the nightly-diff keys;
    `dispatch_sample_ms` per leg is the stepprof attribution the win
    must show up in (absolute per-step time of the dispatch+sample
    phases — shares alone renormalize and hide it)."""
    import time as _time
    import jax as _jax
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models import llama as _llama
    from paddle_tpu.models.llama import LlamaConfig

    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=12, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=8192,
            dtype=jnp.bfloat16, remat=False)
        long_len, new_tokens, page_size, chunk, max_seq = 2048, 64, 64, \
            256, 4096
    else:
        cfg = LlamaConfig.tiny()
        # chunk=4 from the tools/bench_ragged.py sweep: best stream p99
        # (the budget adds at most one row block per step here); 48
        # decode tokens x 3 streams so p99 is a percentile, not the max
        long_len, new_tokens, page_size, chunk, max_seq = 40, 48, 4, 4, 64

    params = _llama.init_params(cfg, _jax.random.PRNGKey(2))
    rng = np.random.default_rng(0)
    long_prompt = rng.integers(0, cfg.vocab_size, long_len).tolist()
    shorts = [rng.integers(0, cfg.vocab_size, 3).tolist()
              for _ in range(3 if not on_tpu else 2)]

    def run(chunk_tokens, inject_long, fused=True, sampled=False):
        knobs = ({"temperature": 0.8, "top_k": 8, "top_p": 0.9,
                  "seed": 7} if sampled else {})
        eng = LLMEngine(params, cfg, num_slots=4, page_size=page_size,
                        max_seq_len=max_seq,
                        prefill_chunk_tokens=chunk_tokens, block_q=4,
                        fused_decode=fused, **knobs)
        eng.generate([[1, 2, 3]], max_new_tokens=2)   # warm the executable
        hs = [eng.submit(p, max_new_tokens=new_tokens) for p in shorts]
        for _ in range(3):
            eng.step()               # streams decoding before the burst
        eng.stepprof.reset_window()  # drop warmup/compile-bearing steps
        t0 = _time.perf_counter()
        if inject_long:
            hs.append(eng.submit(long_prompt, max_new_tokens=2))
        while not all(h.done() for h in hs):
            eng.step()
        dt = _time.perf_counter() - t0
        snap = eng.stats_snapshot()
        itl = eng.latency_snapshot()["inter_token_s"]
        ph = eng.stepprof.report()["phases"]
        disp_sample = sum(ph.get(n, {}).get("mean_s", 0.0)
                          for n in ("dispatch", "sample"))
        eng.shutdown()
        return {
            "chunk_tokens": chunk_tokens,
            "decode_tokens_per_sec": round(snap["decode_tokens"] / dt, 2),
            "itl_p50_ms": round((itl["p50"] or 0.0) * 1e3, 3),
            "itl_p99_ms": round((itl["p99"] or 0.0) * 1e3, 3),
            "prefill_chunks": snap["prefill_chunks"],
            "dispatches": snap["steps_total"],
            "fused_decode_steps": snap["fused_decode_steps"],
            # per-step dispatch+sample time: where the fused win lands
            "dispatch_sample_ms": round(disp_sample * 1e3, 4),
        }

    decode_only = run(chunk, inject_long=False)
    chunked = run(chunk, inject_long=True)
    one_shot = run(long_len, inject_long=True)
    # fused A/B: sampled decode-only, alternating pairs (both legs
    # emit the IDENTICAL token stream — the fused kernel's Gumbel-max
    # draw reproduces jax.random.categorical under the shared key
    # chain — so this is purely a latency diff)
    import statistics as _stats
    run(chunk, inject_long=False, sampled=True, fused=True)   # warm
    run(chunk, inject_long=False, sampled=True, fused=False)
    pairs = []
    for _ in range(3):
        pairs.append((run(chunk, inject_long=False, sampled=True),
                      run(chunk, inject_long=False, sampled=True,
                          fused=False)))
    fused_leg, unfused_leg = pairs[-1]
    fused50 = _stats.median(f["itl_p50_ms"] for f, _u in pairs)
    unfused50 = _stats.median(u["itl_p50_ms"] for _f, u in pairs)
    ratios = [f["itl_p50_ms"] / u["itl_p50_ms"]
              for f, u in pairs if u["itl_p50_ms"]]
    base99 = decode_only["itl_p99_ms"]
    chunk99 = chunked["itl_p99_ms"]
    return {
        "workload": {"streams": len(shorts), "long_prompt": long_len,
                     "new_tokens": new_tokens},
        "decode_only": decode_only,
        "chunked": chunked,
        "one_shot": one_shot,
        "fused": fused_leg,
        "unfused": unfused_leg,
        # acceptance bound: p99 under concurrent prefill vs decode-only
        # (<= 1.5 means a long prompt cannot wreck in-flight latency)
        "itl_p99_vs_decode_only": (round(chunk99 / base99, 3)
                                   if base99 else None),
        # the interleaving win: what one-shot prefill (the old world's
        # head-of-line stall) costs relative to chunked
        "one_shot_vs_chunked_p99": (round(one_shot["itl_p99_ms"]
                                          / chunk99, 3)
                                    if chunk99 else None),
        "itl_fused_p50_ms": round(fused50, 3),
        "itl_unfused_p50_ms": round(unfused50, 3),
        # acceptance bound: fused p50 <= 0.9x unfused (median of the
        # paired per-trial ratios)
        "itl_fused_vs_unfused": (round(_stats.median(ratios), 3)
                                 if ratios else None),
        "dispatch_sample_fused_ms": fused_leg["dispatch_sample_ms"],
        "dispatch_sample_unfused_ms": unfused_leg["dispatch_sample_ms"],
    }


def bench_specdec(dev, on_tpu):
    """extra.specdec: speculative decoding A/B — emitted tokens/sec and
    inter-token latency, speculative (n-gram prompt-lookup drafter
    through ragged verify spans) vs plain decode, on two workloads:

      * repetitive — greedy decoding of prompts whose continuation the
        drafter can find in the request's own history (the acceptance-
        friendly case: copy tasks, code, greedy cycles).  The
        acceptance bound pins >= 1.5x emitted tokens/sec here.
      * adversarial — temperature-1.0 sampling of random prompts: the
        sampled continuation almost never repeats, so drafts are almost
        all rejected — the floor case.  Speculation must not fall
        below plain decode (rejected drafts cost verify rows inside the
        decode span's already-padded block, not extra dispatches).

    Both legs share one geometry with block_q = spec_k + 1, so a verify
    span fills EXACTLY the padded row block a plain decode span already
    occupies — the speculative batch is the same compiled shape and the
    same row count as the plain one, and the drafts ride rows that were
    previously padding.  One dispatch per step either way;
    acceptance-rate reported from the obs gauge the router places on."""
    import time as _time
    import jax as _jax
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models import llama as _llama
    from paddle_tpu.models.llama import LlamaConfig

    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=12, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=8192,
            dtype=jnp.bfloat16, remat=False)
        new_tokens, page_size, max_seq, spec_k, streams = 128, 64, 4096, \
            7, 4
        prompt_len, block_q = 64, 8
    else:
        # chunk budget == block_q: one prefill block; spec_k=5 with
        # block_q=6 keeps verify spans inside the decode span's block
        cfg = LlamaConfig.tiny()
        new_tokens, page_size, max_seq, spec_k, streams = 96, 4, 128, 5, 2
        prompt_len, block_q = 8, 6

    params = _llama.init_params(cfg, _jax.random.PRNGKey(3))
    rng = np.random.default_rng(0)
    # repetitive: a short pattern repeated fills the prompt, so the
    # drafter proposes from step one AND the greedy chain's own cycles
    # keep feeding it (output-history lookup)
    repetitive = []
    for _ in range(streams):
        pat = rng.integers(0, cfg.vocab_size, 3).tolist()
        repetitive.append((pat * prompt_len)[:prompt_len])
    adversarial = [rng.integers(0, cfg.vocab_size, prompt_len).tolist()
                   for _ in range(streams)]

    def run(k, prompts, temperature=0.0):
        eng = LLMEngine(params, cfg, num_slots=streams,
                        page_size=page_size, max_seq_len=max_seq,
                        prefill_chunk_tokens=max(block_q, page_size),
                        block_q=block_q, spec_k=k,
                        temperature=temperature)
        eng.generate([[1, 2, 3]], max_new_tokens=2)  # warm the executable
        t0 = _time.perf_counter()
        hs = [eng.submit(p, max_new_tokens=new_tokens) for p in prompts]
        while not all(h.done() for h in hs):
            eng.step()
        dt = _time.perf_counter() - t0
        snap = eng.stats_snapshot()
        itl = eng.latency_snapshot()["inter_token_s"]
        accept = eng.metrics.get("llm_spec_acceptance_rate").value
        # exact emitted count from the handles themselves (counters
        # split first tokens / decode / verify and include the warmup)
        emitted = sum(len(h.result(timeout=0)) for h in hs)
        eng.shutdown()
        return {
            "tokens_per_sec": round(emitted / dt, 2),
            "itl_p50_ms": round((itl["p50"] or 0.0) * 1e3, 3),
            "itl_p99_ms": round((itl["p99"] or 0.0) * 1e3, 3),
            "steps": snap["steps_total"],
            "acceptance_rate": round(accept, 4),
            "spec_drafted": snap["spec_drafted"],
            "spec_emitted": snap["spec_emitted"],
        }

    out = {"spec_k": spec_k,
           "workload": {"streams": streams, "prompt": prompt_len,
                        "new_tokens": new_tokens}}
    for name, prompts, temp in (("repetitive", repetitive, 0.0),
                                ("adversarial", adversarial, 1.0)):
        plain = run(0, prompts, temp)
        spec = run(spec_k, prompts, temp)
        out[name] = {
            "plain": plain, "spec": spec,
            # the headline: emitted-token throughput, spec vs plain
            "speedup": (round(spec["tokens_per_sec"]
                              / plain["tokens_per_sec"], 3)
                        if plain["tokens_per_sec"] else None),
            "acceptance_rate": spec["acceptance_rate"],
        }
    return out


def bench_prefix_reuse(dev, on_tpu):
    """extra.prefix_reuse: cross-user prefix caching A/B — TTFT at 0% /
    50% / 95% prefix-hit mixes over a SHARED long system prompt (>= 75%
    of each prompt's length), plus hit rate and the fraction of prefill
    pages served by splicing instead of compute.

    A hit admission splices the cached prefix's pages into the slot's
    page table (no dispatch) and chunk-prefills only the unshared
    suffix, so TTFT at a 95% hit mix should be <= 0.5x the 0%-mix
    baseline and per-request prefill work should scale with the suffix
    alone.  Requests run one at a time so TTFT isolates admission +
    prefill, not queueing."""
    import time as _time
    import jax as _jax
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models import llama as _llama
    from paddle_tpu.models.llama import LlamaConfig

    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=12, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=8192,
            dtype=jnp.bfloat16, remat=False)
        shared_len, suffix_len, page_size, chunk, max_seq, n_req = \
            1536, 512, 64, 256, 4096, 12
    else:
        cfg = LlamaConfig.tiny()
        # shared 24 of 32 tokens = 75%; chunk 8 -> a cold prefill takes
        # 4 chunked steps, a full hit exactly 1
        shared_len, suffix_len, page_size, chunk, max_seq, n_req = \
            24, 8, 4, 8, 64, 12

    params = _llama.init_params(cfg, _jax.random.PRNGKey(4))
    rng = np.random.default_rng(0)
    shared = rng.integers(0, cfg.vocab_size, shared_len).tolist()

    def run(mix_pct):
        eng = LLMEngine(params, cfg, num_slots=2, page_size=page_size,
                        max_seq_len=max_seq, prefill_chunk_tokens=chunk,
                        block_q=4)
        eng.generate([[1, 2, 3]], max_new_tokens=2)  # warm the executable
        if mix_pct:
            # seed the cache once (untimed): the fleet-scale analog is
            # the FIRST user of a system prompt paying the cold prefill
            h = eng.submit(
                shared + rng.integers(0, cfg.vocab_size,
                                      suffix_len).tolist(), 2)
            while not h.done():
                eng.step()
        base = eng.stats_snapshot()
        n_hit = round(n_req * mix_pct / 100)
        flags = np.zeros(n_req, bool)
        flags[:n_hit] = True
        rng.shuffle(flags)
        ttfts = []
        for hit in flags:
            head = shared if hit else \
                rng.integers(0, cfg.vocab_size, shared_len).tolist()
            prompt = head + rng.integers(0, cfg.vocab_size,
                                         suffix_len).tolist()
            h = eng.submit(prompt, max_new_tokens=2)
            while not h.done():
                eng.step()
            ttfts.append(h.t_first_token - h.t_submit)
        snap = eng.stats_snapshot()
        eng.shutdown()
        spliced = snap["prefix_spliced_pages"] - base["prefix_spliced_pages"]
        prefilled = -(-(snap["prefill_tokens"] - base["prefill_tokens"])
                      // page_size)
        lookups = (snap["prefix_hits"] + snap["prefix_misses"]
                   - base["prefix_hits"] - base["prefix_misses"])
        return {
            "mix": mix_pct,
            "ttft_p50_ms": round(float(np.median(ttfts)) * 1e3, 3),
            "ttft_mean_ms": round(float(np.mean(ttfts)) * 1e3, 3),
            "hit_rate": round((snap["prefix_hits"] - base["prefix_hits"])
                              / lookups, 4) if lookups else 0.0,
            "spliced_page_fraction": round(
                spliced / (spliced + prefilled), 4)
            if spliced + prefilled else 0.0,
            "prefill_tokens_mean": round(
                (snap["prefill_tokens"] - base["prefill_tokens"])
                / n_req, 2),
            "cow_copies": snap["prefix_cow_copies"]
                          - base["prefix_cow_copies"],
        }

    mixes = {f"mix_{m}": run(m) for m in (0, 50, 95)}
    cold, hot = mixes["mix_0"], mixes["mix_95"]
    return {
        "workload": {"shared_prefix": shared_len, "suffix": suffix_len,
                     "prompt": shared_len + suffix_len,
                     "requests": n_req,
                     "shared_fraction": round(
                         shared_len / (shared_len + suffix_len), 3)},
        **mixes,
        # the acceptance gate: a 95%-hit mix's median TTFT vs the 0%-hit
        # baseline (bound <= 0.5 for a >= 75%-shared prompt)
        "ttft_hit95_vs_cold": (round(hot["ttft_p50_ms"]
                                     / cold["ttft_p50_ms"], 3)
                               if cold["ttft_p50_ms"] else None),
        # chunked-prefill work must scale with the SUFFIX only: tokens
        # actually prefilled per request at 95% hits vs cold
        "prefill_tokens_hit95_vs_cold": (
            round(hot["prefill_tokens_mean"]
                  / cold["prefill_tokens_mean"], 3)
            if cold["prefill_tokens_mean"] else None),
    }


def bench_disagg(dev, on_tpu):
    """extra.disagg: disaggregated prefill/decode serving A/B plus the
    tiered prefix store's warm-start win.

    Leg 1 — decode ITL under a prefill burst: a 1-prefill/2-decode
    fleet vs a 3-mixed fleet (same engines, same workload, pump-driven
    so the measurement is deterministic in structure).  Streaming
    requests decode while a burst of LONG prompts arrives; in the
    disagg fleet the burst's chunked prefills land on the prefill-class
    replica only (streams were handed off to decode-class replicas
    whose steps stay all-decode), in the mixed fleet the burst
    interleaves into every replica's unified step.

    The structural win being priced is PER-CLASS batch geometry: every
    mixed replica must size its unified ragged batch for the compromise
    chunk budget (large enough that a burst's TTFT doesn't crawl), and
    that budget's rows ride EVERY dispatch — pure-decode steps
    included, because the batch is fixed-shape by design.  A
    decode-class replica runs a small chunk budget (its only local
    prefills are spliced continuations' sub-page tails and canaries),
    so its compiled dispatch is genuinely smaller; only the
    prefill-class replica carries the wide geometry.

    ITL is measured as per-STEP time of the stream-serving replicas
    (stepprof frames, window reset at burst submit): in the deployed
    fleet every replica owns its accelerator, so a stream's inter-token
    latency IS its replica's step time — while on this bench's shared
    host, wall-clock between tokens would just re-measure how the
    replicas timeshare one device and hide the isolation entirely.
    Gate: `itl_burst_disagg_vs_mixed` (p99 step time of decode-class
    replicas over p99 of the mixed replicas' steps) <= 0.8.

    Leg 2 — host-tier warm start: one engine prefills a long prompt,
    its pages are LRU-demoted into a shared TieredPrefixStore, and a
    FRESH engine attached to the same store serves the same prompt by
    PROMOTING the pages back (one scatter) instead of re-prefilling.
    Gate: `ttft_warm_vs_cold` <= 0.6."""
    import time as _time
    import jax as _jax
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.inference.kvstore import TieredPrefixStore
    from paddle_tpu.inference.router import Router
    from paddle_tpu.models import llama as _llama
    from paddle_tpu.models.llama import LlamaConfig

    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=12, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=8192,
            dtype=jnp.bfloat16, remat=False)
        long_len, stream_tokens, page_size, max_seq = 2048, 48, 64, 4096
        chunk_wide, chunk_narrow = 256, 64
        n_streams, n_burst = 4, 4
    else:
        cfg = LlamaConfig.tiny()
        # the mixed fleet (and the prefill-class replica) runs chunk=16
        # so the 40-token bursts land in 3 chunks; decode-class replicas
        # run chunk=4 — the sub-page continuation tails and canaries are
        # the only prefill they ever see
        long_len, stream_tokens, page_size, max_seq = 40, 24, 4, 64
        chunk_wide, chunk_narrow = 16, 4
        n_streams, n_burst = 6, 4

    params = _llama.init_params(cfg, _jax.random.PRNGKey(5))
    rng = np.random.default_rng(0)
    # stream prompts span TWO full pages: the warmup handoffs must carry
    # real pages so the gather (prefill class) and scatter (decode
    # class) executables compile during warmup, not under measurement —
    # a zero-page handoff skips the transfer entirely
    streams = [rng.integers(0, cfg.vocab_size, 2 * page_size).tolist()
               for _ in range(n_streams)]
    bursts = [rng.integers(0, cfg.vocab_size, long_len).tolist()
              for _ in range(n_burst)]

    # pool sized so burst imports never force evictions mid-measurement:
    # an LRU demotion gathers pages to host inside the decode step, and
    # that cost is the tiered store's price under MEMORY pressure — this
    # leg isolates the prefill-interference question instead
    pool_pages = 8 * (max_seq // page_size)

    def mk(chunk):
        return LLMEngine(params, cfg, num_slots=4, page_size=page_size,
                         max_seq_len=max_seq, prefill_chunk_tokens=chunk,
                         num_pages=pool_pages, block_q=4)

    def run_fleet(roles):
        engines = [mk(chunk_wide),
                   mk(chunk_narrow if roles else chunk_wide),
                   mk(chunk_narrow if roles else chunk_wide)]
        for e in engines:
            e.generate([[1, 2, 3]], max_new_tokens=2)  # warm executables
        # Role flips frozen: the admission burst is exactly the
        # transient the flip hysteresis exists to ride out, and in pump
        # mode every pump is a tick so even long hysteresis would
        # thrash mid-measurement.
        router = Router(engines=engines, roles=roles,
                        kvstore=TieredPrefixStore() if roles else None,
                        role_flip_ticks=10 ** 9, threaded=False)
        hs = [router.submit(p, stream_tokens) for p in streams]
        # pump until every stream is past admission (and, disagg, past
        # handoff) and actually decoding — the swap executables compile
        # during THIS window, never under measurement
        for _ in range(2000):
            if all((len(h._hop.tokens) if h._hop is not None else 0) >= 2
                   for h in hs):
                break
            router.pump()
        burst_h = [router.submit(p, 2) for p in bursts]
        for e in engines:
            e.stepprof.reset_window()
        all_h = hs + burst_h
        for _ in range(20000):
            if all(h.done() for h in all_h):
                break
            router.pump()
        # decode ITL proxy: every step frame of the replicas that serve
        # the streams during the burst — decode-class only (r1, r2) in
        # the disagg fleet (imports and burst continuations ride those
        # same steps and are deliberately charged), all three in mixed
        stream_rids = {1, 2} if roles else {0, 1, 2}
        step_s = [f["total_s"]
                  for r in router.replicas if r.rid in stream_rids
                  for f in r.engine.stepprof.record_window()]
        snap = router.stats_snapshot()
        router.shutdown()
        return {
            "itl_p50_ms": round(float(np.percentile(step_s, 50)) * 1e3, 3)
            if step_s else None,
            "itl_p99_ms": round(float(np.percentile(step_s, 99)) * 1e3, 3)
            if step_s else None,
            "steps": len(step_s),
            "handoffs": snap["handoffs"],
            "completed": snap["completed"],
        }

    mixed = run_fleet(None)
    disagg = run_fleet("prefill=1,decode=2")

    # -- leg 2: warm-start TTFT from the host tier ---------------------------
    store = TieredPrefixStore()

    def ttft_once(warm_store):
        eng = mk(chunk_wide)
        eng.attach_kvstore(store)
        eng.generate([[1, 2, 3]], max_new_tokens=2)
        # warm the gather/scatter executables on BOTH legs (demote
        # compiles _swap_out, promote compiles _swap_in): the measured
        # TTFT must compare prefill compute vs one promote scatter, not
        # a first-use compile
        junk = [7] * (2 * page_size)
        eng.generate([junk], max_new_tokens=2)
        eng.prefix_index.evict(10 ** 6)
        eng.generate([junk], max_new_tokens=2)
        h = eng.submit(bursts[0], max_new_tokens=2)
        while not h.done():
            eng.step()
        ttft = h.t_first_token - h.t_submit
        if warm_store:
            # LRU-demote everything the request registered: the hook
            # copies each still-valid page into the store as it drops
            eng.prefix_index.evict(10 ** 6)
        snap = eng.stats_snapshot()
        eng.shutdown()
        return ttft, snap

    ttft_cold, cold_snap = ttft_once(warm_store=True)
    ttft_warm, warm_snap = ttft_once(warm_store=False)

    return {
        "workload": {"streams": n_streams, "stream_tokens": stream_tokens,
                     "burst_prompts": n_burst, "burst_len": long_len},
        "mixed": mixed,
        "disagg": disagg,
        # acceptance gate: streaming p99 ITL under the burst, disagg
        # fleet over mixed fleet (<= 0.8: isolating decode-class steps
        # from prefill chunks must buy at least 20% tail latency)
        "itl_burst_disagg_vs_mixed": (
            round(disagg["itl_p99_ms"] / mixed["itl_p99_ms"], 3)
            if mixed["itl_p99_ms"] and disagg["itl_p99_ms"] else None),
        "ttft_cold_ms": round(ttft_cold * 1e3, 3),
        "ttft_warm_ms": round(ttft_warm * 1e3, 3),
        # acceptance gate: TTFT on a fresh engine promoting from the
        # host tier vs the cold chunked prefill (<= 0.6)
        "ttft_warm_vs_cold": (round(ttft_warm / ttft_cold, 3)
                              if ttft_cold else None),
        "demoted_pages": cold_snap["kv_demoted_pages"],
        "promoted_pages": warm_snap["kv_promoted_pages"],
        "tier_hits": warm_snap["prefix_tier_hits"],
    }


def bench_qos(dev, on_tpu):
    """extra.qos: multi-tenant QoS A/B under a hostile mix — what the
    weighted-fair/priority admission path buys a paced high-priority
    tenant while a bulk tenant floods the queue.

    One pump-driven engine, two legs, same workload and pool:

      * OFF — no tenant table, every request untagged: the single
        default FIFO deque, exactly the pre-QoS engine.  A burst of
        bulk requests lands first, so each paced "gold" request waits
        behind the whole backlog for a slot.
      * ON — two-tier table (gold: priority 0, weight 4; bulk:
        priority 3, weight 1), requests tagged: WFQ puts every gold
        arrival at the head of admission, so it takes the next slot
        that frees instead of draining the flood first.

    Gates (lower-is-better ratios, ON over OFF, for the GOLD tenant
    only): `ttft_hipri_qos_on_vs_off` <= 0.8 on p99 time-to-first-token
    and `itl_hipri_qos_on_vs_off` <= 0.8 on p99 END-TO-END per-emitted-
    token latency ((t_done - t_submit) / tokens — queueing and
    preemption delay included; pure step time would be identical in
    both legs because the compiled dispatch doesn't know about tenants,
    BY DESIGN).  Also reported: Jain fairness index over weight-
    normalized per-tenant emitted tokens/sec in the ON leg (1.0 =
    allocation exactly proportional to configured weights)."""
    import time as _time
    import jax as _jax
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models import llama as _llama
    from paddle_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig.tiny()
    page_size, max_seq = 4, 32
    n_bulk, bulk_new = 16, 12
    n_gold, gold_new = 6, 8
    gold_every = 8   # pump steps between gold arrivals (the pacing)

    params = _llama.init_params(cfg, _jax.random.PRNGKey(7))
    rng = np.random.default_rng(3)
    bulk_prompts = [rng.integers(0, cfg.vocab_size, 8).tolist()
                    for _ in range(n_bulk)]
    gold_prompts = [rng.integers(0, cfg.vocab_size, 8).tolist()
                    for _ in range(n_gold)]

    QOS_ON = {"gold": {"priority": 0, "weight": 4.0},
              "bulk": {"priority": 3, "weight": 1.0}}

    def run_leg(table):
        tagged = table is not None
        # pool sized so the flood never forces preemption: the gate
        # must price ADMISSION ORDER alone, identically in both legs
        eng = LLMEngine(params, cfg, num_slots=2, page_size=page_size,
                        max_seq_len=max_seq, prefill_chunk_tokens=8,
                        num_pages=3 * (max_seq // page_size),
                        block_q=4, tenants=table)
        eng.generate([[1, 2, 3]], max_new_tokens=2)  # warm executables
        t_start = _time.monotonic()
        bulk_kw = {"tenant": "bulk"} if tagged else {}
        gold_kw = {"tenant": "gold"} if tagged else {}
        bulk_h = [eng.submit(p, bulk_new, **bulk_kw)
                  for p in bulk_prompts]
        gold_h, done_t = [], {}
        all_h = list(bulk_h)
        steps = gi = 0
        while steps < 50000:
            if gi < n_gold and steps % gold_every == 0:
                h = eng.submit(gold_prompts[gi], gold_new, **gold_kw)
                gold_h.append(h)
                all_h.append(h)
                gi += 1
            eng.step()
            steps += 1
            now = _time.monotonic()
            for h in all_h:
                if h.done() and id(h) not in done_t:
                    done_t[id(h)] = now
            if gi >= n_gold and all(h.done() for h in all_h):
                break
        elapsed = _time.monotonic() - t_start
        snap = eng.stats_snapshot()
        eng.shutdown()
        ttfts = [h.t_first_token - h.t_submit for h in gold_h
                 if h.t_first_token is not None]
        e2e = [(done_t[id(h)] - h.t_submit) / max(1, len(h.tokens))
               for h in gold_h if id(h) in done_t and not h.error]
        rates = {
            "gold": sum(len(h.tokens) for h in gold_h
                        if not h.error) / elapsed,
            "bulk": sum(len(h.tokens) for h in bulk_h
                        if not h.error) / elapsed,
        }
        fairness = None
        if tagged:
            # Jain over weight-normalized rates: x_t = rate_t / w_t;
            # 1.0 means throughput split exactly as the weights demand
            xs = [rates[t] / table[t]["weight"] for t in ("gold", "bulk")]
            sq = sum(x * x for x in xs)
            fairness = (sum(xs) ** 2) / (len(xs) * sq) if sq else None
        return {
            "gold_ttft_p99_ms":
                round(float(np.percentile(ttfts, 99)) * 1e3, 3)
                if ttfts else None,
            "gold_e2e_per_token_p99_ms":
                round(float(np.percentile(e2e, 99)) * 1e3, 3)
                if e2e else None,
            "tokens_per_sec": {t: round(v, 2) for t, v in rates.items()},
            "steps": steps,
            "preemptions": snap["preemptions"],
            "completed": snap["completed"],
            "fairness_index": round(fairness, 4) if fairness else None,
        }

    off = run_leg(None)
    on = run_leg(QOS_ON)

    def ratio(key):
        a, b = on[key], off[key]
        return round(a / b, 3) if a and b else None

    return {
        "workload": {"bulk": n_bulk, "bulk_new": bulk_new,
                     "gold": n_gold, "gold_new": gold_new,
                     "gold_every_steps": gold_every},
        "qos_off": off,
        "qos_on": on,
        # acceptance gates: the paced high-priority tenant's tail
        # latency with QoS on over the untagged-FIFO baseline (<= 0.8:
        # priority admission must buy at least 20% under the flood)
        "ttft_hipri_qos_on_vs_off": ratio("gold_ttft_p99_ms"),
        "itl_hipri_qos_on_vs_off": ratio("gold_e2e_per_token_p99_ms"),
        "fairness_index": on["fairness_index"],
    }


def bench_obs_overhead(dev, on_tpu):
    """extra.obs_overhead: what leaving the FULL observability layer on
    costs the decode hot path — span tracer enabled, per-request
    timeline registry enabled (one event per token per request), SLO
    engine observing, step-phase profiler recording, pool-telemetry
    counter tracks sampling, anomaly watchdog armed — vs everything
    disabled, same engine, same workload.  Reported as the p50
    inter-token latency ratio over paired alternating trials (median of
    the PAIRED per-trial ratios, so one noisy trial — or load drift
    across the bench — cannot fake a regression either way).  The
    acceptance pin is < 2%: below that, the whole
    attribution layer is safe to leave on in soak runs and production
    fleets.  Also reports the traced leg's phase-share table and the
    ragged dispatch's PER-PHASE cost_model_ratio keyed by shape class
    (obs.stepprof.cost_join — the number the kernel autotuner reads;
    None on CPU, where no peak FLOP/s is defined)."""
    import statistics
    import time as _time
    import jax as _jax
    from paddle_tpu import obs as _obs
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models import llama as _llama
    from paddle_tpu.models.llama import LlamaConfig

    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=12, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=8192,
            dtype=jnp.bfloat16, remat=False)
        new_tokens, page_size, max_seq, streams, trials = 96, 64, 4096, \
            4, 3
    else:
        cfg = LlamaConfig.tiny()
        new_tokens, page_size, max_seq, streams, trials = 48, 4, 64, 3, 5

    params = _llama.init_params(cfg, _jax.random.PRNGKey(4))
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, 3).tolist()
               for _ in range(streams)]
    attribution = {}                # last traced leg's phase verdicts

    def run(traced: bool, attribute: bool = False) -> float:
        # traced = the WHOLE layer on: span tracer recording, request
        # registry recording every lifecycle edge, SLO engine
        # observing, phase profiler + pool counter tracks + watchdog
        # armed; off = everything disabled (the single-branch no-op
        # paths production would pay anyway)
        import gc
        gc.collect()    # each leg starts from the same GC state
        tracer = _obs.Tracer(enabled=traced, capacity=1 << 15)
        reqreg = _obs.RequestRegistry(enabled=traced)
        eng = LLMEngine(params, cfg, num_slots=streams,
                        page_size=page_size, max_seq_len=max_seq,
                        prefill_chunk_tokens=4, block_q=4,
                        tracer=tracer, reqtrace=reqreg,
                        stepprof=_obs.StepProfiler(enabled=traced),
                        watchdog=_obs.Watchdog(enabled=traced))
        eng.slo.enabled = traced
        eng.generate([[1, 2, 3]], max_new_tokens=2)  # warm the executable
        hs = [eng.submit(p, max_new_tokens=new_tokens) for p in prompts]
        while not all(h.done() for h in hs):
            eng.step()
        itl = eng.latency_snapshot()["inter_token_s"]["p50"]
        if attribute:
            rep = eng.stepprof.report()
            attribution["phase_shares"] = {
                name: round(p["share"], 4)
                for name, p in sorted(rep["phases"].items())}
            attribution["step_p50_ms"] = round(
                rep["step"]["p50_s"] * 1e3, 4)
            attribution["watchdog_anomalies"] = \
                eng.watchdog.anomalies_total
            try:
                # join against the executable the dispatch phase is
                # actually running: with fused_decode on the plain steps
                # profile under the "+fused" shape class and the fused
                # target's flops (sampling epilogue included)
                if eng.fused_decode:
                    flops = _obs.mfu.static_flops(
                        eng._ragged_fused, *eng.ragged_fused_probe_args())
                else:
                    flops = _obs.mfu.static_flops(
                        eng._ragged, *eng.ragged_probe_args())
                joined = eng.stepprof.cost_join("dispatch", flops)
                attribution["dispatch_cost_model_ratio"] = {
                    cls or "untagged": {
                        "measured_mean_ms": round(
                            r["measured_step_s"] * 1e3, 4),
                        "cost_model_ratio": (
                            None if r["cost_model_ratio"] is None
                            else round(r["cost_model_ratio"], 3)),
                    } for cls, r in joined.items()}
            except Exception as e:  # noqa: BLE001 — cost join must not
                attribution["dispatch_cost_model_ratio"] = {
                    "error": repr(e)[:200]}    # kill the bench
        eng.shutdown()
        return itl or 0.0

    run(True)                       # warm both code paths once
    run(False)
    on_p50, off_p50, pair_ratios = [], [], []
    for _ in range(trials):         # alternate so drift hits both legs
        on = run(True)
        off = run(False)
        on_p50.append(on)
        off_p50.append(off)
        if off:
            pair_ratios.append(on / off)
    # the attribution tables come from a DEDICATED traced run after the
    # A/B loop: tracing the dispatch jaxpr for the static cost join is
    # heavy enough to perturb the paired timing runs
    run(True, attribute=True)
    on_med = statistics.median(on_p50)
    off_med = statistics.median(off_p50)
    # the headline ratio is the MEDIAN OF PAIRED RATIOS: each on/off
    # pair runs back to back, so machine-load drift across the bench
    # cancels within a pair instead of landing on one leg's median
    ratio = statistics.median(pair_ratios) if pair_ratios else None
    return {
        "workload": {"streams": streams, "new_tokens": new_tokens,
                     "trials": trials},
        "itl_p50_traced_ms": round(on_med * 1e3, 4),
        "itl_p50_untraced_ms": round(off_med * 1e3, 4),
        # the acceptance pin: < 1.02 means the full attribution layer
        # costs under 2% of decode ITL — safe to leave on in soaks
        "itl_p50_ratio": (None if ratio is None else round(ratio, 4)),
        "overhead_pct": (None if ratio is None
                         else round((ratio - 1.0) * 100, 2)),
        "bound_pct": 2.0,
        # the traced leg's attribution verdicts: per-phase step shares
        # and the ragged dispatch's per-shape-class cost-model join
        **attribution,
    }


def _engine_lifecycle_counters():
    """LLMEngine preemption/lifecycle counters + request latency
    percentiles on a deliberately undersized page pool (2 slots whose
    worst case exceeds the pool, so the admit-on-demand scheduler must
    preempt and resume) — surfaced alongside the decode throughput
    headline to track the serving rung.  Returns (counters, latency):
    latency carries TTFT and inter-token p50/p99 in ms, derived from the
    engine's per-request lifecycle histograms (raw-sample window, not
    bucket interpolation)."""
    import jax as _jax
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models import llama as _llama
    from paddle_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig.tiny()
    params = _llama.init_params(cfg, _jax.random.PRNGKey(1))
    eng = LLMEngine(params, cfg, num_slots=2, page_size=4, max_seq_len=16,
                    num_pages=5)   # below 2-slot worst case -> preemption
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, 8).tolist()
               for _ in range(3)]
    eng.generate(prompts, max_new_tokens=4)
    snap = eng.stats_snapshot()
    counters = {k: snap[k] for k in ("preemptions", "swapped_in", "resumed",
                                     "cancelled", "timed_out", "queue_depth",
                                     "completed")}

    lat = eng.latency_snapshot()

    def ms(key):
        d = lat[key]
        return {"p50_ms": round(d["p50"] * 1e3, 3),
                "p99_ms": round(d["p99"] * 1e3, 3), "n": d["n"]}

    latency = {"ttft": ms("ttft_s"), "inter_token": ms("inter_token_s")}
    return counters, latency


def _run_graphlint(timeout: float = 900.0, rewrite_tier: bool = True,
                   ) -> dict:
    """Finding counts from `tools/graphlint.py --json --fix --apply`
    (CPU subprocess — lint traces, the rewrite tier evaluates tiny probe
    models) so BENCH rounds track Graph Doctor status AND what the
    verified rewrites buy (eqn / static FLOPs / bytes deltas per model)
    alongside perf numbers.  rc=1 means findings/rollbacks, still
    parseable.  If the rewrite tier blows the timeout, retry LINT-ONLY
    so the round keeps counts/mem_peak (the always-available baseline)
    and only the rewrite deltas are lost."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "graphlint.py")
    argv = [sys.executable, script, "--json"]
    if rewrite_tier:
        argv += ["--fix", "--apply"]
    try:
        out = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        if out.returncode not in (0, 1):
            return {"error": f"rc={out.returncode} "
                             f"{out.stderr.strip()[-300:]}"}
        d = json.loads(out.stdout.strip().splitlines()[-1])
        rewrite = {}
        for name, tgt in d.get("targets", {}).items():
            rw = tgt.get("rewrite")
            if rw:
                rewrite[name] = {k: rw[k] for k in (
                    "applied", "rolled_back", "ok", "eqns_before",
                    "eqns_after", "flops_before", "flops_after",
                    "bytes_before", "bytes_after") if k in rw}
        return {"ok": d["ok"], "counts": d["counts"],
                "mem_peak_bytes": d.get("mem_peak_bytes", {}),
                "rewrite": rewrite if rewrite_tier else
                {"error": "rewrite tier skipped: --fix --apply timed out"}}
    except subprocess.TimeoutExpired:
        if rewrite_tier:
            return _run_graphlint(timeout, rewrite_tier=False)
        return {"error": f"graphlint timed out after {timeout:.0f}s"}
    except Exception as e:  # noqa: BLE001 — lint must not kill the bench
        return {"error": repr(e)[:300]}


def _run_threadlint(timeout: float = 300.0) -> dict:
    """extra.threadlint: the lock-discipline tier's verdict on the
    serving stack (tools/graphlint.py --threads --json, CPU
    subprocess) — per-module severity counts over paddle_tpu.inference
    and paddle_tpu.obs.  Static only (AST walk, nothing imports the
    engine); BENCH rounds track race-finding drift the way model-lint
    drift is tracked, and tools/bench_diff.py treats every threadlint
    counter as lower-is-better."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "graphlint.py")
    argv = [sys.executable, script, "--threads", "--json"]
    try:
        out = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        if out.returncode not in (0, 1):
            return {"error": f"rc={out.returncode} "
                             f"{out.stderr.strip()[-300:]}"}
        d = json.loads(out.stdout.strip().splitlines()[-1])
        counts = d.get("counts", {})
        return {"ok": d.get("ok", False), "counts": counts,
                "findings_total": sum(sum(c.values())
                                      for c in counts.values())}
    except subprocess.TimeoutExpired:
        return {"error": f"threadlint timed out after {timeout:.0f}s"}
    except Exception as e:  # noqa: BLE001 — lint must not kill the bench
        return {"error": repr(e)[:300]}


def _run_kernellint(timeout: float = 300.0) -> dict:
    """extra.kernellint: the Pallas kernel verifier's verdict on every
    shipped kernel plus a generated fused-chain kernel
    (tools/graphlint.py --kernels --json, CPU subprocess) — per-kernel
    severity counts from the static block-index/coverage/VMEM/dtype
    proofs.  Static only (tracing, no kernel executes); BENCH rounds
    track kernel-contract drift, and tools/bench_diff.py treats every
    kernellint counter as lower-is-better."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "graphlint.py")
    argv = [sys.executable, script, "--kernels", "--json"]
    try:
        out = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        if out.returncode not in (0, 1):
            return {"error": f"rc={out.returncode} "
                             f"{out.stderr.strip()[-300:]}"}
        d = json.loads(out.stdout.strip().splitlines()[-1])
        counts = d.get("counts", {})
        return {"ok": d.get("ok", False), "counts": counts,
                "findings_total": sum(sum(c.values())
                                      for c in counts.values())}
    except subprocess.TimeoutExpired:
        return {"error": f"kernellint timed out after {timeout:.0f}s"}
    except Exception as e:  # noqa: BLE001 — lint must not kill the bench
        return {"error": repr(e)[:300]}


def _run_spmd(timeout: float = 600.0) -> dict:
    """extra.spmd: the SPMD propagation tier's verdict on the sharded
    llama train step under a 2x2 (dp x tp) mesh — per-eqn sharding
    coverage, priced collectives, and the comm-vs-compute roofline
    (tools/graphlint.py --mesh, CPU subprocess with 8 forced host
    devices).  Static only: nothing executes beyond tracing."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "graphlint.py")
    argv = [sys.executable, script, "llama", "--mesh", "data=2,model=2",
            "--no-hlo", "--json"]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    flags = env.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        out = subprocess.run(argv, capture_output=True, text=True,
                             timeout=timeout, env=env)
        if out.returncode not in (0, 1):
            return {"error": f"rc={out.returncode} "
                             f"{out.stderr.strip()[-300:]}"}
        d = json.loads(out.stdout.strip().splitlines()[-1])
        sp = d.get("targets", {}).get("llama", {}).get("spmd")
        if sp is None:
            return {"error": "spmd tier did not run"}
        sp.pop("rows", None)            # the per-eqn table is a CLI view
        sp["collectives"] = sp.get("collectives", [])[:5]
        return sp
    except subprocess.TimeoutExpired:
        return {"error": f"spmd lint timed out after {timeout:.0f}s"}
    except Exception as e:  # noqa: BLE001 — lint must not kill the bench
        return {"error": repr(e)[:300]}


def _run_router(timeout: float = 600.0) -> dict:
    """extra.router: the fleet tier's micro-bench (tools/chaos_fleet.py
    --bench, CPU subprocess over scripted engines — no model compute, so
    the numbers isolate the ROUTER): placement overhead per submit
    (least-loaded scoring + hop placement) and failover-to-first-token
    latency under an injected replica death vs the no-death baseline."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "chaos_fleet.py")
    argv = [sys.executable, script, "--bench", "--json"]
    try:
        out = subprocess.run(argv, capture_output=True, text=True,
                             timeout=timeout,
                             env={**os.environ, "JAX_PLATFORMS": "cpu"})
        if out.returncode != 0:
            return {"error": f"rc={out.returncode} "
                             f"{out.stderr.strip()[-300:]}"}
        return json.loads(out.stdout.strip().splitlines()[-1])
    except subprocess.TimeoutExpired:
        return {"error": f"router bench timed out after {timeout:.0f}s"}
    except Exception as e:  # noqa: BLE001 — must not kill the bench
        return {"error": repr(e)[:300]}


def _run_sub(name: str, timeout: "float | None" = None) -> dict:
    """Run `python bench.py --sub {name}` and parse its one-line JSON."""
    if timeout is None:
        try:
            timeout = float(os.environ.get("BENCH_SUB_TIMEOUT", "1500"))
        except ValueError:
            timeout = 1500.0
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--sub", name],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env={**os.environ, "BENCH_BACKEND": _BACKEND})
        if out.returncode != 0:
            return {"error": f"rc={out.returncode} {out.stderr.strip()[-300:]}"}
        return json.loads(out.stdout.strip().splitlines()[-1])
    except subprocess.TimeoutExpired:
        return {"error": f"sub-bench {name} timed out after {timeout:.0f}s"}
    except Exception as e:  # noqa: BLE001
        return {"error": repr(e)[:300]}


def _sub_main(name: str) -> None:
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    fn = {"dit": bench_dit, "moe": bench_moe, "decode": bench_decode,
          "ragged": bench_ragged, "specdec": bench_specdec,
          "prefix_reuse": bench_prefix_reuse,
          "obs_overhead": bench_obs_overhead,
          "disagg": bench_disagg, "qos": bench_qos}[name]
    try:
        print(json.dumps(fn(dev, on_tpu)))
    except Exception as e:  # noqa: BLE001 — emit one parseable line anyway
        print(json.dumps({"error": repr(e)[:300]}))


def main():
    from paddle_tpu.models import llama
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.distributed.parallelize import ShardedTrainState
    from paddle_tpu.optimizer.functional import AdamW

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        # ~700M-param Llama-3-style model, bf16, remat on — representative of
        # the 8B recipe's per-chip compute, sized to fit one chip's HBM with
        # full fp32 AdamW state (params+master+m+v = 14 bytes/param).
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=12, num_attention_heads=16, num_key_value_heads=8,
            max_position_embeddings=8192, dtype=jnp.bfloat16, remat=True)
        # S=8192: the tiled Pallas flash backward (O(S·D) residuals) makes
        # long-sequence training steps HBM-feasible.  Measured r3 sweep on
        # v5e: B2/S4096 58.8% MFU, B4/S4096 59.4%, B2/S8192 62.1%,
        # B1/S16384 63.9% (but lower tok/s); B2/S8192 maximizes MFU while
        # keeping tokens/sec above the round-2 headline.
        B, S, steps = 2, 8192, 10
    else:
        cfg = LlamaConfig.tiny()
        B, S, steps = 4, 64, 3

    mesh = mesh_lib.make_mesh(data=1)
    tokens = np.random.default_rng(0).integers(0, cfg.vocab_size, (B, S + 1))
    import gc

    from paddle_tpu.obs import mfu as obs_mfu

    # measured-vs-static info for the final timed run (overwritten per
    # _run_with_unroll leg, so it reflects the leg the headline uses)
    obs_info = {}

    def run(c):
        st = ShardedTrainState(c, llama, mesh,
                               AdamW(learning_rate=1e-4, grad_clip_norm=1.0))
        params, opt_state = st.init(jax.random.PRNGKey(0))
        batch = st.shard_batch(llama.lm_batch_from_tokens(
            jnp.asarray(tokens, dtype=jnp.int32)))
        step_fn = st.jitted_step(batch)
        # jaxpr-counted FLOPs of ONE step (the cost pass's number — it
        # can differ from the 6N headline formula; that delta is signal)
        try:
            obs_info["flops_per_step"] = obs_mfu.static_flops(
                step_fn, params, opt_state, batch)
            obs_info.pop("flops_error", None)   # stale error from a
            # failed unrolled leg must not outlive a fallback success
        except Exception as e:  # noqa: BLE001 — cost must not kill bench
            obs_info["flops_per_step"] = None
            obs_info["flops_error"] = repr(e)[:200]
        sentinel = obs_mfu.RecompileSentinel().watch("llama_train_step",
                                                     step_fn)
        out = _timed_steps(st, params, opt_state, batch, steps,
                           on_warm=sentinel.check)
        sentinel.check()
        obs_info["recompiles"] = sentinel.counts()["llama_train_step"]
        # free the state (params+opt ~ 10 GB) before the sub-benches
        del st, params, opt_state, batch
        gc.collect()
        return out

    dt, final_loss, layers_note = _run_with_unroll(run, cfg, on_tpu)
    tokens_per_sec = B * S * steps / dt
    peak = _peak_flops(dev)
    mfu = (tokens_per_sec * llama.flops_per_token(cfg, S) / peak) if peak else 0.0
    llama_params = llama.num_params(cfg)
    runtime = obs_mfu.runtime_report(
        dt / steps, obs_info.get("flops_per_step") or 0.0, peak_flops=peak)

    # each sub-bench runs in its OWN process: device buffers are truly
    # released between flagships (in-process, residue from the llama run
    # surfaced as INVALID_ARGUMENT/OOM on the axon backend) and one
    # flagship failing cannot poison the next
    dit_extra = _run_sub("dit")
    moe_extra = _run_sub("moe")
    decode_extra = _run_sub("decode")
    ragged_extra = _run_sub("ragged")
    specdec_extra = _run_sub("specdec")
    prefix_extra = _run_sub("prefix_reuse")
    obs_overhead_extra = _run_sub("obs_overhead")
    disagg_extra = _run_sub("disagg")
    qos_extra = _run_sub("qos")
    graphlint_extra = _run_graphlint()
    graphlint_mem_peaks = graphlint_extra.pop("mem_peak_bytes", None)
    rewrite_extra = graphlint_extra.pop("rewrite", None)
    threadlint_extra = _run_threadlint()
    kernellint_extra = _run_kernellint()
    spmd_extra = _run_spmd()
    router_extra = _run_router()

    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {
            "device": getattr(dev, "device_kind", dev.platform),
            "mfu": round(mfu, 4),
            "model_params": llama_params,
            "layers": layers_note,
            "batch": B, "seq": S, "steps": steps,
            "loss": final_loss,
            "backend_probe": _BACKEND,
            # PaLM-appendix convention: 6N + full 12·L·H·D·S attention term,
            # NO causal 1/2 discount (state it so the MFU is unambiguous)
            "flops_convention": "PaLM 6N + 12LHDS, no causal discount",
            # measured-vs-static (paddle_tpu.obs.mfu): runtime MFU uses
            # the cost pass's jaxpr-counted FLOPs (vs the 6N headline);
            # cost_model_ratio = measured / predicted step time (~1 means
            # the static model is placement-trustworthy; None on CPU)
            "runtime_mfu": round(runtime["runtime_mfu"], 4),
            "cost_model_ratio": (
                None if runtime["cost_model_ratio"] is None
                else round(runtime["cost_model_ratio"], 3)),
            "flops_per_step_static": obs_info.get("flops_per_step"),
            "flops_error": obs_info.get("flops_error"),
            "measured_step_s": round(dt / steps, 4),
            # post-warmup compile-cache misses of the timed step (the
            # recompile sentinel; anything >0 poisons the timing)
            "recompiles": obs_info.get("recompiles"),
            # BASELINE config 4 (conv+attention diffusion flagship)
            "dit": dit_extra,
            # BASELINE config 5 (MoE expert-parallel)
            "moe": moe_extra,
            # serving decode throughput: paged KV + Pallas paged attention
            "decode": decode_extra,
            # unified ragged prefill+decode: ITL-under-concurrent-prefill
            # A/B (chunked vs one-shot vs decode-only baseline)
            "ragged": ragged_extra,
            # speculative decoding A/B (n-gram drafter + ragged verify
            # spans vs plain decode): emitted tokens/sec speedup +
            # acceptance rate on repetitive and adversarial workloads
            "specdec": specdec_extra,
            # cross-user prefix reuse A/B: TTFT at 0/50/95% hit mixes
            # over a shared system prompt + spliced-page fraction (the
            # page-table-splice admission vs cold chunked prefill)
            "prefix_reuse": prefix_extra,
            # observability-layer cost: decode ITL with full request
            # tracing (span tracer + per-request timelines + SLO) on vs
            # off — pinned < 2% so the layer stays on in soak runs
            "obs_overhead": obs_overhead_extra,
            # disaggregated prefill/decode A/B: streaming decode p99 ITL
            # under a long-prompt burst on a 1-prefill/2-decode fleet vs
            # 3-mixed, plus warm-start TTFT promoting a demoted prefix
            # from the tiered host store vs a cold chunked prefill
            "disagg": disagg_extra,
            # multi-tenant QoS A/B: paced high-priority tenant's p99
            # TTFT and end-to-end per-token latency under a bulk-tenant
            # flood, WFQ/priority admission on vs untagged FIFO (both
            # gates <= 0.8), plus the weight-normalized Jain fairness
            # index over per-tenant emitted tokens/sec
            "qos": qos_extra,
            # Graph Doctor finding counts over the shipped models
            # (tools/graphlint.py --json; tracks lint drift across rounds)
            "graphlint": graphlint_extra,
            # lock-discipline tier over the serving stack (graphlint
            # --threads): per-module race/lock-order/blocking/leak
            # finding counts — all lower-is-better in bench_diff
            "threadlint": threadlint_extra,
            # Pallas kernel verifier (graphlint --kernels): per-kernel
            # OOB/coverage/VMEM/dtype finding counts over the shipped
            # kernels + a generated fused chain — lower-is-better
            "kernellint": kernellint_extra,
            # per-model static memory peak (jaxpr liveness walker) so
            # BENCH_*.json tracks the footprint trend round over round
            "graphlint_mem_peak_bytes": graphlint_mem_peaks,
            # rewrite tier (graphlint --fix --apply): per-model eqn count
            # + static FLOPs/bytes before/after the verified passes —
            # what closing the lint->transform loop buys each round
            "rewrite": rewrite_extra,
            # SPMD tier (graphlint --mesh data=2,model=2): predicted
            # shardings + priced collectives + comm-vs-compute roofline
            # for the sharded llama step — the static substrate the
            # pod-scale partitioner work is measured against
            "spmd": spmd_extra,
            # fleet tier (tools/chaos_fleet.py --bench): placement
            # overhead per submit + failover-to-first-token under an
            # injected replica death (scripted engines — router-only
            # numbers, no model compute in the measurement)
            "router": router_extra,
        },
    }))


if __name__ == "__main__":
    def _diag_line(e: BaseException) -> None:
        print(json.dumps({
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": 0.0,
            "unit": "tokens/sec/chip",
            "vs_baseline": 0.0,
            "extra": {"error": repr(e)[:500], "backend_probe": _BACKEND},
        }))

    try:
        if len(sys.argv) >= 3 and sys.argv[1] == "--sub":
            _sub_main(sys.argv[2])
            sys.exit(0)
        main()
    except KeyboardInterrupt as e:
        _diag_line(e)
        sys.exit(130)
    except Exception as e:  # noqa: BLE001 — always emit one parseable line
        _diag_line(e)
        sys.exit(1)  # a broken bench must not look like a successful run
