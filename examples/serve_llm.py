"""Serve a Llama-family model with continuous batching + paged KV cache.

Starts an LLMEngine over a tiny model, exposes the batched HTTP endpoint,
fires concurrent requests at it, and checks the streamed-back tokens match
the offline greedy `generate()` chain.

Usage:  python examples/serve_llm.py
"""
import os
import sys

# allow running from a source checkout without installing
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import json
import threading
import urllib.request

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.inference import LLMEngine, serve_llm
from paddle_tpu.models import generation, llama
from paddle_tpu.models.llama import LlamaConfig


def main():
    cfg = LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    # a bounded queue (QueueFull -> HTTP 503 + Retry-After) and a page pool
    # sized for the EXPECTED footprint: under pressure the engine preempts
    # a victim (swap to host / resume later) instead of refusing admission
    engine = LLMEngine(params, cfg, num_slots=2, page_size=8, max_seq_len=64,
                       max_pending=32, preempt_mode="swap")
    srv, _ = serve_llm(engine)
    url = f"http://127.0.0.1:{srv.server_address[1]}/"
    print("serving on", url)

    with urllib.request.urlopen(url + "healthz", timeout=30) as resp:
        print("healthz:", json.loads(resp.read()))

    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, n).tolist() for n in (4, 6, 5)]
    results = [None] * len(prompts)

    def post(i):
        req = urllib.request.Request(url, data=json.dumps(
            {"prompt": prompts[i], "max_new_tokens": 8}).encode())
        results[i] = json.loads(
            urllib.request.urlopen(req, timeout=120).read())["tokens"]

    # 3 concurrent requests share 2 decode slots: the third is admitted the
    # moment a slot frees up (continuous batching), not after a full drain
    threads = [threading.Thread(target=post, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    for p, got in zip(prompts, results):
        want = np.asarray(generation.generate(
            params, jnp.asarray([p], jnp.int32), cfg,
            max_new_tokens=8))[0].tolist()
        assert got == want, (got, want)
        print("served tokens:", got)

    # request lifecycle: deadlines are enforced every engine step, and a
    # cancelled/expired request frees its slot+pages immediately
    doomed = engine.submit(prompts[0], max_new_tokens=40, deadline=600.0)
    doomed.cancel()
    try:
        doomed.result(timeout=30)
    except Exception as e:  # RequestCancelled
        print("cancelled request resolved with:", type(e).__name__)

    stats = json.loads(urllib.request.urlopen(url + "stats",
                                              timeout=30).read())
    print("engine stats:", stats)

    # the same counters as Prometheus text, plus the latency histograms
    # (TTFT / inter-token / queue-wait) a scraper ingests — /stats and
    # /metrics are rendered from one registry and cannot drift
    with urllib.request.urlopen(url + "metrics", timeout=30) as resp:
        assert resp.headers["Content-Type"].startswith("text/plain")
        metrics = resp.read().decode()
    print("metrics sample:")
    for line in metrics.splitlines():
        if line.startswith(("llm_ttft_seconds_count",
                            "llm_inter_token_seconds_count",
                            "llm_completed_total")):
            print(" ", line)
    srv.shutdown()
    print("OK")


if __name__ == "__main__":
    main()
