"""Recsys-style training: PS-hosted embeddings + device dense net.

Usage:  python examples/recsys_ps.py
The sparse half lives on parameter servers (host memory); only the rows a
batch touches reach the device — the heterogeneous capacity split.
"""
import os
import sys

# allow running from a source checkout without installing
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax.numpy as jnp
import numpy as np

from paddle_tpu.distributed.ps import HeterTrainer, PSClient, PSServer
from paddle_tpu.optimizer.functional import AdamW


def main():
    rng = np.random.default_rng(0)
    n_users, dim, batch = 10_000, 16, 64
    true_emb = rng.normal(size=(n_users, dim)).astype(np.float32)
    true_w = rng.normal(size=(dim,)).astype(np.float32)

    trainer = HeterTrainer(
        PSClient([PSServer(), PSServer()]), table_id=0, dim=dim,
        dense_params={"w": np.zeros(dim, np.float32),
                      "b": np.zeros((), np.float32)},
        dense_apply=lambda p, rows, y: jnp.mean(
            (rows @ p["w"] + p["b"] - y) ** 2),
        dense_optimizer=AdamW(learning_rate=0.05, weight_decay=0.0),
        table_kwargs=dict(optimizer="adagrad", lr=0.3, initial_range=0.05))

    for step in range(200):
        ids = rng.integers(0, n_users, batch)
        y = jnp.asarray((true_emb[ids] @ true_w).astype(np.float32))
        loss = trainer.step(ids, y)
        if step % 40 == 0 or step == 199:
            rows = sum(s.sparse_table_size(0)
                       for s in trainer.client.servers)
            print(f"step {step:3d}  loss {loss:.4f}  rows touched {rows}")


if __name__ == "__main__":
    main()
