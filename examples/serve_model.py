"""Export a model, load it with the inference Predictor, serve over HTTP.

Usage:  python examples/serve_model.py
"""
import os
import sys

# allow running from a source checkout without installing
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import json
import tempfile
import urllib.request

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import inference


def main():
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 3))
    prefix = tempfile.mkdtemp() + "/model"
    paddle.jit.save(net, prefix, input_spec=[
        paddle.static.InputSpec([1, 8], "float32", name="x")])
    print("exported StableHLO artifact:", prefix + ".stablehlo")

    predictor = inference.create_predictor(inference.Config(prefix))
    srv, _ = inference.serve(predictor)
    url = f"http://127.0.0.1:{srv.server_address[1]}/"
    x = np.random.default_rng(0).normal(size=(1, 8)).astype(np.float32)
    req = urllib.request.Request(
        url, data=json.dumps({"inputs": [x.tolist()]}).encode())
    out = json.loads(urllib.request.urlopen(req, timeout=30).read())
    print("served prediction:", out["outputs"][0])
    np.testing.assert_allclose(out["outputs"][0],
                               np.asarray(net(paddle.to_tensor(x)).numpy()),
                               rtol=1e-4, atol=1e-4)
    srv.shutdown()
    print("OK")


if __name__ == "__main__":
    main()
