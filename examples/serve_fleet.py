"""Fleet-tier serving: N replicas behind a Router that survives replica
death.

Builds a 2-replica fleet with an EngineSupervisor, exposes the fleet
HTTP endpoint (aggregate /healthz, per-replica-labelled /metrics),
serves traffic, then KILLS a replica mid-service and shows the fleet
keep answering token-exactly while the supervisor rebuilds the dead
replica and the canary gate reinstates it.

The default fleet runs ScriptedEngines — the real LLMEngine scheduler
with deterministic scripted compute — because the fleet machinery is
model-agnostic and the point here is the robustness choreography.  Pass
--real to run the same fleet over tiny-llama LLMEngines (slower: each
replica compiles its own programs).

Pass --roles to disaggregate the fleet: prefill-class replicas run the
prompt and hand the finished KV pages to a decode-class replica over
the host-staged transfer path, a shared tiered prefix store keeps
demoted prefixes warm, and the same death choreography applies per
class.

Usage:  python examples/serve_fleet.py [--real]
        python examples/serve_fleet.py --roles prefill=1,decode=2
"""
import os
import sys

# allow running from a source checkout without installing
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import argparse
import json
import time
import urllib.request


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--real", action="store_true",
                    help="tiny-llama LLMEngine replicas instead of "
                         "scripted ones")
    ap.add_argument("--roles", default=None, metavar="SPEC",
                    help="disaggregate the fleet, e.g. "
                         "'prefill=1,decode=2' (replica count follows "
                         "from the spec; default stays 2 mixed)")
    args = ap.parse_args()

    from paddle_tpu.inference import faults as F
    from paddle_tpu.inference.router import Router, serve_fleet
    from paddle_tpu.inference.supervisor import EngineSupervisor

    if args.real:
        import jax

        from paddle_tpu.inference import LLMEngine
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig

        cfg = LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))

        def factory():
            return LLMEngine(params, cfg, num_slots=2, page_size=8,
                             max_seq_len=64, max_pending=32)

        def reference(prompt, n):
            import jax.numpy as jnp
            import numpy as np

            from paddle_tpu.models import generation
            return np.asarray(generation.generate(
                params, jnp.asarray([prompt], jnp.int32), cfg,
                max_new_tokens=n))[0].tolist()
    else:
        def factory():
            return F.ScriptedEngine(num_slots=2, page_size=4,
                                    max_seq_len=16, max_pending=32)

        def reference(prompt, n):
            return F.ScriptedEngine.reference_tokens(prompt, n)

    fleet_kw = {"num_replicas": 2}
    if args.roles:
        # replica count follows from the spec ("prefill=1,decode=2" ->
        # 3); the shared store is what lets a decode replica serve a
        # prefix its prefill peer demoted
        from paddle_tpu.inference.kvstore import TieredPrefixStore

        n = sum(int(part.split("=", 1)[1]) if "=" in part else 1
                for part in args.roles.split(",") if part.strip())
        fleet_kw = {"num_replicas": max(n, 2), "roles": args.roles,
                    "kvstore": TieredPrefixStore()}
    router = Router(factory=factory, threaded=True,
                    supervisor=EngineSupervisor(factory),
                    health_interval=0.01, backoff_base=0.05, **fleet_kw)
    if args.roles:
        print("replica roles:", router.stats_snapshot()["replica_roles"])
    srv, _ = serve_fleet(router)
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    print("fleet serving on", url)

    def post(prompt, n):
        req = urllib.request.Request(url + "/", data=json.dumps(
            {"prompt": prompt, "max_new_tokens": n}).encode())
        return json.loads(urllib.request.urlopen(req, timeout=120).read())

    with urllib.request.urlopen(url + "/healthz", timeout=30) as resp:
        print("healthz:", json.loads(resp.read()))

    # serve a few requests; outputs must match the single-engine chain
    for i in range(3):
        prompt = [1 + i, 2, 3]
        out = post(prompt, 4)
        assert out["tokens"] == reference(prompt, 4), out
        print(f"served {prompt} -> {out['tokens']} (hops {out['hops']})")

    # kill replica 0 mid-service: the router detects the dead step
    # thread, retries safely-recoverable work on replica 1, and the
    # supervisor rebuilds replica 0 behind the canary gate
    print("\n-- killing replica 0 --")
    router.kill(router.replicas[0])
    served = 0
    for i in range(6):
        prompt = [9, i, 1]
        out = post(prompt, 3)
        assert out["tokens"] == reference(prompt, 3), out
        served += 1
    print(f"fleet answered {served}/6 requests during/after the death")

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if (router.stats["rebuilds"] >= 1
                and router.stats["reinstatements"] >= 1):
            break
        time.sleep(0.05)
    snap = router.stats_snapshot()
    print("deaths:", snap["deaths"], "rebuilds:", snap["rebuilds"],
          "reinstatements:", snap["reinstatements"],
          "replica states:", snap["replica_states"])
    assert snap["deaths"] >= 1 and snap["rebuilds"] >= 1

    # one scrape shows fleet counters + per-replica placement signals
    with urllib.request.urlopen(url + "/metrics", timeout=30) as resp:
        text = resp.read().decode()
    wanted = [ln for ln in text.splitlines()
              if ln.startswith(("fleet_deaths_total", "fleet_rebuilds_",
                                "llm_queue_depth", "llm_free_pages"))]
    print("\nmetrics excerpt:")
    print("\n".join(wanted))

    report = F.fleet_check_invariants(router, [], probe=True)
    print("\nfleet invariants ok:", report["ok"])
    srv.shutdown()
    print("drained and shut down")


if __name__ == "__main__":
    main()
