"""Train a Llama-style model with the auto-parallelize planner.

Usage:  python examples/train_llama.py [--steps N] [--trace out.json]
Runs on whatever devices jax sees (one TPU chip, or the 8-virtual-device
CPU mesh under JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8).

Telemetry rides the hapi ObsCallback (paddle_tpu.obs): each step is a
fenced `train_step` span in its own step lane, the recompile sentinel
watches the jitted step for post-warmup cache misses, and the run ends
with a per-span summary table plus a measured-vs-static report —
runtime MFU (measured step time x cost-pass FLOPs / chip peak) and
`cost_model_ratio` (measured / predicted step time).  `--trace out.json`
exports the spans as Chrome/Perfetto JSON (load in ui.perfetto.dev, or
summarize with tools/trace_summary.py).
"""
import os
import sys

# allow running from a source checkout without installing
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import argparse

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.distributed.auto_tuner import auto_parallelize, V5E
from paddle_tpu.hapi.callbacks import ObsCallback
from paddle_tpu.models import llama
from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.obs import mfu as obs_mfu
from paddle_tpu.obs import trace as obs_trace


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--trace", default=None, metavar="OUT.json",
                    help="export a Chrome/Perfetto trace of the run")
    args = ap.parse_args()

    cfg = LlamaConfig(vocab_size=1024, hidden_size=256, intermediate_size=512,
                      num_hidden_layers=4, num_attention_heads=8,
                      num_key_value_heads=4, max_position_embeddings=args.seq)
    state, plan = auto_parallelize(cfg, llama, global_batch=args.batch,
                                   seq=args.seq, chip=V5E)
    print(f"plan: mesh={plan.mesh_sizes} zero={plan.zero_stage} "
          f"est {plan.step_time*1e3:.1f} ms/step")
    params, opt = state.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)

    # the training-side telemetry hookup: spans + step-time histogram +
    # recompile sentinel, driven through the hapi callback protocol
    obs = ObsCallback(export_path=args.trace,
                      fence_of=lambda logs: logs.get("metrics"))
    obs.on_train_begin()
    watched = False
    flops_per_step = None
    for step in range(args.steps):
        toks = rng.integers(0, cfg.vocab_size, (args.batch, args.seq + 1))
        batch = state.shard_batch(llama.lm_batch_from_tokens(
            jnp.asarray(toks, jnp.int32)))
        if not watched:
            # one batch structure -> one jitted executable: watch it for
            # post-warmup recompiles and price it once with the cost pass
            obs.watch("llama_train_step", state.jitted_step(batch))
            try:
                flops_per_step = obs_mfu.static_flops(
                    state.jitted_step(batch), params, opt, batch)
            except Exception as e:  # noqa: BLE001 — cost must not kill
                print(f"static cost unavailable: {e!r:.120}")
            watched = True
        obs.on_train_batch_begin(step)
        params, opt, m = state.step(params, opt, batch)
        obs.on_train_batch_end(step, logs={"metrics": m})
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step:3d}  loss {float(m['loss']):.4f}  "
                  f"gnorm {float(m['grad_norm']):.3f}")
    obs.on_train_end()

    summ = obs.step_summary()
    print(obs_trace.format_summary(
        obs_trace.summarize(obs.tracer.events())))
    if summ["steps"] and flops_per_step is not None:
        report = obs_mfu.runtime_report(summ["mean_step_s"], flops_per_step)
        ratio = report["cost_model_ratio"]
        print(f"measured {summ['mean_step_s']*1e3:.1f} ms/step "
              f"(p99 {summ['p99_step_s']*1e3:.1f})  "
              f"runtime MFU {report['runtime_mfu']:.3f}  "
              f"cost_model_ratio "
              f"{'n/a (no peak for this backend)' if ratio is None else f'{ratio:.2f}'}  "
              f"recompiles {obs.sentinel.counts()}")
    if args.trace:
        print(f"trace written to {args.trace} "
              f"(summarize: python tools/trace_summary.py {args.trace})")


if __name__ == "__main__":
    main()
