"""Train a Llama-style model with the auto-parallelize planner.

Usage:  python examples/train_llama.py [--steps N]
Runs on whatever devices jax sees (one TPU chip, or the 8-virtual-device
CPU mesh under JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8).
"""
import os
import sys

# allow running from a source checkout without installing
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import argparse

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.distributed.auto_tuner import auto_parallelize, V5E
from paddle_tpu.models import llama
from paddle_tpu.models.llama import LlamaConfig


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    args = ap.parse_args()

    cfg = LlamaConfig(vocab_size=1024, hidden_size=256, intermediate_size=512,
                      num_hidden_layers=4, num_attention_heads=8,
                      num_key_value_heads=4, max_position_embeddings=args.seq)
    state, plan = auto_parallelize(cfg, llama, global_batch=args.batch,
                                   seq=args.seq, chip=V5E)
    print(f"plan: mesh={plan.mesh_sizes} zero={plan.zero_stage} "
          f"est {plan.step_time*1e3:.1f} ms/step")
    params, opt = state.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    for step in range(args.steps):
        toks = rng.integers(0, cfg.vocab_size, (args.batch, args.seq + 1))
        batch = state.shard_batch(llama.lm_batch_from_tokens(
            jnp.asarray(toks, jnp.int32)))
        params, opt, m = state.step(params, opt, batch)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step:3d}  loss {float(m['loss']):.4f}  "
                  f"gnorm {float(m['grad_norm']):.3f}")


if __name__ == "__main__":
    main()
