"""Flagship Llama model: correctness + sharded training on the 8-device CPU mesh.

Mirrors the reference test strategy (SURVEY.md §4): numeric checks on tiny
configs, distributed paths exercised on a virtual multi-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models import llama
from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.distributed import mesh as mesh_lib
from paddle_tpu.distributed.parallelize import ShardedTrainState
from paddle_tpu.optimizer.functional import AdamW, cosine_schedule


def tiny():
    return LlamaConfig.tiny()


class TestForward:
    def test_shapes_and_dtype(self):
        c = tiny()
        params = llama.init_params(c, seed=0)
        ids = jnp.array(np.random.randint(0, c.vocab_size, (2, 16)), dtype=jnp.int32)
        logits = llama.forward(params, ids, c)
        assert logits.shape == (2, 16, c.vocab_size)
        assert logits.dtype == jnp.float32

    def test_scan_matches_unrolled(self):
        c = tiny()
        params = llama.init_params(c, seed=1)
        ids = jnp.array(np.random.randint(0, c.vocab_size, (2, 12)), dtype=jnp.int32)
        a = llama.forward(params, ids, c)
        c2 = LlamaConfig(**{**c.__dict__, "scan_layers": False})
        b = llama.forward(params, ids, c2)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5)

    def test_causality(self):
        """Changing a future token must not change past logits."""
        c = tiny()
        params = llama.init_params(c, seed=2)
        ids = np.random.randint(0, c.vocab_size, (1, 10)).astype(np.int32)
        la = llama.forward(params, jnp.asarray(ids), c)
        ids2 = ids.copy()
        ids2[0, -1] = (ids2[0, -1] + 7) % c.vocab_size
        lb = llama.forward(params, jnp.asarray(ids2), c)
        np.testing.assert_allclose(np.asarray(la[0, :-1]), np.asarray(lb[0, :-1]),
                                   rtol=1e-5, atol=1e-5)

    def test_tied_embeddings(self):
        c = LlamaConfig(**{**tiny().__dict__, "tie_word_embeddings": True})
        params = llama.init_params(c, seed=0)
        assert "lm_head" not in params
        ids = jnp.zeros((1, 4), dtype=jnp.int32)
        assert llama.forward(params, ids, c).shape == (1, 4, c.vocab_size)

    @pytest.mark.slow
    def test_remat_matches(self):
        c = tiny()
        c_remat = LlamaConfig(**{**c.__dict__, "remat": True})
        params = llama.init_params(c, seed=3)
        ids = jnp.array(np.random.randint(0, c.vocab_size, (1, 8)), dtype=jnp.int32)
        batch = {"input_ids": ids, "labels": ids}
        g1 = jax.grad(llama.loss_fn)(params, batch, c)
        g2 = jax.grad(llama.loss_fn)(params, batch, c_remat)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)

    def test_remat_save_attn_policy_matches(self):
        """save_attn (checkpoint_name'd attention outputs kept, qkv+attention
        skipped in the backward recompute) is numerics-identical to full."""
        import dataclasses
        c = tiny()
        params = llama.init_params(c, seed=3)
        ids = jnp.array(np.random.randint(0, c.vocab_size, (1, 8)), dtype=jnp.int32)
        batch = {"input_ids": ids, "labels": ids}
        c_full = dataclasses.replace(c, remat=True)
        c_sa = dataclasses.replace(c, remat=True, remat_policy="save_attn")
        g1 = jax.grad(llama.loss_fn)(params, batch, c_full)
        g2 = jax.grad(llama.loss_fn)(params, batch, c_sa)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
        with pytest.raises(ValueError, match="remat_policy"):
            llama.loss_fn(params, batch,
                          dataclasses.replace(c, remat=True,
                                              remat_policy="bogus"))


class TestLoss:
    def test_ignore_index(self):
        c = tiny()
        params = llama.init_params(c, seed=0)
        ids = jnp.array(np.random.randint(0, c.vocab_size, (2, 8)), dtype=jnp.int32)
        labels = ids.at[:, :4].set(-100)
        l_masked = llama.loss_fn(params, {"input_ids": ids, "labels": labels}, c)
        assert np.isfinite(float(l_masked))
        # fully-ignored batch yields 0 (guarded denominator)
        l_zero = llama.loss_fn(
            params, {"input_ids": ids, "labels": jnp.full_like(ids, -100)}, c)
        assert float(l_zero) == 0.0

    def test_loss_decreases_training(self):
        c = tiny()
        params = llama.init_params(c, seed=0)
        opt = AdamW(learning_rate=1e-2, grad_clip_norm=1.0)
        state = opt.init(params)
        tokens = jnp.array(np.random.randint(0, c.vocab_size, (4, 17)), dtype=jnp.int32)
        batch = llama.lm_batch_from_tokens(tokens)

        @jax.jit
        def step(params, state):
            loss, grads = jax.value_and_grad(llama.loss_fn)(params, batch, c)
            params, state = opt.update(grads, state, params)
            return params, state, loss

        losses = []
        for _ in range(12):
            params, state, loss = step(params, state)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, losses


class TestShardedTraining:
    @pytest.mark.parametrize("layout", [
        dict(data=2, sharding=2, model=2),  # hybrid — default-run coverage
        pytest.param(dict(data=8), marks=pytest.mark.slow),
        pytest.param(dict(data=2, model=4), marks=pytest.mark.slow),
        pytest.param(dict(data=2, model=2, sep=2), marks=pytest.mark.slow),
    ])
    def test_train_step_layouts(self, layout):
        c = tiny()
        mesh = mesh_lib.make_mesh(**layout)
        st = ShardedTrainState(c, llama, mesh,
                               AdamW(learning_rate=1e-3, grad_clip_norm=1.0))
        params, opt_state = st.init(jax.random.PRNGKey(0))
        tokens = np.random.randint(0, c.vocab_size, (8, 17)).astype(np.int32)
        batch = st.shard_batch(llama.lm_batch_from_tokens(jnp.asarray(tokens)))
        params, opt_state, metrics = st.step(params, opt_state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert np.isfinite(float(metrics["grad_norm"]))

    @pytest.mark.slow
    def test_tp_matches_single_device(self):
        """The same step on dp=1 mesh vs tp=4 mesh gives the same loss."""
        c = tiny()
        tokens = np.random.randint(0, c.vocab_size, (4, 17)).astype(np.int32)
        batch_np = llama.lm_batch_from_tokens(jnp.asarray(tokens))
        losses = {}
        for name, layout in [("single", dict(data=1)), ("tp", dict(model=4))]:
            mesh = mesh_lib.make_mesh(**layout)
            st = ShardedTrainState(c, llama, mesh, AdamW(learning_rate=1e-3))
            params, opt_state = st.init(jax.random.PRNGKey(7))
            batch = st.shard_batch(batch_np)
            _, _, metrics = st.step(params, opt_state, batch)
            losses[name] = float(metrics["loss"])
        assert abs(losses["single"] - losses["tp"]) < 1e-3, losses

    def test_zero_shards_optimizer_state(self):
        c = tiny()
        mesh = mesh_lib.make_mesh(data=2, sharding=4)
        st = ShardedTrainState(c, llama, mesh, zero_stage=1)
        params, opt_state = st.init(jax.random.PRNGKey(0))
        # at least one optimizer-state leaf must be sharded over 'sharding'
        sharded = [
            l for l in jax.tree.leaves(opt_state.m)
            if any("sharding" in str(p) for p in l.sharding.spec)
        ]
        assert sharded, "ZeRO-1: no optimizer state sharded over the sharding axis"


class TestUtils:
    def test_num_params_tiny(self):
        c = tiny()
        n = llama.num_params(c)
        assert n > 0
        params = llama.init_params(c, seed=0)
        manual = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
        assert n == manual

    def test_flops_positive(self):
        assert llama.flops_per_token(LlamaConfig.llama3_8b(), 4096) > 1e10

    def test_cosine_schedule(self):
        lr = cosine_schedule(1e-3, 10, 100)
        assert float(lr(jnp.asarray(0))) == 0.0
        assert abs(float(lr(jnp.asarray(10))) - 1e-3) < 1e-9
        assert float(lr(jnp.asarray(100))) < 2e-4
