"""Test config: force an 8-virtual-device CPU platform BEFORE jax imports.

This mirrors the reference's distributed-test strategy (SURVEY.md §4: localhost
multi-process NCCL) mapped to TPU-style testing: a virtual 8-device CPU mesh
exercises every sharding/collective path without hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override ambient axon/tpu setting
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle

    np.random.seed(2024)
    paddle.seed(2024)
    yield
