"""Test config: force an 8-virtual-device CPU platform BEFORE jax imports.

This mirrors the reference's distributed-test strategy (SURVEY.md §4: localhost
multi-process NCCL) mapped to TPU-style testing: a virtual 8-device CPU mesh
exercises every sharding/collective path without hardware.

The device count is process-global (XLA fixes it at backend init), so it
cannot literally vary per test — instead it is OPT-IN by declaration:

  * modules/tests that NEED a multi-device platform mark themselves
    ``@pytest.mark.multidevice(4)`` (or use the ``forced_mesh`` fixture)
    and are SKIPPED, not failed, when the session has fewer devices;
  * ``PADDLE_HOST_DEVICES=N`` overrides the forced count (``0``/``1``
    disables forcing entirely — a true single-device session), leaving
    undeclared tests (including the 5 legacy-jax known-fails) untouched.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override ambient axon/tpu setting
_n_dev = os.environ.get("PADDLE_HOST_DEVICES", "8")
flags = os.environ.get("XLA_FLAGS", "")
if _n_dev not in ("0", "1") \
        and "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={_n_dev}").strip()
# persistent compilation cache: repeat suite runs skip XLA compiles (~4x on
# this box; .jax_cache is gitignored)
_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(_repo, ".jax_cache"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# the env vars above are NOT read by this jax version — set explicitly
# (verified: an empty .jax_cache after full runs; with these, repeat suite
# runs skip most XLA compiles)
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle

    np.random.seed(2024)
    paddle.seed(2024)
    yield


def pytest_addoption(parser):
    parser.addoption("--full", action="store_true", default=False,
                     help="also run tests marked slow")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running coverage test (run with --full or "
        "PADDLE_FULL_TESTS=1; the driver/CI budget keeps the default run "
        "under 300s)")
    config.addinivalue_line(
        "markers", "multidevice(n): test needs >= n forced host devices; "
        "skipped (not failed) when the session has fewer (e.g. "
        "PADDLE_HOST_DEVICES=1)")


def pytest_collection_modifyitems(config, items):
    n_avail = len(jax.devices())
    for item in items:
        m = item.get_closest_marker("multidevice")
        if m is not None:
            need = int(m.args[0]) if m.args else 2
            if n_avail < need:
                item.add_marker(pytest.mark.skip(
                    reason=f"needs {need} devices, session has {n_avail} "
                    "(multidevice is opt-in; see PADDLE_HOST_DEVICES)"))
    if config.getoption("--full") or os.environ.get("PADDLE_FULL_TESTS"):
        return
    skip = pytest.mark.skip(reason="slow (use --full)")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


# gen-2 GC rescans every live container object; late in the suite the
# process holds millions of long-lived ones (jit caches, jaxprs, modules)
# and automatic gen-2 passes dominate -- the same test measures 2-5x
# slower at 90% suite position than in isolation.  Periodically collect
# once and freeze the survivors into the permanent generation so future
# passes scan only fresh allocations.  Refcounting (and hence ordinary
# deallocation) is unaffected; only cycle detection skips frozen objects.
_GC_FREEZE_EVERY = 40
_gc_teardowns = [0]


def pytest_runtest_teardown(item, nextitem):
    import gc

    _gc_teardowns[0] += 1
    if _gc_teardowns[0] % _GC_FREEZE_EVERY == 0:
        gc.collect()
        gc.freeze()


@pytest.fixture
def forced_mesh():
    """A 2x2 (data x model) mesh over the forced host devices — the
    fixture form of the ``multidevice`` opt-in (skips when the session
    is single-device)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 forced host devices")
    from paddle_tpu.distributed import mesh as mesh_lib

    return mesh_lib.make_mesh(data=2, model=2)
