"""Test config: force an 8-virtual-device CPU platform BEFORE jax imports.

This mirrors the reference's distributed-test strategy (SURVEY.md §4: localhost
multi-process NCCL) mapped to TPU-style testing: a virtual 8-device CPU mesh
exercises every sharding/collective path without hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override ambient axon/tpu setting
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
# persistent compilation cache: repeat suite runs skip XLA compiles (~4x on
# this box; .jax_cache is gitignored)
_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(_repo, ".jax_cache"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# the env vars above are NOT read by this jax version — set explicitly
# (verified: an empty .jax_cache after full runs; with these, repeat suite
# runs skip most XLA compiles)
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle

    np.random.seed(2024)
    paddle.seed(2024)
    yield


def pytest_addoption(parser):
    parser.addoption("--full", action="store_true", default=False,
                     help="also run tests marked slow")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running coverage test (run with --full or "
        "PADDLE_FULL_TESTS=1; the driver/CI budget keeps the default run "
        "under 300s)")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--full") or os.environ.get("PADDLE_FULL_TESTS"):
        return
    skip = pytest.mark.skip(reason="slow (use --full)")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
