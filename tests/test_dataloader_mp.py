"""Multiprocess DataLoader (reference io/dataloader/dataloader_iter.py:358)."""

import numpy as np
import pytest

from paddle_tpu.io import DataLoader, Dataset


class RangeSquares(Dataset):
    """Module-level (picklable for spawned workers)."""

    def __init__(self, n=32):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.array([i, i * i], np.float32)


class TestMultiprocessLoader:
    @pytest.mark.slow
    def test_order_and_values_match_sync(self):
        ds = RangeSquares(24)
        sync = [np.asarray(b) for b in DataLoader(ds, batch_size=4,
                                                  num_workers=0)]
        mp = [np.asarray(b) for b in DataLoader(ds, batch_size=4,
                                                num_workers=2)]
        assert len(mp) == len(sync) == 6
        for a, b in zip(mp, sync):
            np.testing.assert_array_equal(a, b)

    def test_worker_failure_surfaces(self):
        class Bad(RangeSquares):
            pass
        # Bad is local (unpicklable by spawn) -> falls back to thread path,
        # which still works
        out = list(DataLoader(Bad(8), batch_size=4, num_workers=2))
        assert len(out) == 2

    def test_unpicklable_collate_falls_back(self):
        marker = []
        out = list(DataLoader(RangeSquares(8), batch_size=4, num_workers=1,
                              collate_fn=lambda b: (marker, np.stack(b))[1]))
        assert len(out) == 2
