"""Quantization depth (VERDICT r3 weak #2 / next #7): per-channel +
moving-average observers, QuantedConv2D, and the weight-only-int8 path
consumed by inference.Predictor."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import inference
from paddle_tpu.quantization import (
    QAT, PTQ, AbsmaxObserver, FakeQuanterChannelWiseAbsMax,
    FakeQuanterWithAbsMaxObserver, MovingAverageAbsmaxObserver,
    PerChannelAbsmaxObserver, QuantConfig, QuantedConv2D, QuantedLinear)


class ConvNet(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.conv = paddle.nn.Conv2D(3, 8, 3, padding=1)
        self.fc = paddle.nn.Linear(8 * 4 * 4, 5)

    def forward(self, x):
        h = paddle.nn.functional.relu(self.conv(x))
        h = paddle.nn.functional.adaptive_avg_pool2d(h, 4)
        return self.fc(paddle.flatten(h, 1))


def _x(b=2):
    return np.random.default_rng(0).normal(size=(b, 3, 8, 8)).astype(
        np.float32) * 0.5


class TestObservers:
    def test_per_channel_scales_shape(self):
        obs = PerChannelAbsmaxObserver(quant_axis=0)
        w = np.zeros((4, 3), np.float32)
        w[1] = 10.0  # one outlier channel
        w[2] = 0.1
        obs(paddle.to_tensor(w))
        s = obs.scales().numpy().reshape(-1)
        assert s.shape == (4,)
        assert s[1] == pytest.approx(10.0) and s[2] == pytest.approx(0.1)

    def test_per_channel_running_max(self):
        obs = PerChannelAbsmaxObserver(quant_axis=0)
        obs(paddle.to_tensor(np.array([[1.0], [5.0]], np.float32)))
        obs(paddle.to_tensor(np.array([[3.0], [2.0]], np.float32)))
        s = obs.scales().numpy().reshape(-1)
        np.testing.assert_allclose(s, [3.0, 5.0])

    def test_moving_average_observer_smooths_outlier(self):
        obs = MovingAverageAbsmaxObserver(moving_rate=0.9)
        for _ in range(5):
            obs(paddle.to_tensor(np.ones((4,), np.float32)))
        steady = float(obs.scales().numpy())
        obs(paddle.to_tensor(100 * np.ones((4,), np.float32)))
        after = float(obs.scales().numpy())
        assert after < 100 * 0.2, "EMA should damp a single outlier batch"
        assert after > steady


class TestQuantedConv2D:
    def test_qat_swaps_conv_and_linear(self):
        net = ConvNet()
        q = QuantConfig(activation=FakeQuanterWithAbsMaxObserver,
                        weight=FakeQuanterChannelWiseAbsMax)
        qnet = QAT(q).quantize(net)
        assert isinstance(qnet.conv, QuantedConv2D)
        assert isinstance(qnet.fc, QuantedLinear)

    def test_qat_forward_close_and_trainable(self):
        paddle.seed(3)
        net = ConvNet()
        x = paddle.to_tensor(_x())
        ref = net(x).numpy()
        q = QuantConfig(activation=FakeQuanterWithAbsMaxObserver,
                        weight=FakeQuanterChannelWiseAbsMax)
        qnet = QAT(q).quantize(net)
        out = qnet(x)
        np.testing.assert_allclose(out.numpy(), ref, rtol=0.25, atol=0.25)
        # STE: grads flow to the original weights
        loss = paddle.sum(out * out)
        loss.backward()
        assert qnet.conv.weight.grad is not None
        assert np.isfinite(qnet.conv.weight.grad.numpy()).all()

    def test_per_channel_beats_per_tensor_with_outlier_channel(self):
        """The motivating case: one huge output channel destroys per-tensor
        int8 resolution for the small channels."""
        paddle.seed(4)
        lin = paddle.nn.Linear(16, 8)
        w = lin.weight.numpy().copy()
        w[:, 0] *= 100.0  # outlier output channel
        lin.weight.set_value(w)
        x = paddle.to_tensor(np.random.default_rng(5).normal(
            size=(4, 16)).astype(np.float32))
        ref = lin(x).numpy()

        def err(weight_quanter):
            q = QuantConfig(activation=None, weight=weight_quanter)
            qnet = QAT(q).quantize(lin)
            got = qnet(x).numpy()
            # compare on the small channels (1..7)
            return np.abs(got[:, 1:] - ref[:, 1:]).max()

        e_tensor = err(FakeQuanterWithAbsMaxObserver)
        e_channel = err(lambda: FakeQuanterChannelWiseAbsMax(quant_axis=-1))
        assert e_channel < e_tensor / 4, (e_channel, e_tensor)


class TestPTQToPredictor:
    def test_ptq_convert_serve_parity(self, tmp_path):
        """The full weight-only-int8 deployment path: PTQ calibrate ->
        convert (int8 weights + per-channel scales) -> jit.save ->
        Predictor -> parity within int8 tolerance."""
        paddle.seed(6)
        net = ConvNet()
        xs = [_x() for _ in range(4)]
        ref = net(paddle.to_tensor(xs[0])).numpy()

        cfg = QuantConfig(activation=MovingAverageAbsmaxObserver,
                          weight=lambda: PerChannelAbsmaxObserver(
                              quant_axis=0))
        ptq = PTQ(cfg)
        qnet = ptq.quantize(net)
        for x in xs:  # calibration passes
            qnet(paddle.to_tensor(x))
        deployed = ptq.convert(qnet)

        # int8 weights actually stored
        assert deployed.conv.w_int8.numpy().dtype == np.int8
        assert deployed.fc.w_int8.numpy().dtype == np.int8
        # per-channel conv scales: one per output channel
        assert deployed.conv.weight_scale.numpy().size == 8

        out = deployed(paddle.to_tensor(xs[0])).numpy()
        np.testing.assert_allclose(out, ref, rtol=0.1, atol=0.1)

        prefix = str(tmp_path / "q_net")
        paddle.jit.save(deployed, prefix, input_spec=[
            paddle.static.InputSpec([2, 3, 8, 8], "float32", name="x")])
        pred = inference.create_predictor(inference.Config(prefix))
        (served,) = pred.run([xs[0]])
        np.testing.assert_allclose(served, out, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(served, ref, rtol=0.1, atol=0.1)
