"""Decode path: KV-cache generation + masked_multihead_attention + serving.

Reference: PaddleNLP generation over analysis_predictor (C39) and the
masked_multihead_attention decode kernel.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models import generation, llama
from paddle_tpu.models.llama import LlamaConfig


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


class TestKVCache:
    def test_cached_prefill_matches_full_forward(self, tiny):
        cfg, params = tiny
        ids = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 12)),
            jnp.int32)
        full = llama.forward(params, ids, cfg)
        cache = generation.init_kv_cache(cfg, 2, 16)
        cached, _ = generation.forward_with_cache(params, ids, cfg, cache, 0)
        np.testing.assert_allclose(np.asarray(cached), np.asarray(full),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.slow
    def test_incremental_decode_matches_full_forward(self, tiny):
        """Prefill 8 then decode 4 one-by-one == full forward on 12."""
        cfg, params = tiny
        rng = np.random.default_rng(1)
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 12)), jnp.int32)
        full = llama.forward(params, ids, cfg)
        cache = generation.init_kv_cache(cfg, 2, 12)
        _, cache = generation.forward_with_cache(
            params, ids[:, :8], cfg, cache, 0)
        outs = []
        for i in range(8, 12):
            lg, cache = generation.forward_with_cache(
                params, ids[:, i:i + 1], cfg, cache, i)
            outs.append(lg[:, 0])
        got = jnp.stack(outs, axis=1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(full[:, 8:12]),
                                   rtol=2e-4, atol=2e-4)

    def _greedy_reference(self, cfg, params, ids, n):
        seq = ids
        for _ in range(n):
            nxt = jnp.argmax(llama.forward(params, seq, cfg)[:, -1], -1)
            seq = jnp.concatenate([seq, nxt[:, None].astype(jnp.int32)], 1)
        return seq[:, ids.shape[1]:]

    def test_greedy_matches_uncached_chain(self, tiny):
        """Regression: decode positions were off by one (cache slot S+i vs
        S+i-1), which only a multi-token uncached-parity check catches."""
        cfg, params = tiny
        ids = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 6)),
            jnp.int32)
        a = generation.generate(params, ids, cfg, max_new_tokens=5)
        np.testing.assert_array_equal(
            np.asarray(a),
            np.asarray(self._greedy_reference(cfg, params, ids, 5)))

    @pytest.mark.slow
    def test_greedy_generate_deterministic_and_consistent(self, tiny):
        cfg, params = tiny
        for seed in range(1, 5):  # multiple prompts: parity is not seed luck
            ids = jnp.asarray(
                np.random.default_rng(seed).integers(0, cfg.vocab_size, (2, 6)),
                jnp.int32)
            a = generation.generate(params, ids, cfg, max_new_tokens=5)
            b = generation.generate(params, ids, cfg, max_new_tokens=5)
            assert a.shape == (2, 5)
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            np.testing.assert_array_equal(
                np.asarray(a),
                np.asarray(self._greedy_reference(cfg, params, ids, 5)))

    def test_left_padded_batch_matches_per_row(self, tiny):
        """Variable-length prompts (left-padded + attention_mask) must
        generate exactly what each row generates alone, unpadded."""
        cfg, params = tiny
        rng = np.random.default_rng(5)
        lens = [4, 7]
        S = max(lens)
        ids = np.zeros((2, S), np.int32)
        mask = np.zeros((2, S), np.int32)
        rows = [rng.integers(0, cfg.vocab_size, n) for n in lens]
        for b, (n, row) in enumerate(zip(lens, rows)):
            ids[b, S - n:] = row          # LEFT padding
            mask[b, S - n:] = 1
        batched = np.asarray(generation.generate(
            params, jnp.asarray(ids), cfg, max_new_tokens=5,
            attention_mask=jnp.asarray(mask)))
        for b, row in enumerate(rows):
            solo = np.asarray(generation.generate(
                params, jnp.asarray(row[None, :], jnp.int32), cfg,
                max_new_tokens=5))
            np.testing.assert_array_equal(batched[b], solo[0])

    @pytest.mark.slow
    def test_sampling_modes_run(self, tiny):
        cfg, params = tiny
        ids = jnp.asarray(
            np.random.default_rng(3).integers(0, cfg.vocab_size, (1, 4)),
            jnp.int32)
        for kw in ({"temperature": 1.0}, {"temperature": 0.8, "top_k": 5},
                   {"temperature": 1.0, "top_p": 0.9}):
            out = generation.generate(params, ids, cfg, max_new_tokens=3,
                                      key=jax.random.PRNGKey(7), **kw)
            arr = np.asarray(out)
            assert arr.shape == (1, 3)
            assert (0 <= arr).all() and (arr < cfg.vocab_size).all()

    def test_eos_padding(self, tiny):
        cfg, params = tiny
        ids = jnp.asarray(
            np.random.default_rng(4).integers(0, cfg.vocab_size, (1, 4)),
            jnp.int32)
        base = np.asarray(generation.generate(params, ids, cfg,
                                              max_new_tokens=6))
        eos = int(base[0, 2])  # force an early "eos"
        out = np.asarray(generation.generate(params, ids, cfg,
                                             max_new_tokens=6, eos_id=eos))
        after = np.where(out[0] == eos)[0]
        assert len(after) and (out[0, after[0]:] == eos).all()


class TestMaskedMHA:
    def test_matches_reference_attention(self):
        """Decoding token-by-token via masked_multihead_attention must equal
        full causal attention over the accumulated sequence."""
        from paddle_tpu import kernels
        B, H, M, D = 2, 3, 6, 8
        rng = np.random.default_rng(5)
        steps = [rng.standard_normal((B, 3 * H * D)).astype(np.float32)
                 for _ in range(M)]
        cache = paddle.to_tensor(np.zeros((2, B, H, M, D), np.float32))
        outs = []
        for t, x in enumerate(steps):
            seq = paddle.to_tensor(np.full((B,), t, np.int32))
            out, cache = paddle.incubate.nn.functional.masked_multihead_attention(
                paddle.to_tensor(x), cache, sequence_lengths=seq)
            outs.append(np.asarray(out.numpy()))
        got = np.stack(outs, axis=1)  # (B, M, H*D)
        # reference: full attention over the same q/k/v sequence
        qkv = np.stack(steps, 1).reshape(B, M, 3, H, D)
        q, k, v = (jnp.asarray(qkv[:, :, i].reshape(B, M, H, D))
                   for i in range(3))
        want = kernels.attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(got, np.asarray(want).reshape(B, M, H * D),
                                   rtol=2e-4, atol=2e-4)

    def test_bias_and_rejects_quant(self):
        B, H, M, D = 1, 2, 4, 8
        x = paddle.to_tensor(np.random.randn(B, 3 * H * D).astype(np.float32))
        cache = paddle.to_tensor(np.zeros((2, B, H, M, D), np.float32))
        bias = paddle.to_tensor(np.random.randn(3 * H * D).astype(np.float32))
        out, _ = paddle.incubate.nn.functional.masked_multihead_attention(
            x, cache, bias=bias)
        assert np.isfinite(np.asarray(out.numpy())).all()
        with pytest.raises(NotImplementedError):
            paddle.incubate.nn.functional.masked_multihead_attention(
                x, cache, out_scale=2.0)
        # reference kwargs passed AT their defaults change nothing -> run
        out2, _ = paddle.incubate.nn.functional.masked_multihead_attention(
            x, cache, bias=bias, compute_dtype="default", quant_round_type=1,
            quant_max_bound=127.0, quant_min_bound=-127.0)
        np.testing.assert_array_equal(np.asarray(out2.numpy()),
                                      np.asarray(out.numpy()))
        # a real quant-scale tensor must raise, not silently de-quantize
        with pytest.raises(NotImplementedError, match="qkv_out_scale"):
            paddle.incubate.nn.functional.masked_multihead_attention(
                x, cache, qkv_out_scale=paddle.to_tensor(
                    np.ones(3 * H * D, np.float32)))


class TestServedArtifact:
    def test_jit_saved_decode_step_serves_tokens(self, tiny, tmp_path):
        """AOT serving slice: export a fixed-window next-token function to
        StableHLO via jit.save, reload with jit.load, and drive a greedy
        token loop off the served artifact."""
        cfg, params = tiny
        W = 8  # serving window

        class NextToken(paddle.nn.Layer):
            def forward(self, ids, length):
                logits = llama.forward(params, ids.data if hasattr(ids, "data")
                                       else ids, cfg)
                idx = jnp.clip(length.data if hasattr(length, "data")
                               else length, 1, W) - 1
                last = jnp.take_along_axis(
                    logits, idx.reshape(1, 1, 1).astype(jnp.int32).repeat(
                        logits.shape[0], 0).repeat(1, 1), axis=1)
                return jnp.argmax(last[:, 0], -1).astype(jnp.int32)

        path = str(tmp_path / "servable")
        paddle.jit.save(NextToken(), path, input_spec=[
            paddle.static.InputSpec([1, W], "int32", "ids"),
            paddle.static.InputSpec([1], "int32", "len"),
        ])
        served = paddle.jit.load(path)

        rng = np.random.default_rng(6)
        prompt = rng.integers(0, cfg.vocab_size, (1, 4)).astype(np.int32)
        window = np.zeros((1, W), np.int32)
        window[:, :4] = prompt
        toks = []
        n = 4
        for _ in range(3):
            nxt = served(paddle.to_tensor(window),
                         paddle.to_tensor(np.array([n], np.int32)))
            tok = int(np.asarray(nxt.numpy() if hasattr(nxt, "numpy") else nxt)[0])
            toks.append(tok)
            window[0, n] = tok
            n += 1
        # parity with the in-process greedy chain
        want = np.asarray(generation.generate(
            params, jnp.asarray(prompt), cfg, max_new_tokens=3))[0]
        assert toks == list(want)
