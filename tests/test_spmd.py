"""SPMD tier (Graph Doctor tier 4) tests: mesh-aware sharding
propagation, the static collective cost model, and the verified
shard_constraint rewrite pass.

Seeded-bad snippet per new code (SHARD_RESHARD, mesh-aware
SHARD_REPLICATED with the exact spec, COLLECTIVE_BOUND), propagation
rules (elementwise/dot/scan/pjit), the comm_cost ring formulas, the
rewrite pass's inject + gap-elision + corrupted-rollback behaviors, the
ShardedTrainState exposure, and the graphlint --mesh baseline plumbing.

The whole module opts into the forced multi-device host platform via the
``multidevice`` marker (see conftest) — single-device sessions skip it.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu  # noqa: F401 — x64 on, same dtype world as the library
from paddle_tpu import analysis
from paddle_tpu.analysis import Severity, comm_cost, spmd

pytestmark = pytest.mark.multidevice(4)

OPTS = {"sharding_min_bytes": 1 << 10}


def warnings_of(report, code):
    return [f for f in report.by_code(code)
            if f.severity >= Severity.WARNING]


def _mesh1d(n=2):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


def _sharded(mesh, shape=(8, 64), spec=P("data", None)):
    return jax.device_put(jnp.ones(shape, jnp.float32),
                          NamedSharding(mesh, spec))


# ---------------------------------------------------------------------------
# comm_cost: the ring formulas and tables
# ---------------------------------------------------------------------------


class TestCommCost:
    def test_ring_fractions(self):
        sizes = {"data": 4}
        bw = comm_cost.link_bandwidth("v5e")
        ag = comm_cost.price_collective("all_gather", 1 << 20, ["data"],
                                        sizes)
        assert ag.moved_bytes == int((1 << 20) * 3 / 4)
        ar = comm_cost.price_collective("all_reduce", 1 << 20, ["data"],
                                        sizes)
        assert ar.moved_bytes == 2 * ag.moved_bytes
        a2a = comm_cost.price_collective("all_to_all", 1 << 20, ["data"],
                                         sizes)
        assert a2a.moved_bytes == int((1 << 20) * 3 / 16)
        assert ag.seconds > ag.moved_bytes / bw / 2  # bw term dominates

    def test_multi_axis_uses_product(self):
        c = comm_cost.price_collective(
            "all_gather", 1 << 20, ["data", "model"],
            {"data": 2, "model": 4})
        assert c.axis_size == 8

    def test_scan_weight_multiplies(self):
        a = comm_cost.price_collective("all_reduce", 1 << 10, ["data"],
                                       {"data": 2}, weight=1)
        b = comm_cost.price_collective("all_reduce", 1 << 10, ["data"],
                                       {"data": 2}, weight=7)
        assert b.seconds == pytest.approx(7 * a.seconds)
        assert b.moved_bytes == 7 * a.moved_bytes

    def test_chip_table_substring_match(self):
        assert comm_cost.link_bandwidth("TPU v5 lite") == \
            comm_cost.link_bandwidth("v5e")
        assert comm_cost.link_bandwidth("TPU v5p") > \
            comm_cost.link_bandwidth("v5e")
        # unknown chips price at the documented default, never 0
        assert comm_cost.link_bandwidth("cpu") > 0
        assert comm_cost.chip_peak_flops("TPU v4") == 275e12

    def test_roofline_verdict(self):
        big = [comm_cost.price_collective("all_reduce", 1 << 30, ["data"],
                                          {"data": 2})]
        r = comm_cost.roofline(1e6, big, mesh_size=2, chip="v5e")
        assert r["bound"] == "comm" and r["comm_fraction"] > 0.99
        r = comm_cost.roofline(1e15, [], mesh_size=2, chip="v5e")
        assert r["bound"] == "compute" and r["t_comm_s"] == 0.0


# ---------------------------------------------------------------------------
# propagation rules (the abstract interpreter, via propagate())
# ---------------------------------------------------------------------------


class TestPropagate:
    def test_elementwise_and_views_carry_spec(self):
        mesh = _mesh1d()

        def f(x):
            return jnp.tanh(x * 2.0).T.reshape(64, 8)

        closed = jax.make_jaxpr(f)(jnp.ones((8, 64), jnp.float32))
        res = spmd.propagate(closed, mesh, in_specs=[["data", None]],
                             options=OPTS)
        # the transpose output must carry the axis on dim 1
        rows = {r["path"]: r for r in res.eqn_rows}
        t = next(r for p, r in rows.items() if "transpose" in p)
        assert "'data'" in t["out_specs"][0]

    def test_dot_contraction_goes_partial_and_prices_psum(self):
        mesh = _mesh1d()

        def f(a, b):
            return a @ b                # contract a's dim1 (sharded)

        closed = jax.make_jaxpr(f)(jnp.ones((8, 64), jnp.float32),
                                   jnp.ones((64, 8), jnp.float32))
        res = spmd.propagate(closed, mesh,
                             in_specs=[[None, "data"], ["data", None]],
                             options=OPTS)
        kinds = {c.kind for c in res.collectives}
        assert "all_reduce" in kinds    # the output materializes the psum
        assert res.roofline["n_collectives"] >= 1

    def test_scan_carry_fixpoint_keeps_sharding(self):
        mesh = _mesh1d()

        def f(c):
            def body(carry, _):
                return carry * 2.0, ()
            out, _ = jax.lax.scan(body, c, None, length=5)
            return out

        closed = jax.make_jaxpr(f)(jnp.ones((8, 64), jnp.float32))
        res = spmd.propagate(closed, mesh, in_specs=[["data", None]],
                             options=OPTS)
        scan_row = next(r for r in res.eqn_rows
                        if r["primitive"] == "scan")
        assert "'data'" in scan_row["out_specs"][0]

    def test_pjit_in_shardings_seed_the_interior(self):
        mesh = _mesh1d()
        sh = NamedSharding(mesh, P("data", None))

        @jax.jit
        def f(x):
            return x + 1.0

        jf = jax.jit(f, in_shardings=(sh,))
        closed = jax.make_jaxpr(jf)(jnp.ones((8, 64), jnp.float32))
        res = spmd.propagate(closed, mesh, options=OPTS)
        add_row = next(r for r in res.eqn_rows if "add" in r["path"])
        assert "'data'" in add_row["out_specs"][0]


# ---------------------------------------------------------------------------
# seeded-bad snippets: one per new finding code
# ---------------------------------------------------------------------------


class TestSeededFindings:
    def test_reshard_axis_move_flagged_and_priced(self):
        mesh = _mesh1d()

        @jax.jit
        def bad(x):
            # producer shards dim 0; the constraint moves the axis to
            # dim 1 -> an all-to-all of the whole array
            y = jax.lax.with_sharding_constraint(
                x * 2.0, NamedSharding(mesh, P(None, "data")))
            return y.sum()

        r = analysis.analyze(bad, _sharded(mesh), mesh=mesh, options=OPTS)
        hits = warnings_of(r, "SHARD_RESHARD")
        assert hits, str(r)
        assert hits[0].data["collective"] == "all_to_all"
        assert hits[0].data["bytes"] == 8 * 64 * 4

    def test_mesh_aware_replicated_carries_exact_spec(self):
        mesh = _mesh1d()

        @jax.jit
        def bad(x):
            big = jnp.zeros((64, 64), jnp.float32) + 1.0
            return x.sum() + (big @ big.T).sum()

        r = analysis.analyze(bad, _sharded(mesh), mesh=mesh, options=OPTS)
        hits = warnings_of(r, "SHARD_REPLICATED")
        assert hits
        f = hits[0]
        assert f.data["spec"] == ["data", None]
        assert f.data["axis"] == "data" and f.data["dim"] == 0
        assert f.data["target"].startswith(f.eqn_path)
        assert 64 % 2 == 0              # divisibility is the proof

    def test_indivisible_shape_is_not_accused(self):
        mesh = _mesh1d()

        @jax.jit
        def odd(x):
            big = jnp.zeros((63, 63), jnp.float32) + 1.0  # 2 divides nothing
            return x.sum() + big.sum()

        r = analysis.analyze(odd, _sharded(mesh), mesh=mesh, options=OPTS)
        assert not r.by_code("SHARD_REPLICATED")

    def test_gap_is_priced_all_gather(self):
        mesh = _mesh1d()

        @jax.jit
        def gap(x):
            y = jax.lax.with_sharding_constraint(
                x * 2.0, NamedSharding(mesh, P(None, None)))
            return y.sum()

        r = analysis.analyze(gap, _sharded(mesh), mesh=mesh, options=OPTS)
        hits = warnings_of(r, "SHARD_GAP")
        assert hits and hits[0].data["collective"] == "all_gather"

    def test_collective_bound_warns_when_comm_dominates(self):
        mesh = _mesh1d()

        @jax.jit
        def commy(x):
            y = jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P(None, None)))  # big all-gather
            return y.sum()

        r = analysis.analyze(commy, _sharded(mesh, shape=(256, 1024)),
                             mesh=mesh, options=OPTS)
        bound = r.by_code("COLLECTIVE_BOUND")
        assert bound and bound[0].severity >= Severity.WARNING
        assert bound[0].data["roofline"]["bound"] == "comm"
        assert bound[0].data["collectives"]        # priced, named, listed

    def test_collective_bound_info_when_compute_dominates(self):
        mesh = _mesh1d()

        @jax.jit
        def compute_heavy(x):
            return (x @ x.T @ x).sum()

        r = analysis.analyze(compute_heavy, _sharded(mesh, (256, 256)),
                             mesh=mesh, options=OPTS)
        bound = r.by_code("COLLECTIVE_BOUND")
        assert bound and bound[0].severity == Severity.INFO

    def test_spmd_summary_reports_table(self):
        mesh = _mesh1d()

        @jax.jit
        def f(x):
            return jnp.tanh(x).sum()

        r = analysis.analyze(f, _sharded(mesh), mesh=mesh, options=OPTS)
        s = r.by_code("SPMD_SUMMARY")
        assert s and s[0].data["n_eqns"] >= 2 and s[0].data["rows"]

    def test_inert_without_mesh_and_legacy_optin(self):
        @jax.jit
        def f(x):
            return jnp.zeros((64, 64), jnp.float32).sum() + x.sum()

        r = analysis.analyze(f, jnp.ones((8,)), options=OPTS)
        assert not r.by_code("SHARD_*") and not r.by_code("COLLECTIVE_*")
        # legacy taint walk still reachable behind the option
        mesh = _mesh1d()
        r = analysis.analyze(
            f, _sharded(mesh, (8,), P("data")), mesh=mesh,
            options=dict(OPTS, legacy_sharding_taint=True))
        hits = warnings_of(r, "SHARD_REPLICATED")
        assert hits and any(f_.checker == "sharding" for f_ in hits)


# ---------------------------------------------------------------------------
# the shard_constraint rewrite pass (inject / elide / rollback)
# ---------------------------------------------------------------------------


class TestShardConstraintRewrite:
    def _bad(self, mesh):
        @jax.jit
        def bad(x):
            big = jnp.zeros((64, 64), jnp.float32) + 1.0
            return x.sum() + (big @ big.T).sum()

        return bad

    def test_injects_exact_spec_and_verifies(self):
        mesh = _mesh1d()
        bad = self._bad(mesh)
        x = _sharded(mesh)
        fn, rep = analysis.rewrite(bad, x, passes=["shard_constraint"],
                                   options=OPTS, mesh=mesh)
        (o,) = rep.outcomes
        assert o.status == "applied", o.reason
        assert rep.ok
        acts = [a for a in o.actions if a.code == "SHARD_REPLICATED"]
        assert acts and acts[0].data["spec"] == ["data", None]
        # the injected constraint is in the rewritten jaxpr
        prims = [e.primitive.name for e, _p, _w in
                 analysis.iter_eqns(fn.rewritten_jaxpr)]
        assert "sharding_constraint" in prims
        assert float(fn(x)) == pytest.approx(float(bad(x)))

    def test_elides_replicating_gap(self):
        mesh = _mesh1d()

        @jax.jit
        def gap(x):
            y = jax.lax.with_sharding_constraint(
                x * 2.0, NamedSharding(mesh, P(None, None)))
            return y.sum()

        x = _sharded(mesh)
        fn, rep = analysis.rewrite(gap, x, passes=["shard_constraint"],
                                   options=OPTS, mesh=mesh)
        (o,) = rep.outcomes
        assert o.status == "applied", o.reason
        assert any(a.code == "SHARD_GAP" for a in o.actions)
        assert float(fn(x)) == pytest.approx(float(gap(x)))

    def test_corrupted_injection_rolls_back(self, monkeypatch):
        """A shard_constraint pass whose injected 'constraint' perturbs
        values must be REJECTED by the equivalence gate — the original
        jaxpr survives."""
        rewrite_lib = analysis.rewrite_lib

        mesh = _mesh1d()
        bad = self._bad(mesh)
        x = _sharded(mesh)
        monkeypatch.setattr(
            rewrite_lib.jax.lax, "with_sharding_constraint",
            lambda v, s: v * 1.25)
        fn, rep = analysis.rewrite(bad, x, passes=["shard_constraint"],
                                   options=OPTS, mesh=mesh)
        (o,) = rep.outcomes
        assert o.status == "rolled_back"
        assert not rep.ok
        assert bool(jnp.allclose(fn(x), bad(x)))

    def test_skips_without_mesh(self):
        mesh = _mesh1d()
        bad = self._bad(mesh)
        _fn, rep = analysis.rewrite(bad, jnp.ones((8, 64), jnp.float32),
                                    passes=["shard_constraint"],
                                    options=OPTS)
        (o,) = rep.outcomes
        assert o.status in ("skipped", "no-op")

    def test_registered_in_default_pass_order(self):
        assert "shard_constraint" in analysis.list_rewrites()
        from paddle_tpu.analysis.rewrite import _DEFAULT_PASSES
        assert "shard_constraint" in _DEFAULT_PASSES
        assert _DEFAULT_PASSES.index("shard_constraint") < \
            _DEFAULT_PASSES.index("donation")


# ---------------------------------------------------------------------------
# fixes: constraint patches carry the exact spec + site target
# ---------------------------------------------------------------------------


class TestFixes:
    def test_replicated_patch_emits_exact_spec(self):
        mesh = _mesh1d()

        @jax.jit
        def bad(x):
            big = jnp.zeros((64, 64), jnp.float32) + 1.0
            return x.sum() + (big @ big.T).sum()

        r = analysis.analyze(bad, _sharded(mesh), mesh=mesh, options=OPTS)
        patches = analysis.fixes.suggest_fixes(r)
        shard = [p for p in patches if p.kind == "SHARD_REPLICATED"]
        assert shard
        assert "P('data', None)" in shard[0].diff
        assert shard[0].target           # dedupe-safe site identity

    def test_distinct_sites_do_not_dedupe_collapse(self):
        mesh = _mesh1d()

        @jax.jit
        def two(x):
            a = jnp.zeros((64, 64), jnp.float32) + 1.0
            b = jnp.ones((128, 64), jnp.float32) * 3.0
            return x.sum() + a.sum() + b.sum()

        r = analysis.analyze(two, _sharded(mesh), mesh=mesh, options=OPTS)
        patches = analysis.fixes.suggest_fixes(r)
        shard = [p for p in patches if p.kind == "SHARD_REPLICATED"]
        assert len(shard) == len({p.patch_id for p in shard})
        assert len(shard) >= 2

    def test_reshard_patch_names_collective(self):
        mesh = _mesh1d()

        @jax.jit
        def bad(x):
            y = jax.lax.with_sharding_constraint(
                x * 2.0, NamedSharding(mesh, P(None, "data")))
            return y.sum()

        r = analysis.analyze(bad, _sharded(mesh), mesh=mesh, options=OPTS)
        patches = analysis.fixes.suggest_fixes(r)
        resh = [p for p in patches if p.kind == "SHARD_RESHARD"]
        assert resh and "all_to_all" in resh[0].title


# ---------------------------------------------------------------------------
# ShardedTrainState exposure + graphlint --mesh plumbing
# ---------------------------------------------------------------------------


def _load_graphlint():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "graphlint.py")
    spec = importlib.util.spec_from_file_location("graphlint_spmd", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestSurfaces:
    @pytest.mark.multidevice(4)
    def test_sharded_train_state_spmd_report(self, forced_mesh):
        from paddle_tpu.models import llama
        from paddle_tpu.distributed.parallelize import ShardedTrainState
        from paddle_tpu.optimizer.functional import AdamW

        cfg = llama.LlamaConfig.tiny()
        st = ShardedTrainState(cfg, llama, forced_mesh,
                               AdamW(learning_rate=1e-4,
                                     grad_clip_norm=1.0))
        toks = np.random.default_rng(0).integers(0, cfg.vocab_size,
                                                 (4, 9))
        batch = st.shard_batch(llama.lm_batch_from_tokens(
            jnp.asarray(toks, jnp.int32)))
        specs = st.spmd_in_specs(batch)
        assert any(s and "model" in str(s) for s in specs)
        rep = st.spmd_report(batch, checkers=["spmd"])
        assert rep.by_code("SPMD_SUMMARY")
        bound = rep.by_code("COLLECTIVE_BOUND")
        assert bound and bound[0].data["roofline"]["n_collectives"] > 0
        # the shipped sharded step has no reshard boundary
        assert not rep.by_code("SHARD_RESHARD")

    def test_mesh_spec_parsing_aliases(self):
        gl = _load_graphlint()
        assert gl._parse_mesh("dp=2,tp=4") == {"data": 2, "model": 4}
        assert gl._parse_mesh("data=2,ep=2") == {"data": 2, "expert": 2}
        with pytest.raises(SystemExit):
            gl._parse_mesh("bogus=2")

    def test_baseline_diff_catches_reshard_regression(self):
        gl = _load_graphlint()
        base = {"schema_version": 3, "targets": {
            "llama": {"codes": {"COLLECTIVE_BOUND": "warning"},
                      "spmd": {"reshard_count": 0, "bound": "comm"}}}}
        cur = {"llama": {"codes": {"COLLECTIVE_BOUND": "warning"},
                         "spmd": {"reshard_count": 2, "bound": "comm"}}}
        news = gl._baseline_diff(cur, base)
        assert any("SHARD_RESHARD count grew" in n for n in news)
        # a NEW code fails too (the seeded-resharding-bug CI path)
        cur2 = {"llama": {"codes": {"COLLECTIVE_BOUND": "warning",
                                    "SHARD_RESHARD": "warning"},
                          "spmd": {"reshard_count": 0}}}
        assert any("SHARD_RESHARD" in n
                   for n in gl._baseline_diff(cur2, base))

    def test_seeded_resharding_bug_fails_baseline_gate(self, capsys,
                                                       tmp_path):
        """Acceptance: a seeded resharding bug is caught by
        SHARD_RESHARD and fails the baseline gate — wire a corrupted
        'train target' through the real graphlint diff path."""
        gl = _load_graphlint()
        mesh = _mesh1d()

        def target_bad():
            @jax.jit
            def bad(x):
                y = jax.lax.with_sharding_constraint(
                    x * 2.0, NamedSharding(mesh, P(None, "data")))
                return y.sum()

            return bad, (_sharded(mesh),), {"mesh": mesh,
                                            "options": dict(OPTS)}

        old = dict(gl.TARGETS)
        gl.TARGETS.clear()
        gl.TARGETS["bad"] = target_bad
        try:
            baseline = tmp_path / "b.json"
            baseline.write_text(json.dumps({
                "schema_version": 3,
                "targets": {"bad": {
                    "codes": {"COLLECTIVE_BOUND": "warning",
                              "SPMD_SUMMARY": "info",
                              "COST_SUMMARY": "info",
                              "COST_HOTSPOT": "info",
                              "MEM_PEAK": "info"},
                    "spmd": {"reshard_count": 0, "bound": "comm"}}}}))
            rc = gl.main(["--baseline", str(baseline), "--no-hlo",
                          "--json"])
            out = json.loads(
                capsys.readouterr().out.strip().splitlines()[-1])
            assert rc == 1
            assert any("SHARD_RESHARD" in n
                       for n in out["new_vs_baseline"])
        finally:
            gl.TARGETS.clear()
            gl.TARGETS.update(old)
