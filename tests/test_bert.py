"""BERT encoder family (PaddleNLP-BERT analog over nn.TransformerEncoder,
reference python/paddle/nn/layer/transformer.py:443)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import bert
from paddle_tpu.models.bert import BertConfig


@pytest.fixture(scope="module")
def tiny():
    cfg = BertConfig.tiny()
    return cfg, bert.init_params(cfg, jax.random.PRNGKey(0))


class TestForward:
    def test_shapes_and_pooler(self, tiny):
        cfg, params = tiny
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (3, 16)), jnp.int32)
        seq, pooled = jax.jit(
            lambda p, i: bert.forward(p, i, cfg))(params, ids)
        assert seq.shape == (3, 16, cfg.hidden_size)
        assert pooled.shape == (3, cfg.hidden_size)
        assert np.all(np.abs(np.asarray(pooled)) <= 1.0)  # tanh pooler

    def test_padding_mask_isolates_pad_tokens(self, tiny):
        """Changing tokens under the padding mask must not change unpadded
        outputs (bidirectional attention respects the key mask)."""
        cfg, params = tiny
        rng = np.random.default_rng(1)
        ids = rng.integers(0, cfg.vocab_size, (2, 12))
        mask = np.ones((2, 12), np.int32)
        mask[:, 8:] = 0
        ids2 = ids.copy()
        ids2[:, 8:] = rng.integers(0, cfg.vocab_size, (2, 4))  # perturb pads
        f = jax.jit(lambda p, i, m: bert.forward(p, i, cfg,
                                                 attention_mask=m)[0])
        a = np.asarray(f(params, jnp.asarray(ids, jnp.int32),
                         jnp.asarray(mask)))
        b = np.asarray(f(params, jnp.asarray(ids2, jnp.int32),
                         jnp.asarray(mask)))
        np.testing.assert_allclose(a[:, :8], b[:, :8], atol=1e-5)
        assert not np.allclose(a[:, 8:], b[:, 8:])  # pads themselves differ

    def test_bidirectional_not_causal(self, tiny):
        """Perturbing a LATER token must change EARLIER outputs."""
        cfg, params = tiny
        rng = np.random.default_rng(2)
        ids = rng.integers(0, cfg.vocab_size, (1, 10))
        ids2 = ids.copy()
        ids2[0, 9] = (ids2[0, 9] + 1) % cfg.vocab_size
        f = jax.jit(lambda p, i: bert.forward(p, i, cfg)[0])
        a = np.asarray(f(params, jnp.asarray(ids, jnp.int32)))
        b = np.asarray(f(params, jnp.asarray(ids2, jnp.int32)))
        assert not np.allclose(a[0, 0], b[0, 0])

    def test_token_types_change_output(self, tiny):
        cfg, params = tiny
        ids = jnp.asarray(np.random.default_rng(3).integers(
            0, cfg.vocab_size, (1, 8)), jnp.int32)
        tt = jnp.asarray(np.array([[0, 0, 0, 0, 1, 1, 1, 1]]), jnp.int32)
        a, _ = bert.forward(params, ids, cfg)
        b, _ = bert.forward(params, ids, cfg, token_type_ids=tt)
        assert not np.allclose(np.asarray(a), np.asarray(b))


class TestTraining:
    def test_mlm_nsp_loss_decreases(self, tiny):
        cfg, params = tiny
        rng = np.random.default_rng(4)
        B, S = 4, 16
        ids = rng.integers(0, cfg.vocab_size, (B, S))
        labels = np.full((B, S), -100)
        mask_pos = rng.random((B, S)) < 0.3
        labels[mask_pos] = ids[mask_pos]
        masked = ids.copy()
        masked[mask_pos] = 3  # [MASK]
        batch = {
            "input_ids": jnp.asarray(masked, jnp.int32),
            "labels": jnp.asarray(labels, jnp.int32),
            "next_sentence_label": jnp.asarray(rng.integers(0, 2, B),
                                               jnp.int32),
        }

        @jax.jit
        def step(p):
            loss, g = jax.value_and_grad(
                lambda p_: bert.mlm_loss_fn(p_, batch, cfg))(p)
            return loss, jax.tree_util.tree_map(
                lambda w, gw: w - 0.05 * gw.astype(w.dtype), p, g)

        losses = []
        for _ in range(15):
            loss, params = step(params)
            losses.append(float(loss))
        assert losses[-1] < 0.7 * losses[0], (losses[0], losses[-1])

    def test_unmasked_positions_do_not_contribute(self, tiny):
        cfg, params = tiny
        ids = jnp.asarray(np.random.default_rng(5).integers(
            0, cfg.vocab_size, (2, 8)), jnp.int32)
        all_ignored = {"input_ids": ids,
                       "labels": jnp.full((2, 8), -100, jnp.int32)}
        assert float(bert.mlm_loss_fn(params, all_ignored, cfg)) == 0.0


class TestShardedBert:
    def test_train_step_on_hybrid_mesh(self):
        """The parallelize stack is model-agnostic: BERT trains on a
        data x sharding x model mesh with ZeRO-3 param sharding."""
        from paddle_tpu.distributed import mesh as mesh_lib
        from paddle_tpu.distributed.parallelize import ShardedTrainState
        from paddle_tpu.optimizer.functional import AdamW

        cfg = BertConfig.tiny()
        mesh = mesh_lib.make_mesh(data=2, sharding=2, model=2)
        st = ShardedTrainState(cfg, bert, mesh,
                               AdamW(learning_rate=1e-3, grad_clip_norm=1.0),
                               zero_stage=3)
        params, opt = st.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        ids = rng.integers(4, cfg.vocab_size, (8, 16))
        labels = np.full((8, 16), -100)
        mask_pos = rng.random((8, 16)) < 0.3
        labels[mask_pos] = ids[mask_pos]
        batch = st.shard_batch({
            "input_ids": jnp.asarray(ids, jnp.int32),
            "labels": jnp.asarray(labels, jnp.int32)})
        losses = []
        for _ in range(5):
            params, opt, m = st.step(params, opt, batch)
            losses.append(float(m["loss"]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses
        # ZeRO-3: stored params genuinely sharded over the zero axis
        sharded = [s for s in jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda x: x.sharding, params))
            if "sharding" in str(s.spec)]
        assert sharded, "no parameter carries the zero-axis sharding"
        # the batch sharding is a pytree PREFIX: a richer batch (mask,
        # token types, NSP labels) goes through the same jitted step
        full = st.shard_batch({
            "input_ids": jnp.asarray(ids, jnp.int32),
            "labels": jnp.asarray(labels, jnp.int32),
            "attention_mask": jnp.ones((8, 16), jnp.int32),
            "token_type_ids": jnp.zeros((8, 16), jnp.int32),
            "next_sentence_label": jnp.asarray(
                rng.integers(0, 2, 8), jnp.int32)})
        params, opt, m = st.step(params, opt, full)
        assert np.isfinite(float(m["loss"]))
        # shard_batch accepts paddle Tensor leaves too (unwraps raw arrays)
        import paddle_tpu as paddle
        tb = st.shard_batch({"input_ids": paddle.to_tensor(ids),
                             "labels": paddle.to_tensor(labels)})
        params, opt, m = st.step(params, opt, tb)
        assert np.isfinite(float(m["loss"]))

    def test_fully_padded_row_keeps_grads_finite(self):
        """An all-zero attention_mask row must not poison gradients with
        NaN (softmax over a row of -inf)."""
        cfg = BertConfig.tiny()
        params = bert.init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        ids = rng.integers(4, cfg.vocab_size, (2, 8))
        labels = np.full((2, 8), -100)
        labels[0, 1] = ids[0, 1]
        mask = np.ones((2, 8), np.int32)
        mask[1, :] = 0  # ragged last batch: one row entirely padding
        batch = {"input_ids": jnp.asarray(ids, jnp.int32),
                 "labels": jnp.asarray(labels, jnp.int32),
                 "attention_mask": jnp.asarray(mask)}
        loss, grads = jax.value_and_grad(
            lambda p: bert.mlm_loss_fn(p, batch, cfg))(params)
        assert np.isfinite(float(loss))
        for g in jax.tree_util.tree_leaves(grads):
            assert np.isfinite(np.asarray(g)).all()
        # NSP: a fully-padded row is excluded from the sentence mean —
        # its (garbage) pooled output must not shift the loss
        batch_nsp = dict(batch, next_sentence_label=jnp.asarray([1, 0],
                                                                jnp.int32))
        with_pad = float(bert.mlm_loss_fn(params, batch_nsp, cfg))
        solo = {k: v[:1] for k, v in batch_nsp.items()}
        only_real = float(bert.mlm_loss_fn(params, solo, cfg))
        np.testing.assert_allclose(with_pad, only_real, rtol=1e-5)


def test_num_params_and_configs():
    assert bert.num_params(BertConfig.tiny()) > 0
    base = bert.num_params(BertConfig.base())
    # BERT-base is ~110M params — sanity-check the architecture arithmetic
    assert 100e6 < base < 120e6, base
