"""Dropless MoE grouped matmul (kernels/pallas_grouped_matmul.py): kernel
exactness through the Pallas interpreter, custom_vjp gradcheck against the
dense reference, and token-exactness of the "gmm" dispatch mode vs the
einsum mode under no-drop routing."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.distributed import moe as moe_lib
from paddle_tpu.kernels import pallas_grouped_matmul as pg


def _rand_problem(seed=0, X=5, K=16, N=24, sizes=(7, 0, 13, 3, 9)):
    rng = np.random.default_rng(seed)
    gs = jnp.asarray(sizes, jnp.int32)
    M = int(gs.sum())
    lhs = jnp.asarray(rng.normal(size=(M, K)), jnp.float32)
    rhs = jnp.asarray(rng.normal(size=(X, K, N)), jnp.float32)
    return lhs, rhs, gs


class TestGmmKernel:
    @pytest.mark.parametrize("impl", ["interpret", "dense"])
    def test_forward_matches_reference(self, impl):
        lhs, rhs, gs = _rand_problem()
        ref = pg.grouped_matmul_reference(lhs, rhs, gs)
        out = pg.grouped_matmul(lhs, rhs, gs, tile_m=8, impl=impl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)

    def test_forward_jit_and_uneven_tiles(self):
        # group sizes hitting every tile case: exact multiple, sub-tile,
        # empty, and a tile_m+1 straddle-forcing size
        lhs, rhs, gs = _rand_problem(seed=1, sizes=(8, 1, 0, 9, 14))
        ref = pg.grouped_matmul_reference(lhs, rhs, gs)
        f = jax.jit(lambda a, b: pg.grouped_matmul(a, b, gs, tile_m=8,
                                                   impl="interpret"))
        np.testing.assert_allclose(np.asarray(f(lhs, rhs)), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)

    def test_single_group_is_plain_matmul(self):
        rng = np.random.default_rng(2)
        lhs = jnp.asarray(rng.normal(size=(24, 8)), jnp.float32)
        rhs = jnp.asarray(rng.normal(size=(1, 8, 16)), jnp.float32)
        gs = jnp.asarray([24], jnp.int32)
        out = pg.grouped_matmul(lhs, rhs, gs, tile_m=8, impl="interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(lhs @ rhs[0]),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("impl", ["interpret", "dense"])
    def test_custom_vjp_gradcheck(self, impl):
        """jax.grad through the kernel == jax.grad through the dense
        reference (dgrad GMM + per-group transposed-GMM wgrad)."""
        lhs, rhs, gs = _rand_problem(seed=3)

        def loss_kernel(l, r):
            o = pg.grouped_matmul(l, r, gs, tile_m=8, impl=impl)
            return (o * jnp.cos(o)).sum()

        def loss_ref(l, r):
            o = pg.grouped_matmul_reference(l, r, gs)
            return (o * jnp.cos(o)).sum()

        gl, gr = jax.grad(loss_kernel, argnums=(0, 1))(lhs, rhs)
        gl_r, gr_r = jax.grad(loss_ref, argnums=(0, 1))(lhs, rhs)
        np.testing.assert_allclose(np.asarray(gl), np.asarray(gl_r),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gr_r),
                                   rtol=1e-4, atol=1e-5)
        # empty group (index 1) owns no rows -> exactly zero weight grad
        assert float(jnp.abs(gr[1]).max()) == 0.0

    def test_layout_covers_rows_and_marks_dead_tiles(self):
        gs = jnp.asarray([7, 0, 13], jnp.int32)
        lay = pg.make_layout(gs, 20, tile_m=8)
        starts = np.asarray(lay.starts)
        assert lay.padded_rows % lay.tile_m == 0
        np.testing.assert_array_equal(starts, [0, 8, 8])  # aligned starts
        gids = np.asarray(lay.tile_gids)
        live = np.asarray(lay.tile_live)
        # tiles: rows 0-7 -> g0, 8-15 -> g2, 16-23 -> g2 (rows 16-20 live),
        # then trailing dead tiles
        assert gids[0] == 0 and live[0] == 1
        assert gids[1] == 2 and live[1] == 1
        assert gids[2] == 2 and live[2] == 1
        assert live[3:].sum() == 0


class TestGmmDispatch:
    """Token-exactness of dispatch_mode="gmm" vs the einsum mode on CPU
    (interpret mode) under no-drop routing, plus grads and the auto rule."""

    def _setup(self, top_k, N=48, X=4, E=16, F=32, seed=0):
        cfg = moe_lib.MoEConfig(num_experts=X, top_k=top_k,
                                capacity_factor=None)
        key = jax.random.PRNGKey(seed)
        p = moe_lib.init_moe_ffn_params(key, E, F, cfg, dtype=jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(seed + 1), (2, N // 2, E),
                              jnp.float32)
        return cfg, p, x

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_token_exact_vs_einsum(self, top_k, monkeypatch):
        monkeypatch.setattr(pg, "_FORCE_IMPL", "interpret")
        cfg, p, x = self._setup(top_k)
        oe, ae = moe_lib.moe_ffn(x, p, cfg, dispatch="einsum")
        og, ag = moe_lib.moe_ffn(x, p, cfg, dispatch="gmm")
        np.testing.assert_allclose(np.asarray(oe), np.asarray(og),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(ae), float(ag), rtol=1e-6)

    def test_dropless_under_extreme_imbalance(self, monkeypatch):
        """All tokens to one expert: capacity modes drop, gmm keeps all."""
        monkeypatch.setattr(pg, "_FORCE_IMPL", "interpret")
        X, E, F = 4, 16, 32
        tight = moe_lib.MoEConfig(num_experts=X, top_k=1,
                                  capacity_factor=0.5, min_capacity=1,
                                  aux_loss_weight=0.0, z_loss_weight=0.0)
        p = moe_lib.init_moe_ffn_params(jax.random.PRNGKey(0), E, F, tight,
                                        dtype=jnp.float32)
        # router biased so every token picks expert 0
        p = dict(p, router=p["router"] * 0.0
                 + jnp.eye(E, X) * 0.0 + jnp.array([[9.0, 0, 0, 0]] * E))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, E), jnp.float32)
        _, _, m_sc = moe_lib.moe_ffn(x, p, tight, dispatch="scatter",
                                     return_metrics=True)
        og, _, m_gm = moe_lib.moe_ffn(x, p, tight, dispatch="gmm",
                                      return_metrics=True)
        assert float(m_sc["dropped_fraction"]) > 0.4
        assert float(m_gm["dropped_fraction"]) == 0.0
        # gmm output == gate-weighted dense per-token reference
        tok = x.reshape(-1, E)
        probs = jax.nn.softmax(tok @ p["router"], axis=-1)
        ref = np.zeros_like(np.asarray(tok))
        for t in range(tok.shape[0]):
            e = int(jnp.argmax(probs[t]))
            h = (jax.nn.silu(tok[t] @ p["w_gate"][e])
                 * (tok[t] @ p["w_up"][e])) @ p["w_down"][e]
            ref[t] = float(probs[t, e]) * np.asarray(h)
        np.testing.assert_allclose(np.asarray(og.reshape(-1, E)), ref,
                                   rtol=1e-4, atol=1e-5)

    def test_grad_parity_vs_einsum(self, monkeypatch):
        monkeypatch.setattr(pg, "_FORCE_IMPL", "interpret")
        cfg, p, x = self._setup(top_k=2, seed=4)

        def loss(q, mode):
            o, aux = moe_lib.moe_ffn(x, q, cfg, dispatch=mode)
            return (o * o).mean() + aux

        ge = jax.grad(lambda q: loss(q, "einsum"))(p)
        gg = jax.grad(lambda q: loss(q, "gmm"))(p)
        for k in p:
            np.testing.assert_allclose(np.asarray(ge[k]), np.asarray(gg[k]),
                                       rtol=2e-4, atol=2e-5, err_msg=k)

    def test_auto_mode_picks_gmm_when_dropless(self, monkeypatch):
        cfg, p, x = self._setup(top_k=2)
        calls = []
        orig = moe_lib._gmm_expert_ffn
        monkeypatch.setattr(moe_lib, "_gmm_expert_ffn",
                            lambda *a, **k: calls.append(1) or orig(*a, **k))
        out, _ = moe_lib.moe_ffn(x, p, cfg)          # dispatch=None (auto)
        assert calls, "capacity_factor=None should auto-route to gmm"
        assert out.shape == x.shape

    def test_compute_capacity_clamped_to_tokens(self):
        # huge capacity_factor: C caps at N (a token fills at most one
        # slot per expert), so the einsum path can't exceed (N, X, N)
        cfg = moe_lib.MoEConfig(num_experts=4, top_k=2, capacity_factor=64.0)
        assert moe_lib.compute_capacity(32, cfg) == 32
        cfg_none = moe_lib.MoEConfig(num_experts=4, top_k=2,
                                     capacity_factor=None)
        assert moe_lib.compute_capacity(32, cfg_none) == 32

    def test_moe_llama_gmm_forward_parity(self, monkeypatch):
        from paddle_tpu.models import moe_llama
        monkeypatch.setattr(pg, "_FORCE_IMPL", "interpret")
        cfg_e = dataclasses.replace(moe_llama.MoELlamaConfig.tiny(),
                                    capacity_factor=None,
                                    moe_dispatch="einsum")
        cfg_g = dataclasses.replace(cfg_e, moe_dispatch="gmm")
        params = moe_llama.init_params(cfg_e, seed=3)
        ids = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 16)),
                          jnp.int32)
        le = moe_llama.forward(params, ids, cfg_e)
        lg = moe_llama.forward(params, ids, cfg_g)
        np.testing.assert_allclose(np.asarray(le), np.asarray(lg),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.slow
    def test_gmm_train_step_reduces_loss(self):
        """End-to-end: dropless MoE-Llama trains on the sharded state."""
        from paddle_tpu.distributed import mesh as mesh_lib
        from paddle_tpu.distributed.parallelize import ShardedTrainState
        from paddle_tpu.models import moe_llama
        from paddle_tpu.optimizer.functional import AdamW

        cfg = dataclasses.replace(moe_llama.MoELlamaConfig.tiny(),
                                  capacity_factor=None, moe_dispatch="gmm")
        mesh = mesh_lib.make_mesh(data=2, extra_axes={"expert": 4})
        state = ShardedTrainState(cfg, moe_llama, mesh,
                                  optimizer=AdamW(learning_rate=5e-3),
                                  zero_stage=1)
        params, opt_state = state.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, cfg.vocab_size, (4, 17))
        batch = state.shard_batch(
            {"input_ids": jnp.asarray(tokens[:, :-1], jnp.int32),
             "labels": jnp.asarray(tokens[:, 1:], jnp.int32)})
        losses = []
        for _ in range(10):
            params, opt_state, metrics = state.step(params, opt_state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]


class TestRoutingMetrics:
    def test_top_k_gating_returns_metrics(self):
        cfg = moe_lib.MoEConfig(num_experts=2, top_k=1, capacity_factor=1.0,
                                min_capacity=1, aux_loss_weight=0.0,
                                z_loss_weight=0.0)
        logits = jnp.tile(jnp.array([[5.0, -5.0]]), (8, 1))
        dispatch, _, _, m = moe_lib.top_k_gating(logits, cfg,
                                                 return_metrics=True)
        assert float(m["dropped_fraction"]) == 0.5  # capacity 4 of 8
        assert float(m["dropped_count"]) == 4.0
        assert int(dispatch.sum()) == 4

    def test_routing_stats_full_model(self):
        from paddle_tpu.models import moe_llama
        cfg = dataclasses.replace(moe_llama.MoELlamaConfig.tiny(),
                                  capacity_factor=0.5,
                                  moe_dispatch="scatter")
        params = moe_llama.init_params(cfg, seed=0)
        ids = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 16)),
                          jnp.int32)
        st = moe_llama.routing_stats(params, ids, cfg)
        assert 0.0 < float(st["dropped_fraction"]) < 1.0
        assert np.isfinite(float(st["aux_loss"]))
        # gmm dispatch is dropless by construction
        st_g = moe_llama.routing_stats(
            params, ids, dataclasses.replace(cfg, capacity_factor=None,
                                             moe_dispatch="gmm"))
        assert float(st_g["dropped_fraction"]) == 0.0

    def test_eager_moe_layer_reports_drops(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        experts = [nn.Linear(16, 16) for _ in range(2)]
        layer = moe_lib.MoELayer(
            16, experts, gate=moe_lib.MoEConfig(
                num_experts=2, top_k=1, capacity_factor=1.0, min_capacity=1))
        x = paddle.to_tensor(np.random.randn(2, 8, 16).astype(np.float32))
        layer(x)
        assert 0.0 <= float(layer.last_dropped_fraction) <= 1.0
