"""Domain-library data paths (VERDICT r3 missing #6/#7): geometric
sampling/reindex, text datasets, audio wave backend."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import geometric
from paddle_tpu.text import datasets as tds


class TestSampleNeighbors:
    def _csc(self):
        # graph: 0 <- {1,2,3}; 1 <- {0}; 2 <- {}; 3 <- {0,1,2}
        colptr = np.asarray([0, 3, 4, 4, 7], np.int64)
        row = np.asarray([1, 2, 3, 0, 0, 1, 2], np.int64)
        return row, colptr

    def test_all_neighbors(self):
        row, colptr = self._csc()
        nbr, cnt = geometric.sample_neighbors(
            paddle.to_tensor(row), paddle.to_tensor(colptr),
            paddle.to_tensor(np.asarray([0, 2, 3], np.int64)))
        np.testing.assert_array_equal(cnt.numpy(), [3, 0, 3])
        np.testing.assert_array_equal(nbr.numpy(), [1, 2, 3, 0, 1, 2])

    def test_sample_size_caps_and_subsets(self):
        row, colptr = self._csc()
        paddle.seed(0)
        nbr, cnt = geometric.sample_neighbors(
            paddle.to_tensor(row), paddle.to_tensor(colptr),
            paddle.to_tensor(np.asarray([0, 3], np.int64)), sample_size=2)
        np.testing.assert_array_equal(cnt.numpy(), [2, 2])
        got = nbr.numpy()
        assert set(got[:2]).issubset({1, 2, 3})
        assert set(got[2:]).issubset({0, 1, 2})
        assert len(set(got[:2])) == 2  # no replacement

    def test_return_eids(self):
        row, colptr = self._csc()
        eids = np.arange(100, 107, dtype=np.int64)
        nbr, cnt, oe = geometric.sample_neighbors(
            paddle.to_tensor(row), paddle.to_tensor(colptr),
            paddle.to_tensor(np.asarray([1], np.int64)),
            eids=paddle.to_tensor(eids), return_eids=True)
        np.testing.assert_array_equal(oe.numpy(), [103])
        with pytest.raises(ValueError, match="eids"):
            geometric.sample_neighbors(
                paddle.to_tensor(row), paddle.to_tensor(colptr),
                paddle.to_tensor(np.asarray([1], np.int64)),
                return_eids=True)


class TestReindexGraph:
    def test_reference_docstring_example(self):
        """The exact example from geometric/reindex.py:37."""
        x = paddle.to_tensor(np.asarray([0, 1, 2], np.int64))
        neighbors = paddle.to_tensor(
            np.asarray([8, 9, 0, 4, 7, 6, 7], np.int64))
        count = paddle.to_tensor(np.asarray([2, 3, 2], np.int32))
        src, dst, nodes = geometric.reindex_graph(x, neighbors, count)
        np.testing.assert_array_equal(src.numpy(), [3, 4, 0, 5, 6, 7, 6])
        np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 1, 1, 2, 2])
        np.testing.assert_array_equal(nodes.numpy(), [0, 1, 2, 8, 9, 4, 7, 6])

    def test_count_mismatch_raises(self):
        with pytest.raises(ValueError, match="count"):
            geometric.reindex_graph(
                paddle.to_tensor(np.asarray([0], np.int64)),
                paddle.to_tensor(np.asarray([1, 2], np.int64)),
                paddle.to_tensor(np.asarray([1], np.int32)))

    def test_composes_with_sample_neighbors(self):
        colptr = np.asarray([0, 3, 4, 4, 7], np.int64)
        row = np.asarray([1, 2, 3, 0, 0, 1, 2], np.int64)
        x = paddle.to_tensor(np.asarray([0, 3], np.int64))
        nbr, cnt = geometric.sample_neighbors(
            paddle.to_tensor(row), paddle.to_tensor(colptr), x)
        src, dst, nodes = geometric.reindex_graph(x, nbr, cnt)
        # every reindexed edge endpoint resolves back to the original id
        nn = nodes.numpy()
        np.testing.assert_array_equal(nn[src.numpy()], nbr.numpy())
        assert dst.numpy().max() < 2


class TestTextDatasets:
    def test_imdb_synthetic(self):
        ds = tds.Imdb(mode="train")
        assert len(ds) == 200
        ids, label = ds[0]
        assert ids.dtype == np.int64 and label in (0, 1)
        assert "<unk>" in ds.word_idx

    def test_imdb_from_directory(self, tmp_path):
        for sub, texts in (("pos", ["great movie", "superb acting"]),
                           ("neg", ["awful mess", "boring plot"])):
            d = tmp_path / sub
            d.mkdir()
            for i, t in enumerate(texts):
                (d / f"{i}.txt").write_text(t)
        ds = tds.Imdb(data_file=str(tmp_path))
        assert len(ds) == 4
        labels = sorted(int(ds[i][1]) for i in range(4))
        assert labels == [0, 0, 1, 1]

    def test_conll05_shapes(self):
        ds = tds.Conll05st()
        item = ds[0]
        assert len(item) == 9  # ids, pred, 5 ctx, mark, labels
        n = len(item[0])
        assert all(len(a) == n for a in item)
        assert item[7].sum() == 1  # exactly one predicate mark
        assert len(ds.label_dict) >= 2

    def test_imikolov_ngram_and_seq(self):
        ng = tds.Imikolov(window_size=3, data_type="NGRAM")
        assert all(len(it) == 3 for it in [ng[0], ng[1]])
        sq = tds.Imikolov(data_type="SEQ")
        src, trg = sq[0]
        np.testing.assert_array_equal(src[1:], trg[:-1])

    def test_uci_housing_splits_and_normalization(self):
        tr = tds.UciHousing(mode="train")
        te = tds.UciHousing(mode="test")
        assert len(tr) == 404 and len(te) == 102
        x, y = tr[0]
        assert x.shape == (13,) and y.shape == (1,)
        # features normalized to ~[-1, 1]
        allx = np.stack([tr[i][0] for i in range(len(tr))])
        assert np.abs(allx).max() <= 1.0 + 1e-6

    def test_dataloader_integration(self):
        ds = tds.UciHousing(mode="train")
        loader = paddle.io.DataLoader(ds, batch_size=32, shuffle=False)
        xb, yb = next(iter(loader))
        assert tuple(xb.shape) == (32, 13) and tuple(yb.shape) == (32, 1)


class TestAudioBackends:
    def test_save_load_roundtrip(self, tmp_path):
        sr = 16000
        t = np.linspace(0, 1, sr // 10).astype(np.float32)
        wav = 0.5 * np.sin(2 * np.pi * 440 * t)[None, :]  # (1, T)
        path = str(tmp_path / "tone.wav")
        paddle.audio.save(path, paddle.to_tensor(wav), sr)
        back, sr2 = paddle.audio.load(path)
        assert sr2 == sr
        np.testing.assert_allclose(back.numpy(), wav, atol=2e-4)

    def test_info(self, tmp_path):
        sr = 8000
        wav = np.zeros((2, 800), np.float32)  # stereo
        path = str(tmp_path / "s.wav")
        paddle.audio.save(path, paddle.to_tensor(wav), sr)
        meta = paddle.audio.info(path)
        assert meta.sample_rate == sr
        assert meta.num_channels == 2
        assert meta.num_samples == 800
        assert meta.bits_per_sample == 16

    def test_frame_offset_and_num_frames(self, tmp_path):
        sr = 8000
        wav = np.arange(100, dtype=np.float32)[None, :] / 200.0
        path = str(tmp_path / "o.wav")
        paddle.audio.save(path, paddle.to_tensor(wav), sr)
        seg, _ = paddle.audio.load(path, frame_offset=10, num_frames=20)
        assert tuple(seg.shape) == (1, 20)
        np.testing.assert_allclose(seg.numpy(), wav[:, 10:30], atol=2e-4)

    def test_unnormalized_int16(self, tmp_path):
        sr = 8000
        wav = np.full((1, 10), 0.25, np.float32)
        path = str(tmp_path / "i.wav")
        paddle.audio.save(path, paddle.to_tensor(wav), sr)
        raw, _ = paddle.audio.load(path, normalize=False)
        assert np.abs(raw.numpy() - 0.25 * (2 ** 15 - 1)).max() <= 1.0

    def test_backend_registry(self):
        from paddle_tpu.audio import backends as B
        assert "wave_backend" in B.list_available_backends()
        assert B.get_current_backend() == "wave_backend"
        with pytest.raises(NotImplementedError, match="not registered"):
            B.set_backend("soundfile")

    def test_non_wav_rejected(self, tmp_path):
        path = tmp_path / "fake.wav"
        path.write_bytes(b"not a wav file at all")
        with pytest.raises(NotImplementedError, match="PCM16"):
            paddle.audio.load(str(path))


class TestWmtMovielens:
    def test_wmt14_triplets(self):
        from paddle_tpu.text.datasets import WMT14
        ds = WMT14(mode="train", dict_size=20)
        src, trg, trg_next = ds[0]
        assert src.dtype == np.int64
        assert trg[0] == 0                      # <s>
        assert trg_next[-1] == 1                # <e>
        np.testing.assert_array_equal(trg[1:], trg_next[:-1])
        sd, td = ds.get_dict()
        assert sd["<unk>"] == 2
        rid, _ = ds.get_dict(reverse=True)
        assert rid[2] == "<unk>"
        assert len(sd) <= 20

    def test_wmt14_file_based(self, tmp_path):
        from paddle_tpu.text.datasets import WMT14
        f = tmp_path / "pairs.txt"
        f.write_text("hello world\thallo welt\nbye now\ttschuess jetzt\n")
        ds = WMT14(data_file=str(f), dict_size=50)
        assert len(ds) == 2
        sd, td = ds.get_dict()
        assert "hello" in sd and "hallo" in td

    def test_wmt16_separate_dicts(self):
        from paddle_tpu.text.datasets import WMT16
        ds = WMT16(mode="val", src_dict_size=15, trg_dict_size=18)
        assert len(ds.src_dict) <= 15 and len(ds.trg_dict) <= 18
        d = ds.get_dict("en")
        assert d is ds.src_dict

    def test_movielens_items(self):
        from paddle_tpu.text.datasets import Movielens
        tr = Movielens(mode="train")
        te = Movielens(mode="test")
        assert len(tr) > 0 and len(te) > 0
        item = tr[0]
        assert len(item) == 8
        uid, gender, age, job, mid, cats, title, rating = item
        assert gender[0] in (0, 1)
        assert 0 <= age[0] < 7
        assert 1.0 <= rating[0] <= 5.0
        assert cats.dtype == np.int64 and len(cats) >= 1

    def test_movielens_file_based(self, tmp_path):
        from paddle_tpu.text.datasets import Movielens
        (tmp_path / "users.dat").write_text("1::M::25::4\n2::F::35::7\n")
        (tmp_path / "movies.dat").write_text(
            "1::Toy Story::Animation|Comedy\n2::Heat::Action\n")
        (tmp_path / "ratings.dat").write_text(
            "1::1::5::978300760\n2::2::3::978302109\n1::2::4::978301968\n")
        ds = Movielens(data_file=str(tmp_path), mode="train", test_ratio=0.0)
        assert len(ds) == 3
        assert ds.categories_dict["Animation"] >= 0
