"""Distributed checkpoint: shard-by-shard save + reshard-on-load.

Reference analog: distributed/auto_parallel/static/converter.py (reshard a
checkpoint onto a different parallel layout) + dist_saver.py.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.distributed import checkpoint as ckpt, mesh as mesh_lib


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    mesh_lib.set_global_mesh(None)


class TestCheckpointCore:
    def test_roundtrip_resharded(self, tmp_path):
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh8 = mesh_lib.make_mesh(data=8)
        x = jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                           NamedSharding(mesh8, P("data", None)))
        y = jnp.float32(7.5)  # replicated scalar
        ckpt.save_state(str(tmp_path / "c"), {"x": x, "y": y},
                        extra={"step": 3})
        # shard files: 8 for x
        files = os.listdir(tmp_path / "c" / "arrays" / "x")
        assert len(files) == 8
        assert ckpt.load_extra(str(tmp_path / "c"))["step"] == 3

        # reshard onto a DIFFERENT mesh: 4 devices, other axis sharded
        mesh4 = mesh_lib.make_mesh(data=4, devices=jax.devices()[:4])
        tmpl = {"x": jax.ShapeDtypeStruct((8, 8), jnp.float32),
                "y": jax.ShapeDtypeStruct((), jnp.float32)}
        sh = {"x": NamedSharding(mesh4, P(None, "data")),
              "y": NamedSharding(mesh4, P())}
        out = ckpt.load_state(str(tmp_path / "c"), tmpl, sh)
        np.testing.assert_array_equal(np.asarray(out["x"]), np.asarray(x))
        assert float(out["y"]) == 7.5
        assert out["x"].sharding.spec == P(None, "data")

    def test_replicas_deduped(self, tmp_path):
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = mesh_lib.make_mesh(data=2, model=4)
        x = jax.device_put(jnp.ones((4, 4), jnp.float32),
                           NamedSharding(mesh, P("model", None)))
        ckpt.save_state(str(tmp_path / "c"), {"x": x})
        # replicated over data=2 -> only 4 unique shards written
        files = [f for f in os.listdir(tmp_path / "c" / "arrays" / "x")]
        assert len(files) == 4

    def test_missing_leaf_and_shape_mismatch(self, tmp_path):
        ckpt.save_state(str(tmp_path / "c"), {"a": jnp.zeros((2, 2))})
        with pytest.raises(KeyError):
            ckpt.load_state(str(tmp_path / "c"),
                            {"b": jax.ShapeDtypeStruct((2, 2), jnp.float32)})
        with pytest.raises(ValueError, match="shape mismatch"):
            ckpt.load_state(str(tmp_path / "c"),
                            {"a": jax.ShapeDtypeStruct((3, 2), jnp.float32)})

    def test_latest_step(self, tmp_path):
        root = str(tmp_path / "r")
        assert ckpt.latest_step(root) is None
        ckpt.save_state(ckpt.step_dir(root, 2), {"a": jnp.zeros(2)})
        ckpt.save_state(ckpt.step_dir(root, 10), {"a": jnp.zeros(2)})
        os.makedirs(os.path.join(root, "step_00000099"))  # incomplete
        assert ckpt.latest_step(root) == 10


class TestTrainStateResume:
    def _mk(self, mesh, zero_stage):
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig
        from paddle_tpu.distributed.parallelize import ShardedTrainState
        from paddle_tpu.optimizer.functional import AdamW
        return ShardedTrainState(LlamaConfig.tiny(), llama, mesh,
                                 AdamW(learning_rate=1e-3),
                                 zero_stage=zero_stage)

    @pytest.mark.slow
    def test_resume_on_smaller_mesh_and_other_zero_stage(self, tmp_path):
        """Train 2 steps on 8 devices (zero-3), save, resume on 4 devices
        (zero-1): losses must continue identically vs no interruption."""
        from paddle_tpu.models import llama
        toks = np.random.default_rng(5).integers(0, 256, (8, 33))

        mesh8 = mesh_lib.make_mesh(data=2, sharding=4)
        st8 = self._mk(mesh8, zero_stage=3)
        params, opt = st8.init(jax.random.PRNGKey(0))
        batch8 = st8.shard_batch(
            llama.lm_batch_from_tokens(jnp.asarray(toks, jnp.int32)))
        for _ in range(2):
            params, opt, _ = st8.step(params, opt, batch8)
        st8.save(str(tmp_path / "c"), params, opt, step=2)
        # uninterrupted continuation (baseline)
        p_c, o_c = params, opt
        base = []
        for _ in range(2):
            p_c, o_c, m = st8.step(p_c, o_c, batch8)
            base.append(float(m["loss"]))

        mesh4 = mesh_lib.make_mesh(data=2, sharding=2,
                                   devices=jax.devices()[:4])
        st4 = self._mk(mesh4, zero_stage=1)
        p4, o4 = st4.restore(str(tmp_path / "c"))
        batch4 = st4.shard_batch(
            llama.lm_batch_from_tokens(jnp.asarray(toks, jnp.int32)))
        got = []
        for _ in range(2):
            p4, o4, m = st4.step(p4, o4, batch4)
            got.append(float(m["loss"]))
        np.testing.assert_allclose(got, base, rtol=2e-5, atol=2e-6)


class TestCheckpointManager:
    """Async auto-checkpointing with retention (reference auto_checkpoint)."""

    def _tree(self, v):
        import jax.numpy as jnp
        return {"w": jnp.full((4, 4), float(v)), "b": jnp.full((4,), float(v))}

    def test_async_save_restore_and_retention(self, tmp_path):
        from paddle_tpu.distributed.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path), keep=2, save_interval=2)
        assert mgr.should_save(4) and not mgr.should_save(3)
        for step in (2, 4, 6, 8):
            mgr.save(step, self._tree(step), extra={"step": step})
        mgr.wait()
        # retention: only the newest 2 complete checkpoints remain
        import os
        kept = sorted(n for n in os.listdir(tmp_path) if n.startswith("step_"))
        assert kept == ["step_00000006", "step_00000008"], kept
        (restored, s) = mgr.restore(self._tree(0))
        assert s == 8
        np.testing.assert_array_equal(np.asarray(restored["w"]), 8.0)
        # snapshot semantics: the device buffers may be DELETED (donation)
        # right after save() returns — the write must not touch them
        t = self._tree(10)
        mgr.save(10, t)
        for leaf in t.values():
            leaf.delete()
        mgr.wait()
        (restored, s) = mgr.restore(self._tree(0))
        assert s == 10
        np.testing.assert_array_equal(np.asarray(restored["w"]), 10.0)

    def test_blocking_save_and_error_surface(self, tmp_path):
        from paddle_tpu.distributed.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "ck"), keep=1)
        mgr.save(1, self._tree(1), block=True)
        assert mgr.latest_step() == 1
        with pytest.raises(ValueError, match="keep must be"):
            CheckpointManager(str(tmp_path), keep=0)
        with pytest.raises(FileNotFoundError, match="no complete checkpoint"):
            CheckpointManager(str(tmp_path / "empty")).restore(self._tree(0))
