"""Independent-oracle parity: round-5 ops vs torch (CPU).  The reference's
kernels match torch semantics for these ops, so torch is a reference-
equivalent oracle that shares no code with this repo."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn

torch = pytest.importorskip("torch")


def _t(a):
    return torch.from_numpy(np.asarray(a))


def _copy_cell(tmod, cell, sfx=""):
    """Copy a paddle cell's 4 packed params onto a torch RNN module."""
    with torch.no_grad():
        getattr(tmod, f"weight_ih{sfx}").copy_(_t(cell.weight_ih.numpy()))
        getattr(tmod, f"weight_hh{sfx}").copy_(_t(cell.weight_hh.numpy()))
        getattr(tmod, f"bias_ih{sfx}").copy_(_t(cell.bias_ih.numpy()))
        getattr(tmod, f"bias_hh{sfx}").copy_(_t(cell.bias_hh.numpy()))


class TestRnnCellsVsTorch:
    def test_lstm_cell(self):
        cell = nn.LSTMCell(8, 6)
        tcell = torch.nn.LSTMCell(8, 6)
        _copy_cell(tcell, cell)
        x = np.random.randn(4, 8).astype("float32")
        h0 = np.random.randn(4, 6).astype("float32")
        c0 = np.random.randn(4, 6).astype("float32")
        _, (h, c) = cell(paddle.to_tensor(x),
                         (paddle.to_tensor(h0), paddle.to_tensor(c0)))
        th, tc = tcell(_t(x), (_t(h0), _t(c0)))
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(c.numpy(), tc.detach().numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_gru_cell(self):
        """paddle/torch GRU differ ONLY in where b_hh enters the candidate:
        both compute c = tanh(x_c + r * (h W_c^T + b_hc)) — identical when
        weights are shared, so torch oracles the repo's gate math."""
        cell = nn.GRUCell(8, 6)
        tcell = torch.nn.GRUCell(8, 6)
        _copy_cell(tcell, cell)
        x = np.random.randn(4, 8).astype("float32")
        h0 = np.random.randn(4, 6).astype("float32")
        h, _ = cell(paddle.to_tensor(x), paddle.to_tensor(h0))
        th = tcell(_t(x), _t(h0))
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_lstm_sequence(self):
        net = nn.LSTM(5, 4)
        tnet = torch.nn.LSTM(5, 4, batch_first=True)
        _copy_cell(tnet, net[0].cell, "_l0")
        x = np.random.randn(3, 7, 5).astype("float32")
        out, (h, c) = net(paddle.to_tensor(x))
        tout, (th, tc) = tnet(_t(x))
        np.testing.assert_allclose(out.numpy(), tout.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestOpsVsTorch:
    def test_max_unpool2d(self):
        x = np.random.randn(2, 3, 8, 8).astype("float32")
        tp, tidx = torch.nn.functional.max_pool2d(_t(x), 2,
                                                  return_indices=True)
        up = F.max_unpool2d(paddle.to_tensor(tp.numpy()),
                            paddle.to_tensor(tidx.numpy()), 2,
                            output_size=[8, 8])
        tup = torch.nn.functional.max_unpool2d(tp, tidx, 2)
        np.testing.assert_allclose(up.numpy(), tup.numpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_adaptive_avg_pool3d(self):
        x = np.random.randn(2, 3, 7, 9, 5).astype("float32")
        ours = F.adaptive_avg_pool3d(paddle.to_tensor(x), (2, 3, 2))
        ref = torch.nn.functional.adaptive_avg_pool3d(_t(x), (2, 3, 2))
        np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_cdist(self):
        x = np.random.randn(2, 5, 4).astype("float32")
        y = np.random.randn(2, 7, 4).astype("float32")
        for p in (1.0, 2.0, 3.0):
            ours = paddle.cdist(paddle.to_tensor(x), paddle.to_tensor(y),
                                p=p)
            ref = torch.cdist(_t(x), _t(y), p=p)
            np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                       rtol=1e-4, atol=1e-4)

    def test_diag_embed_offsets(self):
        x = np.random.randn(2, 3, 4).astype("float32")
        for off in (-2, -1, 0, 1, 2):
            ours = F.diag_embed(paddle.to_tensor(x), offset=off)
            ref = torch.diag_embed(_t(x), offset=off)
            np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                       rtol=1e-6, atol=1e-6)

    def test_renorm(self):
        x = np.random.randn(4, 6).astype("float32") * 3
        ours = paddle.renorm(paddle.to_tensor(x), 2.0, 0, 1.0)
        ref = torch.renorm(_t(x), 2.0, 0, 1.0)
        np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_unfold(self):
        x = np.random.randn(3, 10).astype("float32")
        ours = paddle.unfold(paddle.to_tensor(x), 1, 4, 2)
        ref = _t(x).unfold(1, 4, 2)
        np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_i0e_i1e(self):
        x = np.random.randn(16).astype("float32") * 3
        np.testing.assert_allclose(
            paddle.i0e(paddle.to_tensor(x)).numpy(),
            torch.special.i0e(_t(x)).numpy(), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            paddle.i1e(paddle.to_tensor(x)).numpy(),
            torch.special.i1e(_t(x)).numpy(), rtol=1e-5, atol=1e-6)


class TestLossesVsTorch:
    def test_soft_margin(self):
        x = np.random.randn(4, 6).astype("float32")
        y = np.sign(np.random.randn(4, 6)).astype("float32")
        np.testing.assert_allclose(
            F.soft_margin_loss(paddle.to_tensor(x),
                               paddle.to_tensor(y)).numpy(),
            torch.nn.functional.soft_margin_loss(_t(x), _t(y)).numpy(),
            rtol=1e-5, atol=1e-6)

    def test_multi_margin(self):
        x = np.random.randn(5, 7).astype("float32")
        y = np.random.randint(0, 7, 5)
        for p in (1, 2):
            np.testing.assert_allclose(
                F.multi_margin_loss(paddle.to_tensor(x),
                                    paddle.to_tensor(y), p=p).numpy(),
                torch.nn.functional.multi_margin_loss(_t(x), _t(y),
                                                      p=p).numpy(),
                rtol=1e-5, atol=1e-6)

    def test_multi_label_soft_margin(self):
        x = np.random.randn(4, 6).astype("float32")
        y = (np.random.rand(4, 6) > 0.5).astype("float32")
        np.testing.assert_allclose(
            F.multi_label_soft_margin_loss(paddle.to_tensor(x),
                                           paddle.to_tensor(y)).numpy(),
            torch.nn.functional.multilabel_soft_margin_loss(
                _t(x), _t(y)).numpy(), rtol=1e-5, atol=1e-6)

    def test_gaussian_nll(self):
        x = np.random.randn(8).astype("float32")
        y = np.random.randn(8).astype("float32")
        v = (np.abs(np.random.randn(8)) + 0.3).astype("float32")
        for full in (False, True):
            np.testing.assert_allclose(
                F.gaussian_nll_loss(paddle.to_tensor(x),
                                    paddle.to_tensor(y),
                                    paddle.to_tensor(v),
                                    full=full).numpy(),
                torch.nn.functional.gaussian_nll_loss(
                    _t(x), _t(y), _t(v), full=full).numpy(),
                rtol=1e-5, atol=1e-6)

    def test_triplet_margin_with_distance(self):
        a = np.random.randn(5, 8).astype("float32")
        p = np.random.randn(5, 8).astype("float32")
        n = np.random.randn(5, 8).astype("float32")
        for swap in (False, True):
            np.testing.assert_allclose(
                F.triplet_margin_with_distance_loss(
                    paddle.to_tensor(a), paddle.to_tensor(p),
                    paddle.to_tensor(n), swap=swap).numpy(),
                torch.nn.functional.triplet_margin_loss(
                    _t(a), _t(p), _t(n), swap=swap).numpy(),
                rtol=1e-4, atol=1e-5)

    def test_clip_grad_norm_matches_torch(self):
        w = np.random.randn(6).astype("float32")
        g = np.random.randn(6).astype("float32") * 5

        p = paddle.to_tensor(w.copy(), stop_gradient=False)
        (p * paddle.to_tensor(g)).sum().backward()
        total = nn.utils.clip_grad_norm_([p], 1.0)

        tp = torch.tensor(w, requires_grad=True)
        (tp * _t(g)).sum().backward()
        ttotal = torch.nn.utils.clip_grad_norm_([tp], 1.0)
        np.testing.assert_allclose(float(total.numpy()), float(ttotal),
                                   rtol=1e-4)
        np.testing.assert_allclose(p.grad.numpy(), tp.grad.numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestStackedRnnVsTorch:
    def test_bidirectional_two_layer_lstm(self):
        """Pins output values AND the (num_layers*dirs, B, H) state packing
        order against torch (paddle uses the same convention)."""
        net = nn.LSTM(5, 4, num_layers=2, direction="bidirect")
        tnet = torch.nn.LSTM(5, 4, num_layers=2, bidirectional=True,
                             batch_first=True)
        # copy weights: paddle layer l holds BiRNN(cell_fw, cell_bw)
        for layer in range(2):
            bi = net[layer]
            for d, cell in ((0, bi.cell_fw), (1, bi.cell_bw)):
                _copy_cell(tnet, cell,
                           f"_l{layer}" + ("_reverse" if d else ""))
        x = np.random.randn(3, 6, 5).astype("float32")
        out, (h, c) = net(paddle.to_tensor(x))
        tout, (th, tc) = tnet(_t(x))
        np.testing.assert_allclose(out.numpy(), tout.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(c.numpy(), tc.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_two_layer_gru(self):
        net = nn.GRU(5, 4, num_layers=2)
        tnet = torch.nn.GRU(5, 4, num_layers=2, batch_first=True)
        for layer in range(2):
            _copy_cell(tnet, net[layer].cell, f"_l{layer}")
        x = np.random.randn(2, 7, 5).astype("float32")
        out, h = net(paddle.to_tensor(x))
        tout, th = tnet(_t(x))
        np.testing.assert_allclose(out.numpy(), tout.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
