"""Independent-oracle parity: round-5 ops vs torch (CPU).  The reference's
kernels match torch semantics for these ops, so torch is a reference-
equivalent oracle that shares no code with this repo."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn

torch = pytest.importorskip("torch")


def _t(a):
    return torch.from_numpy(np.asarray(a))


def _copy_cell(tmod, cell, sfx=""):
    """Copy a paddle cell's 4 packed params onto a torch RNN module."""
    with torch.no_grad():
        getattr(tmod, f"weight_ih{sfx}").copy_(_t(cell.weight_ih.numpy()))
        getattr(tmod, f"weight_hh{sfx}").copy_(_t(cell.weight_hh.numpy()))
        getattr(tmod, f"bias_ih{sfx}").copy_(_t(cell.bias_ih.numpy()))
        getattr(tmod, f"bias_hh{sfx}").copy_(_t(cell.bias_hh.numpy()))


class TestRnnCellsVsTorch:
    def test_lstm_cell(self):
        cell = nn.LSTMCell(8, 6)
        tcell = torch.nn.LSTMCell(8, 6)
        _copy_cell(tcell, cell)
        x = np.random.randn(4, 8).astype("float32")
        h0 = np.random.randn(4, 6).astype("float32")
        c0 = np.random.randn(4, 6).astype("float32")
        _, (h, c) = cell(paddle.to_tensor(x),
                         (paddle.to_tensor(h0), paddle.to_tensor(c0)))
        th, tc = tcell(_t(x), (_t(h0), _t(c0)))
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(c.numpy(), tc.detach().numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_gru_cell(self):
        """paddle/torch GRU differ ONLY in where b_hh enters the candidate:
        both compute c = tanh(x_c + r * (h W_c^T + b_hc)) — identical when
        weights are shared, so torch oracles the repo's gate math."""
        cell = nn.GRUCell(8, 6)
        tcell = torch.nn.GRUCell(8, 6)
        _copy_cell(tcell, cell)
        x = np.random.randn(4, 8).astype("float32")
        h0 = np.random.randn(4, 6).astype("float32")
        h, _ = cell(paddle.to_tensor(x), paddle.to_tensor(h0))
        th = tcell(_t(x), _t(h0))
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_lstm_sequence(self):
        net = nn.LSTM(5, 4)
        tnet = torch.nn.LSTM(5, 4, batch_first=True)
        _copy_cell(tnet, net[0].cell, "_l0")
        x = np.random.randn(3, 7, 5).astype("float32")
        out, (h, c) = net(paddle.to_tensor(x))
        tout, (th, tc) = tnet(_t(x))
        np.testing.assert_allclose(out.numpy(), tout.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestOpsVsTorch:
    def test_max_unpool2d(self):
        x = np.random.randn(2, 3, 8, 8).astype("float32")
        tp, tidx = torch.nn.functional.max_pool2d(_t(x), 2,
                                                  return_indices=True)
        up = F.max_unpool2d(paddle.to_tensor(tp.numpy()),
                            paddle.to_tensor(tidx.numpy()), 2,
                            output_size=[8, 8])
        tup = torch.nn.functional.max_unpool2d(tp, tidx, 2)
        np.testing.assert_allclose(up.numpy(), tup.numpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_adaptive_avg_pool3d(self):
        x = np.random.randn(2, 3, 7, 9, 5).astype("float32")
        ours = F.adaptive_avg_pool3d(paddle.to_tensor(x), (2, 3, 2))
        ref = torch.nn.functional.adaptive_avg_pool3d(_t(x), (2, 3, 2))
        np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_cdist(self):
        x = np.random.randn(2, 5, 4).astype("float32")
        y = np.random.randn(2, 7, 4).astype("float32")
        for p in (1.0, 2.0, 3.0):
            ours = paddle.cdist(paddle.to_tensor(x), paddle.to_tensor(y),
                                p=p)
            ref = torch.cdist(_t(x), _t(y), p=p)
            np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                       rtol=1e-4, atol=1e-4)

    def test_diag_embed_offsets(self):
        x = np.random.randn(2, 3, 4).astype("float32")
        for off in (-2, -1, 0, 1, 2):
            ours = F.diag_embed(paddle.to_tensor(x), offset=off)
            ref = torch.diag_embed(_t(x), offset=off)
            np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                       rtol=1e-6, atol=1e-6)

    def test_renorm(self):
        x = np.random.randn(4, 6).astype("float32") * 3
        ours = paddle.renorm(paddle.to_tensor(x), 2.0, 0, 1.0)
        ref = torch.renorm(_t(x), 2.0, 0, 1.0)
        np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_unfold(self):
        x = np.random.randn(3, 10).astype("float32")
        ours = paddle.unfold(paddle.to_tensor(x), 1, 4, 2)
        ref = _t(x).unfold(1, 4, 2)
        np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_i0e_i1e(self):
        x = np.random.randn(16).astype("float32") * 3
        np.testing.assert_allclose(
            paddle.i0e(paddle.to_tensor(x)).numpy(),
            torch.special.i0e(_t(x)).numpy(), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            paddle.i1e(paddle.to_tensor(x)).numpy(),
            torch.special.i1e(_t(x)).numpy(), rtol=1e-5, atol=1e-6)


class TestLossesVsTorch:
    def test_soft_margin(self):
        x = np.random.randn(4, 6).astype("float32")
        y = np.sign(np.random.randn(4, 6)).astype("float32")
        np.testing.assert_allclose(
            F.soft_margin_loss(paddle.to_tensor(x),
                               paddle.to_tensor(y)).numpy(),
            torch.nn.functional.soft_margin_loss(_t(x), _t(y)).numpy(),
            rtol=1e-5, atol=1e-6)

    def test_multi_margin(self):
        x = np.random.randn(5, 7).astype("float32")
        y = np.random.randint(0, 7, 5)
        for p in (1, 2):
            np.testing.assert_allclose(
                F.multi_margin_loss(paddle.to_tensor(x),
                                    paddle.to_tensor(y), p=p).numpy(),
                torch.nn.functional.multi_margin_loss(_t(x), _t(y),
                                                      p=p).numpy(),
                rtol=1e-5, atol=1e-6)

    def test_multi_label_soft_margin(self):
        x = np.random.randn(4, 6).astype("float32")
        y = (np.random.rand(4, 6) > 0.5).astype("float32")
        np.testing.assert_allclose(
            F.multi_label_soft_margin_loss(paddle.to_tensor(x),
                                           paddle.to_tensor(y)).numpy(),
            torch.nn.functional.multilabel_soft_margin_loss(
                _t(x), _t(y)).numpy(), rtol=1e-5, atol=1e-6)

    def test_gaussian_nll(self):
        x = np.random.randn(8).astype("float32")
        y = np.random.randn(8).astype("float32")
        v = (np.abs(np.random.randn(8)) + 0.3).astype("float32")
        for full in (False, True):
            np.testing.assert_allclose(
                F.gaussian_nll_loss(paddle.to_tensor(x),
                                    paddle.to_tensor(y),
                                    paddle.to_tensor(v),
                                    full=full).numpy(),
                torch.nn.functional.gaussian_nll_loss(
                    _t(x), _t(y), _t(v), full=full).numpy(),
                rtol=1e-5, atol=1e-6)

    def test_triplet_margin_with_distance(self):
        a = np.random.randn(5, 8).astype("float32")
        p = np.random.randn(5, 8).astype("float32")
        n = np.random.randn(5, 8).astype("float32")
        for swap in (False, True):
            np.testing.assert_allclose(
                F.triplet_margin_with_distance_loss(
                    paddle.to_tensor(a), paddle.to_tensor(p),
                    paddle.to_tensor(n), swap=swap).numpy(),
                torch.nn.functional.triplet_margin_loss(
                    _t(a), _t(p), _t(n), swap=swap).numpy(),
                rtol=1e-4, atol=1e-5)

    def test_clip_grad_norm_matches_torch(self):
        w = np.random.randn(6).astype("float32")
        g = np.random.randn(6).astype("float32") * 5

        p = paddle.to_tensor(w.copy(), stop_gradient=False)
        (p * paddle.to_tensor(g)).sum().backward()
        total = nn.utils.clip_grad_norm_([p], 1.0)

        tp = torch.tensor(w, requires_grad=True)
        (tp * _t(g)).sum().backward()
        ttotal = torch.nn.utils.clip_grad_norm_([tp], 1.0)
        np.testing.assert_allclose(float(total.numpy()), float(ttotal),
                                   rtol=1e-4)
        np.testing.assert_allclose(p.grad.numpy(), tp.grad.numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestStackedRnnVsTorch:
    def test_bidirectional_two_layer_lstm(self):
        """Pins output values AND the (num_layers*dirs, B, H) state packing
        order against torch (paddle uses the same convention)."""
        net = nn.LSTM(5, 4, num_layers=2, direction="bidirect")
        tnet = torch.nn.LSTM(5, 4, num_layers=2, bidirectional=True,
                             batch_first=True)
        # copy weights: paddle layer l holds BiRNN(cell_fw, cell_bw)
        for layer in range(2):
            bi = net[layer]
            for d, cell in ((0, bi.cell_fw), (1, bi.cell_bw)):
                _copy_cell(tnet, cell,
                           f"_l{layer}" + ("_reverse" if d else ""))
        x = np.random.randn(3, 6, 5).astype("float32")
        out, (h, c) = net(paddle.to_tensor(x))
        tout, (th, tc) = tnet(_t(x))
        np.testing.assert_allclose(out.numpy(), tout.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(c.numpy(), tc.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_two_layer_gru(self):
        net = nn.GRU(5, 4, num_layers=2)
        tnet = torch.nn.GRU(5, 4, num_layers=2, batch_first=True)
        for layer in range(2):
            _copy_cell(tnet, net[layer].cell, f"_l{layer}")
        x = np.random.randn(2, 7, 5).astype("float32")
        out, h = net(paddle.to_tensor(x))
        tout, th = tnet(_t(x))
        np.testing.assert_allclose(out.numpy(), tout.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestConvPoolNormVsTorch:
    """Conv / pool / norm / resize / pad families vs torch (the highest-
    traffic user ops after matmul; reference kernels match torch semantics)."""

    def test_conv2d_groups_stride_dilation(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 4, 10, 9)).astype("float32")
        w = rng.standard_normal((6, 2, 3, 3)).astype("float32")
        b = rng.standard_normal((6,)).astype("float32")
        got = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                       paddle.to_tensor(b), stride=2, padding=1,
                       dilation=2, groups=2)
        ref = torch.nn.functional.conv2d(_t(x), _t(w), _t(b), stride=2,
                                         padding=1, dilation=2, groups=2)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_conv2d_transpose_output_padding(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((2, 4, 7, 5)).astype("float32")
        w = rng.standard_normal((4, 3, 3, 3)).astype("float32")
        got = F.conv2d_transpose(paddle.to_tensor(x), paddle.to_tensor(w),
                                 stride=2, padding=1, output_padding=1)
        ref = torch.nn.functional.conv_transpose2d(_t(x), _t(w), stride=2,
                                                   padding=1,
                                                   output_padding=1)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_batch_norm_training_updates_stats(self):
        rng = np.random.default_rng(2)
        x = rng.standard_normal((4, 3, 5, 5)).astype("float32")
        wt = rng.standard_normal((3,)).astype("float32")
        bs = rng.standard_normal((3,)).astype("float32")
        rm = np.zeros((3,), "float32")
        rv = np.ones((3,), "float32")
        p_rm, p_rv = paddle.to_tensor(rm.copy()), paddle.to_tensor(rv.copy())
        got = F.batch_norm(paddle.to_tensor(x), p_rm, p_rv,
                           paddle.to_tensor(wt), paddle.to_tensor(bs),
                           training=True, momentum=0.9)
        t_rm, t_rv = _t(rm.copy()), _t(rv.copy())
        # paddle momentum m: running = m*running + (1-m)*batch == torch 1-m
        ref = torch.nn.functional.batch_norm(
            _t(x), t_rm, t_rv, _t(wt), _t(bs), training=True, momentum=0.1)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(p_rm.numpy(), t_rm.numpy(),
                                   rtol=1e-4, atol=1e-5)
        # running VAR diverges by convention: the reference updates with the
        # BIASED batch variance (batch_norm_kernel.cc /= N*sample_size, no
        # N-1), torch with unbiased — pin the paddle convention directly
        bvar = x.transpose(1, 0, 2, 3).reshape(3, -1).var(axis=1)  # biased
        np.testing.assert_allclose(p_rv.numpy(), 0.9 * rv + 0.1 * bvar,
                                   rtol=1e-4, atol=1e-5)
        n = x.size // 3
        np.testing.assert_allclose(
            t_rv.numpy(), 0.9 * rv + 0.1 * bvar * n / (n - 1),
            rtol=1e-4, atol=1e-5)  # confirm torch really is unbiased

    def test_group_and_instance_norm(self):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((2, 6, 4, 4)).astype("float32")
        wt = rng.standard_normal((6,)).astype("float32")
        bs = rng.standard_normal((6,)).astype("float32")
        got = F.group_norm(paddle.to_tensor(x), 3,
                           weight=paddle.to_tensor(wt),
                           bias=paddle.to_tensor(bs))
        ref = torch.nn.functional.group_norm(_t(x), 3, _t(wt), _t(bs))
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-4)
        got_i = F.instance_norm(paddle.to_tensor(x),
                                weight=paddle.to_tensor(wt),
                                bias=paddle.to_tensor(bs))
        ref_i = torch.nn.functional.instance_norm(_t(x), weight=_t(wt),
                                                  bias=_t(bs))
        np.testing.assert_allclose(got_i.numpy(), ref_i.numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_local_response_norm(self):
        rng = np.random.default_rng(4)
        x = rng.standard_normal((2, 7, 5, 5)).astype("float32")
        got = F.local_response_norm(paddle.to_tensor(x), size=5,
                                    alpha=1e-3, beta=0.6, k=1.5)
        ref = torch.nn.functional.local_response_norm(
            _t(x), size=5, alpha=1e-3, beta=0.6, k=1.5)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_avg_pool2d_ceil_exclusive(self):
        rng = np.random.default_rng(5)
        x = rng.standard_normal((2, 3, 7, 7)).astype("float32")
        got = F.avg_pool2d(paddle.to_tensor(x), kernel_size=3, stride=2,
                           padding=1, ceil_mode=True, exclusive=True)
        ref = torch.nn.functional.avg_pool2d(
            _t(x), 3, stride=2, padding=1, ceil_mode=True,
            count_include_pad=False)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_pool_ceil_mode_changes_output_size(self):
        """8x8, k3 s2 p0: floor -> 3x3, ceil -> 4x4 (the trailing partial
        window is kept) — shapes AND values must match torch."""
        rng = np.random.default_rng(12)
        x = rng.standard_normal((2, 3, 8, 8)).astype("float32")
        for ceil in (False, True):
            got = F.max_pool2d(paddle.to_tensor(x), 3, stride=2,
                               ceil_mode=ceil)
            ref = torch.nn.functional.max_pool2d(_t(x), 3, stride=2,
                                                 ceil_mode=ceil)
            assert tuple(got.shape) == tuple(ref.shape), f"ceil={ceil}"
            np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                       rtol=1e-6, atol=1e-7)
            got_a = F.avg_pool2d(paddle.to_tensor(x), 3, stride=2,
                                 ceil_mode=ceil, exclusive=True)
            ref_a = torch.nn.functional.avg_pool2d(
                _t(x), 3, stride=2, ceil_mode=ceil, count_include_pad=False)
            assert tuple(got_a.shape) == tuple(ref_a.shape)
            np.testing.assert_allclose(got_a.numpy(), ref_a.numpy(),
                                       rtol=1e-5, atol=1e-6)

    def test_avg_pool2d_divisor_override(self):
        rng = np.random.default_rng(13)
        x = rng.standard_normal((1, 2, 6, 6)).astype("float32")
        got = F.avg_pool2d(paddle.to_tensor(x), 2, stride=2,
                           divisor_override=3)
        ref = torch.nn.functional.avg_pool2d(_t(x), 2, stride=2,
                                             divisor_override=3)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-6, atol=1e-7)

    def test_max_pool2d_with_indices(self):
        rng = np.random.default_rng(6)
        x = rng.standard_normal((2, 3, 8, 6)).astype("float32")
        got, idx = F.max_pool2d(paddle.to_tensor(x), kernel_size=2,
                                stride=2, return_mask=True)
        ref, ridx = torch.nn.functional.max_pool2d(
            _t(x), 2, stride=2, return_indices=True)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_array_equal(idx.numpy(), ridx.numpy())

    def test_interpolate_modes(self):
        rng = np.random.default_rng(7)
        x = rng.standard_normal((2, 3, 5, 7)).astype("float32")
        for size in ([10, 13], [3, 4]):       # up- and down-sampling
            for mode, align in (("nearest", False), ("bilinear", False),
                                ("bilinear", True), ("bicubic", False),
                                ("bicubic", True), ("area", False)):
                got = F.interpolate(paddle.to_tensor(x), size=size,
                                    mode=mode, align_corners=align)
                kw = ({} if mode in ("nearest", "area")
                      else {"align_corners": align})
                ref = torch.nn.functional.interpolate(
                    _t(x), size=tuple(size), mode=mode, **kw)
                np.testing.assert_allclose(
                    got.numpy(), ref.numpy(), rtol=1e-4, atol=1e-4,
                    err_msg=f"{mode} align_corners={align} size={size}")

    def test_interpolate_1d_and_3d(self):
        rng = np.random.default_rng(14)
        x1 = rng.standard_normal((2, 3, 9)).astype("float32")
        got = F.interpolate(paddle.to_tensor(x1), size=[15], mode="linear",
                            data_format="NCW")
        ref = torch.nn.functional.interpolate(_t(x1), size=15, mode="linear",
                                              align_corners=False)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-5)
        x3 = rng.standard_normal((1, 2, 4, 5, 6)).astype("float32")
        got = F.interpolate(paddle.to_tensor(x3), size=[7, 8, 9],
                            mode="trilinear", data_format="NCDHW")
        ref = torch.nn.functional.interpolate(
            _t(x3), size=(7, 8, 9), mode="trilinear", align_corners=False)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_pad_modes(self):
        rng = np.random.default_rng(8)
        x = rng.standard_normal((2, 3, 5, 6)).astype("float32")
        for mode in ("reflect", "replicate", "circular"):
            got = F.pad(paddle.to_tensor(x), [1, 2, 2, 1], mode=mode)
            ref = torch.nn.functional.pad(_t(x), (1, 2, 2, 1), mode=mode)
            np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                       rtol=1e-6, atol=1e-7, err_msg=mode)

    def test_pixel_shuffle_roundtrip(self):
        rng = np.random.default_rng(9)
        x = rng.standard_normal((2, 12, 4, 5)).astype("float32")
        got = F.pixel_shuffle(paddle.to_tensor(x), 2)
        ref = torch.nn.functional.pixel_shuffle(_t(x), 2)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-6, atol=1e-7)
        back = F.pixel_unshuffle(paddle.to_tensor(ref.numpy()), 2)
        rback = torch.nn.functional.pixel_unshuffle(ref, 2)
        np.testing.assert_allclose(back.numpy(), rback.numpy(),
                                   rtol=1e-6, atol=1e-7)

    def test_kl_div(self):
        rng = np.random.default_rng(10)
        logp = np.log(rng.dirichlet(np.ones(6), size=(4,)).astype("float32"))
        target = rng.dirichlet(np.ones(6), size=(4,)).astype("float32")
        got = F.kl_div(paddle.to_tensor(logp), paddle.to_tensor(target),
                       reduction="mean")
        ref = torch.nn.functional.kl_div(_t(logp), _t(target),
                                         reduction="mean")
        np.testing.assert_allclose(float(got), float(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_ctc_loss_per_sample(self):
        rng = np.random.default_rng(11)
        T, B, C, L = 12, 3, 5, 4
        logits = rng.standard_normal((T, B, C)).astype("float32")
        logp = torch.log_softmax(_t(logits), dim=-1).numpy()
        labels = rng.integers(1, C, (B, L)).astype("int32")
        in_len = np.array([12, 10, 9], "int64")
        lab_len = np.array([4, 3, 2], "int64")
        got = F.ctc_loss(paddle.to_tensor(logp),
                         paddle.to_tensor(labels),
                         paddle.to_tensor(in_len),
                         paddle.to_tensor(lab_len),
                         blank=0, reduction="none")
        ref = torch.nn.functional.ctc_loss(
            _t(logp), _t(labels.astype("int64")), _t(in_len), _t(lab_len),
            blank=0, reduction="none")
        np.testing.assert_allclose(np.asarray(got.numpy()).reshape(-1),
                                   ref.numpy().reshape(-1),
                                   rtol=1e-4, atol=1e-4)
