"""Independent-oracle parity: round-5 ops vs torch (CPU).  The reference's
kernels match torch semantics for these ops, so torch is a reference-
equivalent oracle that shares no code with this repo."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn

torch = pytest.importorskip("torch")


def _t(a):
    return torch.from_numpy(np.asarray(a))


def _copy_cell(tmod, cell, sfx=""):
    """Copy a paddle cell's 4 packed params onto a torch RNN module."""
    with torch.no_grad():
        getattr(tmod, f"weight_ih{sfx}").copy_(_t(cell.weight_ih.numpy()))
        getattr(tmod, f"weight_hh{sfx}").copy_(_t(cell.weight_hh.numpy()))
        getattr(tmod, f"bias_ih{sfx}").copy_(_t(cell.bias_ih.numpy()))
        getattr(tmod, f"bias_hh{sfx}").copy_(_t(cell.bias_hh.numpy()))


class TestRnnCellsVsTorch:
    def test_lstm_cell(self):
        cell = nn.LSTMCell(8, 6)
        tcell = torch.nn.LSTMCell(8, 6)
        _copy_cell(tcell, cell)
        x = np.random.randn(4, 8).astype("float32")
        h0 = np.random.randn(4, 6).astype("float32")
        c0 = np.random.randn(4, 6).astype("float32")
        _, (h, c) = cell(paddle.to_tensor(x),
                         (paddle.to_tensor(h0), paddle.to_tensor(c0)))
        th, tc = tcell(_t(x), (_t(h0), _t(c0)))
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(c.numpy(), tc.detach().numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_gru_cell(self):
        """paddle/torch GRU differ ONLY in where b_hh enters the candidate:
        both compute c = tanh(x_c + r * (h W_c^T + b_hc)) — identical when
        weights are shared, so torch oracles the repo's gate math."""
        cell = nn.GRUCell(8, 6)
        tcell = torch.nn.GRUCell(8, 6)
        _copy_cell(tcell, cell)
        x = np.random.randn(4, 8).astype("float32")
        h0 = np.random.randn(4, 6).astype("float32")
        h, _ = cell(paddle.to_tensor(x), paddle.to_tensor(h0))
        th = tcell(_t(x), _t(h0))
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_lstm_sequence(self):
        net = nn.LSTM(5, 4)
        tnet = torch.nn.LSTM(5, 4, batch_first=True)
        _copy_cell(tnet, net[0].cell, "_l0")
        x = np.random.randn(3, 7, 5).astype("float32")
        out, (h, c) = net(paddle.to_tensor(x))
        tout, (th, tc) = tnet(_t(x))
        np.testing.assert_allclose(out.numpy(), tout.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestOpsVsTorch:
    def test_max_unpool2d_default_output_size(self):
        """output_size=None infers (in-1)*stride + kernel - 2*pad per dim
        (reference pooling.py:695) — must match torch's default."""
        x = np.random.randn(2, 3, 8, 8).astype("float32")
        tp, tidx = torch.nn.functional.max_pool2d(_t(x), 2,
                                                  return_indices=True)
        up = F.max_unpool2d(paddle.to_tensor(tp.numpy()),
                            paddle.to_tensor(tidx.numpy()), 2)
        tup = torch.nn.functional.max_unpool2d(tp, tidx, 2)
        np.testing.assert_allclose(up.numpy(), tup.numpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_pool_mask_static_roundtrip(self):
        """return_mask + unpool + interpolate survive to_static record-replay
        (the mask op is a second non-diff record)."""
        import paddle_tpu.nn.functional as PF

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2D(3, 4, 3, padding=1)

            def forward(self, x):
                h = PF.relu(self.conv(x))
                out, mask = PF.max_pool2d(h, 2, return_mask=True)
                h2 = PF.interpolate(out, scale_factor=2.0, mode="bilinear")
                return h2 + PF.max_unpool2d(out, mask, 2)

        net = Net()
        x = paddle.to_tensor(np.random.randn(2, 3, 8, 8).astype("float32"))
        eager = net(x).numpy()
        got = paddle.jit.to_static(net)(x).numpy()
        np.testing.assert_allclose(got, eager, rtol=1e-5, atol=1e-6)

    def test_max_unpool2d(self):
        x = np.random.randn(2, 3, 8, 8).astype("float32")
        tp, tidx = torch.nn.functional.max_pool2d(_t(x), 2,
                                                  return_indices=True)
        up = F.max_unpool2d(paddle.to_tensor(tp.numpy()),
                            paddle.to_tensor(tidx.numpy()), 2,
                            output_size=[8, 8])
        tup = torch.nn.functional.max_unpool2d(tp, tidx, 2)
        np.testing.assert_allclose(up.numpy(), tup.numpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_adaptive_avg_pool3d(self):
        x = np.random.randn(2, 3, 7, 9, 5).astype("float32")
        ours = F.adaptive_avg_pool3d(paddle.to_tensor(x), (2, 3, 2))
        ref = torch.nn.functional.adaptive_avg_pool3d(_t(x), (2, 3, 2))
        np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_cdist(self):
        x = np.random.randn(2, 5, 4).astype("float32")
        y = np.random.randn(2, 7, 4).astype("float32")
        for p in (1.0, 2.0, 3.0):
            ours = paddle.cdist(paddle.to_tensor(x), paddle.to_tensor(y),
                                p=p)
            ref = torch.cdist(_t(x), _t(y), p=p)
            np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                       rtol=1e-4, atol=1e-4)

    def test_diag_embed_offsets(self):
        x = np.random.randn(2, 3, 4).astype("float32")
        for off in (-2, -1, 0, 1, 2):
            ours = F.diag_embed(paddle.to_tensor(x), offset=off)
            ref = torch.diag_embed(_t(x), offset=off)
            np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                       rtol=1e-6, atol=1e-6)

    def test_renorm(self):
        x = np.random.randn(4, 6).astype("float32") * 3
        ours = paddle.renorm(paddle.to_tensor(x), 2.0, 0, 1.0)
        ref = torch.renorm(_t(x), 2.0, 0, 1.0)
        np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_unfold(self):
        x = np.random.randn(3, 10).astype("float32")
        ours = paddle.unfold(paddle.to_tensor(x), 1, 4, 2)
        ref = _t(x).unfold(1, 4, 2)
        np.testing.assert_allclose(ours.numpy(), ref.numpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_i0e_i1e(self):
        x = np.random.randn(16).astype("float32") * 3
        np.testing.assert_allclose(
            paddle.i0e(paddle.to_tensor(x)).numpy(),
            torch.special.i0e(_t(x)).numpy(), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            paddle.i1e(paddle.to_tensor(x)).numpy(),
            torch.special.i1e(_t(x)).numpy(), rtol=1e-5, atol=1e-6)


class TestLossesVsTorch:
    def test_soft_margin(self):
        x = np.random.randn(4, 6).astype("float32")
        y = np.sign(np.random.randn(4, 6)).astype("float32")
        np.testing.assert_allclose(
            F.soft_margin_loss(paddle.to_tensor(x),
                               paddle.to_tensor(y)).numpy(),
            torch.nn.functional.soft_margin_loss(_t(x), _t(y)).numpy(),
            rtol=1e-5, atol=1e-6)

    def test_multi_margin(self):
        x = np.random.randn(5, 7).astype("float32")
        y = np.random.randint(0, 7, 5)
        for p in (1, 2):
            np.testing.assert_allclose(
                F.multi_margin_loss(paddle.to_tensor(x),
                                    paddle.to_tensor(y), p=p).numpy(),
                torch.nn.functional.multi_margin_loss(_t(x), _t(y),
                                                      p=p).numpy(),
                rtol=1e-5, atol=1e-6)

    def test_multi_label_soft_margin(self):
        x = np.random.randn(4, 6).astype("float32")
        y = (np.random.rand(4, 6) > 0.5).astype("float32")
        np.testing.assert_allclose(
            F.multi_label_soft_margin_loss(paddle.to_tensor(x),
                                           paddle.to_tensor(y)).numpy(),
            torch.nn.functional.multilabel_soft_margin_loss(
                _t(x), _t(y)).numpy(), rtol=1e-5, atol=1e-6)

    def test_gaussian_nll(self):
        x = np.random.randn(8).astype("float32")
        y = np.random.randn(8).astype("float32")
        v = (np.abs(np.random.randn(8)) + 0.3).astype("float32")
        for full in (False, True):
            np.testing.assert_allclose(
                F.gaussian_nll_loss(paddle.to_tensor(x),
                                    paddle.to_tensor(y),
                                    paddle.to_tensor(v),
                                    full=full).numpy(),
                torch.nn.functional.gaussian_nll_loss(
                    _t(x), _t(y), _t(v), full=full).numpy(),
                rtol=1e-5, atol=1e-6)

    def test_triplet_margin_with_distance(self):
        a = np.random.randn(5, 8).astype("float32")
        p = np.random.randn(5, 8).astype("float32")
        n = np.random.randn(5, 8).astype("float32")
        for swap in (False, True):
            np.testing.assert_allclose(
                F.triplet_margin_with_distance_loss(
                    paddle.to_tensor(a), paddle.to_tensor(p),
                    paddle.to_tensor(n), swap=swap).numpy(),
                torch.nn.functional.triplet_margin_loss(
                    _t(a), _t(p), _t(n), swap=swap).numpy(),
                rtol=1e-4, atol=1e-5)

    def test_clip_grad_norm_matches_torch(self):
        w = np.random.randn(6).astype("float32")
        g = np.random.randn(6).astype("float32") * 5

        p = paddle.to_tensor(w.copy(), stop_gradient=False)
        (p * paddle.to_tensor(g)).sum().backward()
        total = nn.utils.clip_grad_norm_([p], 1.0)

        tp = torch.tensor(w, requires_grad=True)
        (tp * _t(g)).sum().backward()
        ttotal = torch.nn.utils.clip_grad_norm_([tp], 1.0)
        np.testing.assert_allclose(float(total.numpy()), float(ttotal),
                                   rtol=1e-4)
        np.testing.assert_allclose(p.grad.numpy(), tp.grad.numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestStackedRnnVsTorch:
    def test_bidirectional_two_layer_lstm(self):
        """Pins output values AND the (num_layers*dirs, B, H) state packing
        order against torch (paddle uses the same convention)."""
        net = nn.LSTM(5, 4, num_layers=2, direction="bidirect")
        tnet = torch.nn.LSTM(5, 4, num_layers=2, bidirectional=True,
                             batch_first=True)
        # copy weights: paddle layer l holds BiRNN(cell_fw, cell_bw)
        for layer in range(2):
            bi = net[layer]
            for d, cell in ((0, bi.cell_fw), (1, bi.cell_bw)):
                _copy_cell(tnet, cell,
                           f"_l{layer}" + ("_reverse" if d else ""))
        x = np.random.randn(3, 6, 5).astype("float32")
        out, (h, c) = net(paddle.to_tensor(x))
        tout, (th, tc) = tnet(_t(x))
        np.testing.assert_allclose(out.numpy(), tout.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(c.numpy(), tc.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_two_layer_gru(self):
        net = nn.GRU(5, 4, num_layers=2)
        tnet = torch.nn.GRU(5, 4, num_layers=2, batch_first=True)
        for layer in range(2):
            _copy_cell(tnet, net[layer].cell, f"_l{layer}")
        x = np.random.randn(2, 7, 5).astype("float32")
        out, h = net(paddle.to_tensor(x))
        tout, th = tnet(_t(x))
        np.testing.assert_allclose(out.numpy(), tout.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestConvPoolNormVsTorch:
    """Conv / pool / norm / resize / pad families vs torch (the highest-
    traffic user ops after matmul; reference kernels match torch semantics)."""

    def test_conv2d_groups_stride_dilation(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 4, 10, 9)).astype("float32")
        w = rng.standard_normal((6, 2, 3, 3)).astype("float32")
        b = rng.standard_normal((6,)).astype("float32")
        got = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                       paddle.to_tensor(b), stride=2, padding=1,
                       dilation=2, groups=2)
        ref = torch.nn.functional.conv2d(_t(x), _t(w), _t(b), stride=2,
                                         padding=1, dilation=2, groups=2)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_conv2d_transpose_output_padding(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((2, 4, 7, 5)).astype("float32")
        w = rng.standard_normal((4, 3, 3, 3)).astype("float32")
        got = F.conv2d_transpose(paddle.to_tensor(x), paddle.to_tensor(w),
                                 stride=2, padding=1, output_padding=1)
        ref = torch.nn.functional.conv_transpose2d(_t(x), _t(w), stride=2,
                                                   padding=1,
                                                   output_padding=1)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_batch_norm_training_updates_stats(self):
        rng = np.random.default_rng(2)
        x = rng.standard_normal((4, 3, 5, 5)).astype("float32")
        wt = rng.standard_normal((3,)).astype("float32")
        bs = rng.standard_normal((3,)).astype("float32")
        rm = np.zeros((3,), "float32")
        rv = np.ones((3,), "float32")
        p_rm, p_rv = paddle.to_tensor(rm.copy()), paddle.to_tensor(rv.copy())
        got = F.batch_norm(paddle.to_tensor(x), p_rm, p_rv,
                           paddle.to_tensor(wt), paddle.to_tensor(bs),
                           training=True, momentum=0.9)
        t_rm, t_rv = _t(rm.copy()), _t(rv.copy())
        # paddle momentum m: running = m*running + (1-m)*batch == torch 1-m
        ref = torch.nn.functional.batch_norm(
            _t(x), t_rm, t_rv, _t(wt), _t(bs), training=True, momentum=0.1)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(p_rm.numpy(), t_rm.numpy(),
                                   rtol=1e-4, atol=1e-5)
        # running VAR diverges by convention: the reference updates with the
        # BIASED batch variance (batch_norm_kernel.cc /= N*sample_size, no
        # N-1), torch with unbiased — pin the paddle convention directly
        bvar = x.transpose(1, 0, 2, 3).reshape(3, -1).var(axis=1)  # biased
        np.testing.assert_allclose(p_rv.numpy(), 0.9 * rv + 0.1 * bvar,
                                   rtol=1e-4, atol=1e-5)
        n = x.size // 3
        np.testing.assert_allclose(
            t_rv.numpy(), 0.9 * rv + 0.1 * bvar * n / (n - 1),
            rtol=1e-4, atol=1e-5)  # confirm torch really is unbiased

    def test_group_and_instance_norm(self):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((2, 6, 4, 4)).astype("float32")
        wt = rng.standard_normal((6,)).astype("float32")
        bs = rng.standard_normal((6,)).astype("float32")
        got = F.group_norm(paddle.to_tensor(x), 3,
                           weight=paddle.to_tensor(wt),
                           bias=paddle.to_tensor(bs))
        ref = torch.nn.functional.group_norm(_t(x), 3, _t(wt), _t(bs))
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-4)
        got_i = F.instance_norm(paddle.to_tensor(x),
                                weight=paddle.to_tensor(wt),
                                bias=paddle.to_tensor(bs))
        ref_i = torch.nn.functional.instance_norm(_t(x), weight=_t(wt),
                                                  bias=_t(bs))
        np.testing.assert_allclose(got_i.numpy(), ref_i.numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_local_response_norm(self):
        rng = np.random.default_rng(4)
        x = rng.standard_normal((2, 7, 5, 5)).astype("float32")
        got = F.local_response_norm(paddle.to_tensor(x), size=5,
                                    alpha=1e-3, beta=0.6, k=1.5)
        ref = torch.nn.functional.local_response_norm(
            _t(x), size=5, alpha=1e-3, beta=0.6, k=1.5)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_avg_pool2d_ceil_exclusive(self):
        rng = np.random.default_rng(5)
        x = rng.standard_normal((2, 3, 7, 7)).astype("float32")
        got = F.avg_pool2d(paddle.to_tensor(x), kernel_size=3, stride=2,
                           padding=1, ceil_mode=True, exclusive=True)
        ref = torch.nn.functional.avg_pool2d(
            _t(x), 3, stride=2, padding=1, ceil_mode=True,
            count_include_pad=False)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_avg_pool_ceil_inclusive_divisor_clip(self):
        """exclusive=False + ceil_mode: trailing partial windows divide by
        the window clipped to input+pad (reference pooling.cc:74-84), not by
        the full kernel volume — torch count_include_pad=True matches."""
        rng = np.random.default_rng(6)
        x = rng.standard_normal((2, 3, 7, 7)).astype("float32")
        got = F.avg_pool2d(paddle.to_tensor(x), 3, stride=2, padding=1,
                           ceil_mode=True, exclusive=False)
        ref = torch.nn.functional.avg_pool2d(
            _t(x), 3, stride=2, padding=1, ceil_mode=True,
            count_include_pad=True)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-6)
        x3 = rng.standard_normal((1, 2, 5, 7, 6)).astype("float32")
        got = F.avg_pool3d(paddle.to_tensor(x3), 3, stride=2, padding=1,
                           ceil_mode=True, exclusive=False)
        ref = torch.nn.functional.avg_pool3d(
            _t(x3), 3, stride=2, padding=1, ceil_mode=True,
            count_include_pad=True)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_pool_ceil_mode_changes_output_size(self):
        """8x8, k3 s2 p0: floor -> 3x3, ceil -> 4x4 (the trailing partial
        window is kept) — shapes AND values must match torch."""
        rng = np.random.default_rng(12)
        x = rng.standard_normal((2, 3, 8, 8)).astype("float32")
        for ceil in (False, True):
            got = F.max_pool2d(paddle.to_tensor(x), 3, stride=2,
                               ceil_mode=ceil)
            ref = torch.nn.functional.max_pool2d(_t(x), 3, stride=2,
                                                 ceil_mode=ceil)
            assert tuple(got.shape) == tuple(ref.shape), f"ceil={ceil}"
            np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                       rtol=1e-6, atol=1e-7)
            got_a = F.avg_pool2d(paddle.to_tensor(x), 3, stride=2,
                                 ceil_mode=ceil, exclusive=True)
            ref_a = torch.nn.functional.avg_pool2d(
                _t(x), 3, stride=2, ceil_mode=ceil, count_include_pad=False)
            assert tuple(got_a.shape) == tuple(ref_a.shape)
            np.testing.assert_allclose(got_a.numpy(), ref_a.numpy(),
                                       rtol=1e-5, atol=1e-6)

    def test_avg_pool2d_divisor_override(self):
        rng = np.random.default_rng(13)
        x = rng.standard_normal((1, 2, 6, 6)).astype("float32")
        got = F.avg_pool2d(paddle.to_tensor(x), 2, stride=2,
                           divisor_override=3)
        ref = torch.nn.functional.avg_pool2d(_t(x), 2, stride=2,
                                             divisor_override=3)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-6, atol=1e-7)

    def test_max_pool2d_with_indices(self):
        rng = np.random.default_rng(6)
        x = rng.standard_normal((2, 3, 8, 6)).astype("float32")
        got, idx = F.max_pool2d(paddle.to_tensor(x), kernel_size=2,
                                stride=2, return_mask=True)
        ref, ridx = torch.nn.functional.max_pool2d(
            _t(x), 2, stride=2, return_indices=True)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_array_equal(idx.numpy(), ridx.numpy())

    def test_interpolate_modes(self):
        rng = np.random.default_rng(7)
        x = rng.standard_normal((2, 3, 5, 7)).astype("float32")
        for size in ([10, 13], [3, 4]):       # up- and down-sampling
            for mode, align in (("nearest", False), ("bilinear", False),
                                ("bilinear", True), ("bicubic", False),
                                ("bicubic", True), ("area", False)):
                got = F.interpolate(paddle.to_tensor(x), size=size,
                                    mode=mode, align_corners=align)
                kw = ({} if mode in ("nearest", "area")
                      else {"align_corners": align})
                ref = torch.nn.functional.interpolate(
                    _t(x), size=tuple(size), mode=mode, **kw)
                np.testing.assert_allclose(
                    got.numpy(), ref.numpy(), rtol=1e-4, atol=1e-4,
                    err_msg=f"{mode} align_corners={align} size={size}")

    def test_interpolate_scale_factor_drives_ratio(self):
        """A user scale_factor sets the coordinate ratio to 1/scale directly
        (torch default), not a recomputed S/O — differs whenever
        int(S*scale) != S*scale exactly."""
        rng = np.random.default_rng(15)
        x = rng.standard_normal((2, 3, 7, 6)).astype("float32")
        for mode in ("nearest", "bilinear", "bicubic"):
            kw = {} if mode == "nearest" else {"align_corners": False}
            got = F.interpolate(paddle.to_tensor(x), scale_factor=1.5,
                                mode=mode)
            ref = torch.nn.functional.interpolate(_t(x), scale_factor=1.5,
                                                  mode=mode, **kw)
            np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                       rtol=1e-4, atol=1e-4, err_msg=mode)
        got = F.interpolate(paddle.to_tensor(x), scale_factor=0.6,
                            mode="bilinear")
        ref = torch.nn.functional.interpolate(_t(x), scale_factor=0.6,
                                              mode="bilinear",
                                              align_corners=False)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_interpolate_1d_and_3d(self):
        rng = np.random.default_rng(14)
        x1 = rng.standard_normal((2, 3, 9)).astype("float32")
        got = F.interpolate(paddle.to_tensor(x1), size=[15], mode="linear",
                            data_format="NCW")
        ref = torch.nn.functional.interpolate(_t(x1), size=15, mode="linear",
                                              align_corners=False)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-5)
        x3 = rng.standard_normal((1, 2, 4, 5, 6)).astype("float32")
        got = F.interpolate(paddle.to_tensor(x3), size=[7, 8, 9],
                            mode="trilinear", data_format="NCDHW")
        ref = torch.nn.functional.interpolate(
            _t(x3), size=(7, 8, 9), mode="trilinear", align_corners=False)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_pad_modes(self):
        rng = np.random.default_rng(8)
        x = rng.standard_normal((2, 3, 5, 6)).astype("float32")
        for mode in ("reflect", "replicate", "circular"):
            got = F.pad(paddle.to_tensor(x), [1, 2, 2, 1], mode=mode)
            ref = torch.nn.functional.pad(_t(x), (1, 2, 2, 1), mode=mode)
            np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                       rtol=1e-6, atol=1e-7, err_msg=mode)

    def test_pixel_shuffle_roundtrip(self):
        rng = np.random.default_rng(9)
        x = rng.standard_normal((2, 12, 4, 5)).astype("float32")
        got = F.pixel_shuffle(paddle.to_tensor(x), 2)
        ref = torch.nn.functional.pixel_shuffle(_t(x), 2)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-6, atol=1e-7)
        back = F.pixel_unshuffle(paddle.to_tensor(ref.numpy()), 2)
        rback = torch.nn.functional.pixel_unshuffle(ref, 2)
        np.testing.assert_allclose(back.numpy(), rback.numpy(),
                                   rtol=1e-6, atol=1e-7)

    def test_kl_div(self):
        rng = np.random.default_rng(10)
        logp = np.log(rng.dirichlet(np.ones(6), size=(4,)).astype("float32"))
        target = rng.dirichlet(np.ones(6), size=(4,)).astype("float32")
        got = F.kl_div(paddle.to_tensor(logp), paddle.to_tensor(target),
                       reduction="mean")
        ref = torch.nn.functional.kl_div(_t(logp), _t(target),
                                         reduction="mean")
        np.testing.assert_allclose(float(got), float(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_ctc_loss_per_sample(self):
        rng = np.random.default_rng(11)
        T, B, C, L = 12, 3, 5, 4
        logits = rng.standard_normal((T, B, C)).astype("float32")
        logp = torch.log_softmax(_t(logits), dim=-1).numpy()
        labels = rng.integers(1, C, (B, L)).astype("int32")
        in_len = np.array([12, 10, 9], "int64")
        lab_len = np.array([4, 3, 2], "int64")
        got = F.ctc_loss(paddle.to_tensor(logp),
                         paddle.to_tensor(labels),
                         paddle.to_tensor(in_len),
                         paddle.to_tensor(lab_len),
                         blank=0, reduction="none")
        ref = torch.nn.functional.ctc_loss(
            _t(logp), _t(labels.astype("int64")), _t(in_len), _t(lab_len),
            blank=0, reduction="none")
        np.testing.assert_allclose(np.asarray(got.numpy()).reshape(-1),
                                   ref.numpy().reshape(-1),
                                   rtol=1e-4, atol=1e-4)


class TestMoreLossesVsTorch:
    """Loss-convention parity: these are the silent-corruption ops (a wrong
    scale/term trains anyway, just worse) — pin each against torch."""

    def test_cross_entropy_weight_ignore_smoothing(self):
        rng = np.random.default_rng(20)
        logits = rng.standard_normal((6, 5)).astype("float32")
        labels = rng.integers(0, 5, (6,)).astype("int64")
        labels[2] = -100
        w = (rng.random(5) + 0.5).astype("float32")
        got = F.cross_entropy(paddle.to_tensor(logits),
                              paddle.to_tensor(labels),
                              weight=paddle.to_tensor(w), ignore_index=-100)
        ref = torch.nn.functional.cross_entropy(
            _t(logits), _t(labels), weight=_t(w), ignore_index=-100)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-4)
        lab2 = np.abs(labels) % 5
        got = F.cross_entropy(paddle.to_tensor(logits),
                              paddle.to_tensor(lab2), label_smoothing=0.2)
        ref = torch.nn.functional.cross_entropy(_t(logits), _t(lab2),
                                                label_smoothing=0.2)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-4)

    def test_smooth_l1_delta_is_huber(self):
        """paddle smooth_l1_loss(delta) follows the HUBER formula (no /beta
        normalization) — the oracle is torch.huber_loss, NOT torch.smooth_l1."""
        rng = np.random.default_rng(21)
        x = rng.standard_normal((4, 3)).astype("float32")
        y = rng.standard_normal((4, 3)).astype("float32")
        got = F.smooth_l1_loss(paddle.to_tensor(x), paddle.to_tensor(y),
                               delta=0.7)
        ref = torch.nn.functional.huber_loss(_t(x), _t(y), delta=0.7)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)

    def test_bce_with_logits_pos_weight(self):
        rng = np.random.default_rng(22)
        lg = rng.standard_normal((4, 3)).astype("float32")
        tgt = rng.random((4, 3)).astype("float32")
        pw = (rng.random(3) + 0.5).astype("float32")
        got = F.binary_cross_entropy_with_logits(
            paddle.to_tensor(lg), paddle.to_tensor(tgt),
            pos_weight=paddle.to_tensor(pw))
        ref = torch.nn.functional.binary_cross_entropy_with_logits(
            _t(lg), _t(tgt), pos_weight=_t(pw))
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-4)

    def test_poisson_nll_full_stirling(self):
        rng = np.random.default_rng(23)
        x = np.abs(rng.standard_normal((4, 3))).astype("float32")
        y = (np.abs(rng.standard_normal((4, 3))) * 3).astype("float32")
        got = F.poisson_nll_loss(paddle.to_tensor(x), paddle.to_tensor(y),
                                 log_input=False, full=True)
        ref = torch.nn.functional.poisson_nll_loss(_t(x), _t(y),
                                                   log_input=False, full=True)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-4)

    def test_nll_loss_2d(self):
        rng = np.random.default_rng(24)
        lp = torch.log_softmax(
            _t(rng.standard_normal((2, 4, 3, 3)).astype("float32")), 1)
        lab = rng.integers(0, 4, (2, 3, 3)).astype("int64")
        got = F.nll_loss(paddle.to_tensor(lp.numpy()), paddle.to_tensor(lab))
        ref = torch.nn.functional.nll_loss(lp, _t(lab))
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)

    def test_embedding_style_losses(self):
        rng = np.random.default_rng(25)
        a, p, n = (rng.standard_normal((5, 8)).astype("float32")
                   for _ in range(3))
        got = F.triplet_margin_loss(paddle.to_tensor(a), paddle.to_tensor(p),
                                    paddle.to_tensor(n), margin=0.5)
        ref = torch.nn.functional.triplet_margin_loss(_t(a), _t(p), _t(n),
                                                      margin=0.5)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-4)
        x1, x2 = (rng.standard_normal((6,)).astype("float32")
                  for _ in range(2))
        lab = np.sign(rng.standard_normal(6)).astype("float32")
        got = F.margin_ranking_loss(paddle.to_tensor(x1),
                                    paddle.to_tensor(x2),
                                    paddle.to_tensor(lab), margin=0.3)
        ref = torch.nn.functional.margin_ranking_loss(_t(x1), _t(x2),
                                                      _t(lab), margin=0.3)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-4)
        e1 = rng.standard_normal((4, 6)).astype("float32")
        e2 = rng.standard_normal((4, 6)).astype("float32")
        yy = np.array([1, -1, 1, -1], "float32")
        got = F.cosine_embedding_loss(paddle.to_tensor(e1),
                                      paddle.to_tensor(e2),
                                      paddle.to_tensor(yy), margin=0.2)
        ref = torch.nn.functional.cosine_embedding_loss(_t(e1), _t(e2),
                                                        _t(yy), margin=0.2)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-4)
        h = rng.standard_normal((8,)).astype("float32")
        hy = np.sign(rng.standard_normal(8)).astype("float32")
        got = F.hinge_embedding_loss(paddle.to_tensor(h),
                                     paddle.to_tensor(hy), margin=0.8)
        ref = torch.nn.functional.hinge_embedding_loss(_t(h), _t(hy),
                                                       margin=0.8)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-4)


class TestLinalgVsTorch:
    """Dense linalg vs torch (LAPACK-backed on both sides).  Decompositions
    with sign/phase ambiguity are checked by reconstruction instead."""

    def test_solve_det_slogdet(self):
        rng = np.random.default_rng(30)
        A = rng.standard_normal((3, 5, 5)).astype("float32")
        B = rng.standard_normal((3, 5, 2)).astype("float32")
        np.testing.assert_allclose(
            paddle.linalg.solve(paddle.to_tensor(A),
                                paddle.to_tensor(B)).numpy(),
            torch.linalg.solve(_t(A), _t(B)).numpy(), rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(
            paddle.linalg.det(paddle.to_tensor(A)).numpy(),
            torch.linalg.det(_t(A)).numpy(), rtol=1e-4, atol=1e-5)
        sign, logdet = paddle.linalg.slogdet(paddle.to_tensor(A))
        rsign, rlog = torch.linalg.slogdet(_t(A))
        np.testing.assert_allclose(sign.numpy(), rsign.numpy(), atol=1e-6)
        np.testing.assert_allclose(logdet.numpy(), rlog.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_cholesky_pinv(self):
        rng = np.random.default_rng(31)
        A = rng.standard_normal((3, 5, 5)).astype("float32")
        S = A @ A.transpose(0, 2, 1) + 5 * np.eye(5, dtype="float32")
        np.testing.assert_allclose(
            paddle.linalg.cholesky(paddle.to_tensor(S)).numpy(),
            torch.linalg.cholesky(_t(S)).numpy(), rtol=1e-4, atol=1e-5)
        B = rng.standard_normal((3, 5, 2)).astype("float32")
        np.testing.assert_allclose(
            paddle.linalg.pinv(paddle.to_tensor(B)).numpy(),
            torch.linalg.pinv(_t(B)).numpy(), rtol=1e-3, atol=1e-4)

    def test_norm_conventions(self):
        """Vector norms (flat / per-axis) oracle vs torch; the axis-PAIR
        p-norm is the reference's documented entrywise flattened-vector
        convention (tensor/linalg.py:487 'treats the matrix as flattened
        vector'), NOT torch's induced matrix norm — oracle is numpy."""
        rng = np.random.default_rng(32)
        M = rng.standard_normal((4, 6)).astype("float32")
        for p in (1, 2, 3, np.inf):
            np.testing.assert_allclose(
                float(paddle.linalg.norm(paddle.to_tensor(M), p=p)),
                float(torch.linalg.vector_norm(_t(M).flatten(), ord=p)),
                rtol=1e-5)
        for p in (1, 2, np.inf):
            np.testing.assert_allclose(
                paddle.linalg.norm(paddle.to_tensor(M), p=p, axis=1).numpy(),
                torch.linalg.vector_norm(_t(M), ord=p, dim=1).numpy(),
                rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            float(paddle.linalg.norm(paddle.to_tensor(M), p="fro",
                                     axis=[0, 1])),
            float(np.sqrt((M ** 2).sum())), rtol=1e-5)
        np.testing.assert_allclose(
            float(paddle.linalg.norm(paddle.to_tensor(M), p=3, axis=[0, 1])),
            float((np.abs(M) ** 3).sum() ** (1 / 3)), rtol=1e-5)

    def test_decompositions_reconstruct(self):
        rng = np.random.default_rng(33)
        A = rng.standard_normal((4, 6)).astype("float32")
        u, s, vh = paddle.linalg.svd(paddle.to_tensor(A), full_matrices=False)
        rec = u.numpy() @ np.diag(s.numpy()) @ vh.numpy()
        np.testing.assert_allclose(rec, A, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            s.numpy(), torch.linalg.svdvals(_t(A)).numpy(),
            rtol=1e-4, atol=1e-5)  # singular values are unambiguous
        q, r = paddle.linalg.qr(paddle.to_tensor(A))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), A,
                                   rtol=1e-4, atol=1e-4)
        S = A @ A.T + 5 * np.eye(4, dtype="float32")
        w, v = paddle.linalg.eigh(paddle.to_tensor(S))
        np.testing.assert_allclose(
            v.numpy() @ np.diag(w.numpy()) @ v.numpy().T, S,
            rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(
            w.numpy(), torch.linalg.eigvalsh(_t(S)).numpy(),
            rtol=1e-4, atol=1e-4)


class TestIndexSortStatsVsTorch:
    """Index/scatter + sort/quantile/stats conventions vs torch."""

    def test_put_along_axis_reduce_modes(self):
        rng = np.random.default_rng(40)
        x = rng.standard_normal((4, 7)).astype("float32")
        ti = rng.integers(0, 4, (2, 7)).astype("int64")
        # per-column duplicate-free indices for 'assign': scatter's
        # duplicate-update order is undefined in BOTH torch and JAX
        ti_uniq = np.stack([rng.permutation(4)[:2] for _ in range(7)],
                           axis=1).astype("int64")
        vv = rng.standard_normal((2, 7)).astype("float32")
        for red, tred in (("assign", None), ("add", "sum"),
                          ("mul", "prod"), ("multiply", "prod")):
            ix = ti_uniq if red == "assign" else ti
            got = paddle.put_along_axis(
                paddle.to_tensor(x), paddle.to_tensor(ix),
                paddle.to_tensor(vv), 0, reduce=red)
            ref = (_t(x).scatter(0, _t(ix), _t(vv)) if tred is None
                   else _t(x).scatter_reduce(0, _t(ix), _t(vv), reduce=tred))
            np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                       rtol=1e-5, atol=1e-6, err_msg=red)
        with pytest.raises(ValueError, match="put_along_axis reduce"):
            paddle.put_along_axis(paddle.to_tensor(x), paddle.to_tensor(ti),
                                  paddle.to_tensor(vv), 0, reduce="bogus")

    def test_index_family(self):
        rng = np.random.default_rng(41)
        x = rng.standard_normal((4, 7)).astype("float32")
        idx = np.array([0, 2], "int64")
        src = rng.standard_normal((2, 7)).astype("float32")
        np.testing.assert_allclose(
            paddle.index_add(paddle.to_tensor(x), paddle.to_tensor(idx), 0,
                             paddle.to_tensor(src)).numpy(),
            _t(x).index_add(0, _t(idx), _t(src)).numpy(),
            rtol=1e-5, atol=1e-6)
        reps = np.array([1, 2, 0, 3], "int64")
        np.testing.assert_allclose(
            paddle.repeat_interleave(paddle.to_tensor(x),
                                     paddle.to_tensor(reps), axis=0).numpy(),
            torch.repeat_interleave(_t(x), _t(reps), dim=0).numpy())
        sb = np.sort(rng.standard_normal(6).astype("float32"))
        vals = rng.standard_normal((3,)).astype("float32")
        np.testing.assert_array_equal(
            paddle.searchsorted(paddle.to_tensor(sb),
                                paddle.to_tensor(vals)).numpy(),
            torch.searchsorted(_t(sb), _t(vals)).numpy())
        np.testing.assert_array_equal(
            paddle.bucketize(paddle.to_tensor(vals),
                             paddle.to_tensor(sb)).numpy(),
            torch.bucketize(_t(vals), _t(sb)).numpy())

    def test_quantile_interpolations(self):
        rng = np.random.default_rng(42)
        x = rng.standard_normal((4, 7)).astype("float32")
        for interp in ("linear", "lower", "higher", "nearest", "midpoint"):
            got = paddle.quantile(paddle.to_tensor(x), 0.37, axis=1,
                                  interpolation=interp)
            ref = torch.quantile(_t(x), 0.37, dim=1, interpolation=interp)
            np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                       rtol=1e-5, atol=1e-6, err_msg=interp)

    def test_stats_conventions(self):
        rng = np.random.default_rng(43)
        x = rng.standard_normal((4, 7)).astype("float32")
        # paddle std/var default UNBIASED (matches torch default)
        np.testing.assert_allclose(
            paddle.std(paddle.to_tensor(x), axis=1).numpy(),
            _t(x).std(dim=1).numpy(), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            paddle.var(paddle.to_tensor(x), axis=1, unbiased=False).numpy(),
            _t(x).var(dim=1, unbiased=False).numpy(), rtol=1e-5, atol=1e-6)
        xn = x.copy()
        xn[1, 2] = np.nan
        xn[1, 5] = np.nan
        np.testing.assert_allclose(
            paddle.nanmedian(paddle.to_tensor(xn), axis=1).numpy(),
            _t(xn).nanmedian(dim=1).values.numpy())
        np.testing.assert_array_equal(
            paddle.histogram(paddle.to_tensor(x), bins=6, min=-2,
                             max=2).numpy(),
            torch.histc(_t(x), bins=6, min=-2, max=2).numpy())
        np.testing.assert_allclose(
            paddle.logcumsumexp(paddle.to_tensor(x), axis=1).numpy(),
            torch.logcumsumexp(_t(x), dim=1).numpy(), rtol=1e-5, atol=1e-6)
        g, gi = paddle.kthvalue(paddle.to_tensor(x), 3, axis=1)
        r = _t(x).kthvalue(3, dim=1)
        np.testing.assert_allclose(g.numpy(), r.values.numpy())
        np.testing.assert_array_equal(gi.numpy(), r.indices.numpy())


class TestConvMiscVsTorch:
    """conv1d/3d/transpose, im2col, einsum, parameterized activations."""

    def test_conv1d_conv3d_groups(self):
        rng = np.random.default_rng(50)
        x3 = rng.standard_normal((2, 4, 9)).astype("float32")
        w3 = rng.standard_normal((6, 2, 3)).astype("float32")
        got = F.conv1d(paddle.to_tensor(x3), paddle.to_tensor(w3), stride=2,
                       padding=2, dilation=2, groups=2)
        ref = torch.nn.functional.conv1d(_t(x3), _t(w3), stride=2, padding=2,
                                         dilation=2, groups=2)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)
        x5 = rng.standard_normal((1, 4, 5, 6, 7)).astype("float32")
        w5 = rng.standard_normal((3, 4, 2, 2, 2)).astype("float32")
        got = F.conv3d(paddle.to_tensor(x5), paddle.to_tensor(w5), stride=2,
                       padding=1)
        ref = torch.nn.functional.conv3d(_t(x5), _t(w5), stride=2, padding=1)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-4)
        xt = rng.standard_normal((2, 4, 6)).astype("float32")
        wt = rng.standard_normal((4, 3, 3)).astype("float32")
        got = F.conv1d_transpose(paddle.to_tensor(xt), paddle.to_tensor(wt),
                                 stride=2, padding=1, output_padding=1)
        ref = torch.nn.functional.conv_transpose1d(
            _t(xt), _t(wt), stride=2, padding=1, output_padding=1)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_im2col_roundtrip(self):
        rng = np.random.default_rng(51)
        xi = rng.standard_normal((2, 3, 8, 8)).astype("float32")
        got = F.unfold(paddle.to_tensor(xi), kernel_sizes=3, strides=2,
                       paddings=1, dilations=1)
        ref = torch.nn.functional.unfold(_t(xi), 3, stride=2, padding=1,
                                         dilation=1)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-6, atol=1e-6)
        cols = rng.standard_normal((2, 27, 16)).astype("float32")
        got = F.fold(paddle.to_tensor(cols), output_sizes=[8, 8],
                     kernel_sizes=3, strides=2, paddings=1)
        ref = torch.nn.functional.fold(_t(cols), (8, 8), 3, stride=2,
                                       padding=1)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_parameterized_activations(self):
        rng = np.random.default_rng(52)
        x = (rng.standard_normal((4, 6)) * 8).astype("float32")
        px = paddle.to_tensor(x)
        tx = _t(x)
        for got, ref in (
            (F.softplus(px, beta=2, threshold=10),
             torch.nn.functional.softplus(tx, beta=2, threshold=10)),
            (F.elu(px, alpha=0.7), torch.nn.functional.elu(tx, alpha=0.7)),
            (F.celu(px, alpha=0.9), torch.nn.functional.celu(tx, alpha=0.9)),
            (F.selu(px), torch.nn.functional.selu(tx)),
            (F.softshrink(px, 0.7), torch.nn.functional.softshrink(tx, 0.7)),
            (F.hardtanh(px, -0.5, 0.8),
             torch.nn.functional.hardtanh(tx, -0.5, 0.8)),
            (F.mish(px), torch.nn.functional.mish(tx)),
            (F.hardswish(px), torch.nn.functional.hardswish(tx)),
            (F.hardsigmoid(px), torch.nn.functional.hardsigmoid(tx)),
            (F.glu(px, axis=1), torch.nn.functional.glu(tx, dim=1)),
            (F.normalize(px, p=3, axis=1),
             torch.nn.functional.normalize(tx, p=3, dim=1)),
        ):
            np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                       rtol=1e-5, atol=1e-5)

    def test_embedding_padding_idx_zeroes_forward(self):
        """Reference convention (nn/functional/input.py:142: 'pad all-zero
        data'): the padding_idx row is ZERO in the forward output — unlike
        torch, where padding_idx only masks gradients."""
        rng = np.random.default_rng(53)
        emb = rng.standard_normal((10, 4)).astype("float32")
        ids = np.array([[1, 2, 3], [2, 2, 5]], "int64")
        out = F.embedding(paddle.to_tensor(ids), paddle.to_tensor(emb),
                          padding_idx=2).numpy()
        np.testing.assert_allclose(out[ids == 2], 0.0)
        np.testing.assert_allclose(out[0, 0], emb[1], rtol=1e-6)

    def test_bilinear_prelu_pairwise(self):
        rng = np.random.default_rng(54)
        b1 = rng.standard_normal((5, 3)).astype("float32")
        b2 = rng.standard_normal((5, 4)).astype("float32")
        W = rng.standard_normal((6, 3, 4)).astype("float32")
        bb = rng.standard_normal((6,)).astype("float32")
        got = F.bilinear(paddle.to_tensor(b1), paddle.to_tensor(b2),
                         paddle.to_tensor(W),
                         paddle.to_tensor(bb.reshape(1, -1)))
        ref = torch.nn.functional.bilinear(_t(b1), _t(b2), _t(W), _t(bb))
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)
        xi = rng.standard_normal((2, 3, 4, 4)).astype("float32")
        alphas = np.array([0.1, 0.2, 0.3], "float32")
        got = F.prelu(paddle.to_tensor(xi), paddle.to_tensor(alphas))
        ref = torch.nn.functional.prelu(_t(xi), _t(alphas))
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-6, atol=1e-6)
        u = rng.standard_normal((4, 6)).astype("float32")
        v = rng.standard_normal((4, 6)).astype("float32")
        got = F.pairwise_distance(paddle.to_tensor(u), paddle.to_tensor(v),
                                  p=3)
        ref = torch.nn.functional.pairwise_distance(_t(u), _t(v), p=3)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-5)


class TestOptimizersVsTorch:
    """Single/multi-step update-math parity.  Params are PLAIN tensors with
    stop_gradient=False (not nn Parameters) — pinning the reference behavior
    that optimizers update any trainable tensor, which a Parameter-only
    filter silently no-ops."""

    W0 = np.linspace(-1, 1, 6).astype("float32").reshape(2, 3)

    def _run_paddle(self, name, kw, steps=3):
        p = paddle.to_tensor(self.W0.copy())
        p.stop_gradient = False
        opt = getattr(paddle.optimizer, name)(parameters=[p], **kw)
        for _ in range(steps):
            loss = (p * p).sum() * 0.5 + (p.sum() * 0.1)
            loss.backward()
            opt.step()
            opt.clear_grad()
        return p.numpy()

    def _run_torch(self, cls, kw, steps=3):
        p = torch.nn.Parameter(torch.from_numpy(self.W0.copy()))
        opt = cls([p], **kw)
        for _ in range(steps):
            opt.zero_grad()
            loss = (p * p).sum() * 0.5 + (p.sum() * 0.1)
            loss.backward()
            opt.step()
        return p.detach().numpy()

    @pytest.mark.parametrize("pname,pkw,tcls,tkw", [
        ("SGD", dict(learning_rate=0.1), "SGD", dict(lr=0.1)),
        ("Momentum", dict(learning_rate=0.1, momentum=0.9), "SGD",
         dict(lr=0.1, momentum=0.9)),
        ("Momentum", dict(learning_rate=0.1, momentum=0.9,
                          use_nesterov=True), "SGD",
         dict(lr=0.1, momentum=0.9, nesterov=True)),
        ("Adam", dict(learning_rate=0.01), "Adam", dict(lr=0.01)),
        ("AdamW", dict(learning_rate=0.01, weight_decay=0.1), "AdamW",
         dict(lr=0.01, weight_decay=0.1)),
        ("Adamax", dict(learning_rate=0.01), "Adamax", dict(lr=0.01)),
        ("Adagrad", dict(learning_rate=0.05), "Adagrad", dict(lr=0.05)),
        ("Adadelta", dict(learning_rate=1.0, rho=0.9), "Adadelta",
         dict(lr=1.0, rho=0.9)),
    ])
    def test_update_math_matches_torch(self, pname, pkw, tcls, tkw):
        got = self._run_paddle(pname, pkw)
        ref = self._run_torch(getattr(torch.optim, tcls), tkw)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_rmsprop_reference_epsilon_convention(self):
        """paddle RMSProp puts epsilon INSIDE the sqrt (rmsprop kernel:
        g / sqrt(ms + eps)); torch puts it outside — numpy is the oracle."""
        w = self.W0.copy()
        ms = np.zeros_like(w)
        for _ in range(3):
            g = w + 0.1
            ms = 0.9 * ms + 0.1 * g * g
            w = w - 0.01 * g / np.sqrt(ms + 1e-6)
        got = self._run_paddle("RMSProp", dict(learning_rate=0.01, rho=0.9))
        np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)

    def test_plain_tensor_actually_updates(self):
        """Regression: SGD over a plain to_tensor must change its values."""
        got = self._run_paddle("SGD", dict(learning_rate=0.1), steps=1)
        assert not np.allclose(got, self.W0)


class TestDistributionsVsTorch:
    """log_prob/entropy parity for the continuous/discrete families whose
    semantics torch shares (Categorical is excluded: the reference's
    sum-normalization is paddle-specific, pinned in test_distribution.py)."""

    def test_log_prob_and_entropy(self):
        import torch.distributions as td
        P = paddle.distribution
        rng = np.random.default_rng(60)
        v = rng.standard_normal(5).astype("float32")
        pos = (np.abs(rng.standard_normal(5)) + 0.5).astype("float32")
        counts = np.array([0.0, 1, 2, 5, 9], "float32")
        cases = [
            (P.Normal(0.5, 1.3), td.Normal(0.5, 1.3), v, True),
            (P.Laplace(0.2, 0.8), td.Laplace(0.2, 0.8), v, True),
            (P.Gumbel(0.1, 1.1), td.Gumbel(0.1, 1.1), v, True),
            (P.Cauchy(0.0, 1.5), td.Cauchy(0.0, 1.5), v, True),
            (P.Exponential(1.7), td.Exponential(1.7), pos, True),
            (P.Gamma(2.0, 1.5), td.Gamma(2.0, 1.5), pos, True),
            (P.Beta(2.0, 3.0), td.Beta(2.0, 3.0),
             (pos / (pos.max() + 1)).clip(0.05, 0.95), True),
            (P.LogNormal(0.1, 0.9), td.LogNormal(0.1, 0.9), pos, True),
            (P.StudentT(5.0, 0.1, 1.2), td.StudentT(5.0, 0.1, 1.2), v, True),
            (P.Geometric(0.3), td.Geometric(0.3), counts, True),
            (P.Poisson(2.5), td.Poisson(2.5), counts, False),  # torch: no H
        ]
        for pd, rd, x, check_ent in cases:
            name = type(pd).__name__
            np.testing.assert_allclose(
                pd.log_prob(paddle.to_tensor(x)).numpy(),
                rd.log_prob(_t(x)).numpy(), rtol=1e-4, atol=1e-5,
                err_msg=name)
            if check_ent:
                np.testing.assert_allclose(
                    np.asarray(pd.entropy().numpy()),
                    np.asarray(rd.entropy().numpy()), rtol=1e-4, atol=1e-5,
                    err_msg=name)

    def test_kl_closed_forms(self):
        import torch.distributions as td
        P = paddle.distribution
        for (p1, q1), (p2, q2) in [
            ((P.Normal(0.0, 1.0), P.Normal(0.5, 2.0)),
             (td.Normal(0.0, 1.0), td.Normal(0.5, 2.0))),
            ((P.Beta(2.0, 3.0), P.Beta(1.0, 1.0)),
             (td.Beta(2.0, 3.0), td.Beta(1.0, 1.0))),
            ((P.Gamma(2.0, 1.0), P.Gamma(3.0, 2.0)),
             (td.Gamma(2.0, 1.0), td.Gamma(3.0, 2.0))),
        ]:
            np.testing.assert_allclose(
                float(P.kl_divergence(p1, q1)),
                float(td.kl_divergence(p2, q2)), rtol=1e-4)

    def test_multinomial_log_prob(self):
        import torch.distributions as td
        probs = np.array([0.2, 0.3, 0.5], "float32")
        m1 = paddle.distribution.Multinomial(5, paddle.to_tensor(probs))
        m2 = td.Multinomial(5, probs=_t(probs))
        xm = np.array([1.0, 2, 2], "float32")
        np.testing.assert_allclose(
            float(m1.log_prob(paddle.to_tensor(xm))),
            float(m2.log_prob(_t(xm))), rtol=1e-5)


class TestLRSchedulersVsTorch:
    def test_decay_curves_match(self):
        L = paddle.optimizer.lr

        def run_paddle(s, steps=12):
            out = []
            for _ in range(steps):
                out.append(float(s()))
                s.step()
            return np.array(out)

        def run_torch(cls, kw, steps=12):
            p = torch.nn.Parameter(torch.zeros(1))
            opt = torch.optim.SGD([p], lr=0.1)
            s = cls(opt, **kw)
            out = []
            for _ in range(steps):
                out.append(opt.param_groups[0]["lr"])
                opt.step()
                s.step()
            return np.array(out)

        TL = torch.optim.lr_scheduler
        for name, ps, tc, tkw in [
            ("step", L.StepDecay(0.1, step_size=4, gamma=0.5), TL.StepLR,
             dict(step_size=4, gamma=0.5)),
            ("multistep", L.MultiStepDecay(0.1, milestones=[3, 7], gamma=0.1),
             TL.MultiStepLR, dict(milestones=[3, 7], gamma=0.1)),
            ("exp", L.ExponentialDecay(0.1, gamma=0.9), TL.ExponentialLR,
             dict(gamma=0.9)),
            ("cosine", L.CosineAnnealingDecay(0.1, T_max=10),
             TL.CosineAnnealingLR, dict(T_max=10)),
            ("linear", L.LinearLR(0.1, total_steps=8, start_factor=0.25,
                                  end_factor=1.0), TL.LinearLR,
             dict(start_factor=0.25, end_factor=1.0, total_iters=8)),
        ]:
            np.testing.assert_allclose(run_paddle(ps), run_torch(tc, tkw),
                                       rtol=1e-6, atol=1e-9, err_msg=name)


class TestSpecialFunctionsVsTorch:
    def test_special_functions(self):
        rng = np.random.default_rng(70)
        x = rng.standard_normal((3, 4)).astype("float32")
        pos = (np.abs(x) + 0.1).astype("float32")
        u = (rng.random((3, 4)) * 0.98 + 0.01).astype("float32")
        for name, arg, ref in (
            ("erf", x, torch.erf(_t(x))),
            ("erfinv", np.clip(x, -0.99, 0.99),
             torch.erfinv(_t(np.clip(x, -0.99, 0.99)))),
            ("lgamma", pos, torch.lgamma(_t(pos))),
            ("digamma", pos, torch.digamma(_t(pos))),
            ("log1p", pos, torch.log1p(_t(pos))),
            ("logit", u, torch.logit(_t(u))),
            ("i0", x, torch.special.i0(_t(x))),
            ("i1", x, torch.special.i1(_t(x))),
        ):
            got = getattr(paddle, name)(paddle.to_tensor(arg))
            np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                       rtol=1e-5, atol=1e-6, err_msg=name)
        # reference logit(eps) clamps to [eps, 1-eps] (tensor/math.py:5166)
        got = paddle.logit(paddle.to_tensor(u), eps=0.2)
        ref = torch.logit(_t(u), eps=0.2)
        np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            paddle.polygamma(paddle.to_tensor(pos), 1).numpy(),
            torch.special.polygamma(1, _t(pos)).numpy(),
            rtol=1e-4, atol=1e-5)

    def test_complex_ops(self):
        rng = np.random.default_rng(71)
        c = (rng.standard_normal((3, 4))
             + 1j * rng.standard_normal((3, 4))).astype("complex64")
        np.testing.assert_allclose(
            paddle.angle(paddle.to_tensor(c)).numpy(), np.angle(c),
            rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            paddle.conj(paddle.to_tensor(c)).numpy(), c.conj())
        np.testing.assert_allclose(
            paddle.abs(paddle.to_tensor(c)).numpy(), np.abs(c),
            rtol=1e-6, atol=1e-7)
