"""Unified ragged prefill+decode attention: the Pallas kernel vs its
gather reference AND the old paged kernel, `build_ragged_batch` layout
invariants, `generate_ragged()` parity with dense `generate()` /
`generate_paged()`, and the engine-level guarantees the unification buys:
a mixed prefill+decode step is ONE attention dispatch, and the
RecompileSentinel stays silent across mixed prompt lengths after warmup
(steady state is O(1) compiled executables — no bucket menu)."""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.obs as obs
from paddle_tpu.kernels import pallas_paged_attention as ppa
from paddle_tpu.kernels import pallas_ragged_attention as pra
from paddle_tpu.models import generation, llama
from paddle_tpu.models.llama import LlamaConfig


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _kernel_case(seed, spans_spec, Hq, Hkv, D, page_size, pages_per_seq,
                 block_q):
    """Random pools + a ragged batch from (span_len, ctx_len) specs.
    Every span gets its own shuffled page-table row; q rows are random
    (the batch builder's token/scatter columns are unused at kernel
    level)."""
    rng = np.random.default_rng(seed)
    P = len(spans_spec) * pages_per_seq + 1
    spans = []
    for i, (L, ctx) in enumerate(spans_spec):
        pages = (rng.permutation(P - 1)[:pages_per_seq] + 1).tolist()
        spans.append(generation.RaggedSpan(np.zeros(L, np.int32), ctx,
                                           pages[:-(-ctx // page_size)]))
    num_blocks = sum(-(-L // block_q) for L, _ in spans_spec)
    b = generation.build_ragged_batch(spans, num_blocks,
                                      len(spans) + 1, block_q,
                                      page_size, pages_per_seq)
    T = num_blocks * block_q
    q = jnp.asarray(rng.standard_normal((T, Hq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((P, page_size, Hkv, D)),
                    jnp.float32)
    v = jnp.asarray(rng.standard_normal((P, page_size, Hkv, D)),
                    jnp.float32)
    arrs = {n: jnp.asarray(b[n]) for n in
            ("span_pt", "block_seq", "block_qpos", "span_len", "ctx_len",
             "out_rows")}
    return q, k, v, arrs


class TestRaggedKernel:
    @pytest.mark.parametrize("page_size,rep,block_q",
                             [(4, 1, 4), (4, 2, 2), (8, 4, 4), (16, 2, 8)])
    def test_matches_gather_reference(self, page_size, rep, block_q):
        """Interpret-mode kernel vs the dense gather reference on a MIXED
        batch: decode spans (len 1), a mid-prefill chunk (cached context
        behind it), and a fresh chunk, across page sizes / GQA ratios /
        row-block sizes."""
        Hkv, D = 2, 16
        spec = [(1, 7), (5, 9), (3, 3), (1, 1)]
        q, k, v, a = _kernel_case(page_size + rep, spec, Hkv * rep, Hkv,
                                  D, page_size, 4, block_q)
        got = pra.ragged_attention_pallas(
            q, k, v, a["span_pt"], a["block_seq"], a["block_qpos"],
            a["span_len"], a["ctx_len"], interpret=True)
        want = pra.ragged_attention_reference(
            q, k, v, a["span_pt"], a["block_seq"], a["block_qpos"],
            a["span_len"], a["ctx_len"])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_decode_spans_match_paged_kernel(self):
        """A decode-only ragged batch IS the old workload: the unified
        kernel must reproduce the paged decode kernel exactly on the
        same pools (the engine's migration-safety guarantee)."""
        Hkv, rep, D, ps, pps, bq = 2, 2, 16, 4, 4, 2
        spec = [(1, 5), (1, 16), (1, 1)]
        q, k, v, a = _kernel_case(11, spec, Hkv * rep, Hkv, D, ps, pps, bq)
        got = pra.ragged_attention_pallas(
            q, k, v, a["span_pt"], a["block_seq"], a["block_qpos"],
            a["span_len"], a["ctx_len"], interpret=True)
        rows = np.asarray(a["out_rows"])[:len(spec)]
        old = ppa.paged_attention_pallas(
            q[rows], k, v, a["span_pt"][:len(spec)],
            a["ctx_len"][:len(spec)], interpret=True)
        np.testing.assert_allclose(np.asarray(got)[rows], np.asarray(old),
                                   rtol=2e-6, atol=2e-6)

    def test_whole_prompt_span_is_causal_attention(self):
        """One span carrying its WHOLE context (span_len == ctx_len, the
        resume-as-ragged-prefill shape) must equal plain causal
        attention over the span's rows."""
        Hkv, rep, D, ps, bq = 2, 2, 8, 4, 4
        L = 7
        q, k, v, a = _kernel_case(3, [(L, L)], Hkv * rep, Hkv, D, ps, 3,
                                  bq)
        got = pra.ragged_attention_pallas(
            q, k, v, a["span_pt"], a["block_seq"], a["block_qpos"],
            a["span_len"], a["ctx_len"], interpret=True)
        # dense oracle: gather the span's pages, causal-mask, softmax
        pt = np.asarray(a["span_pt"])[0]
        ck = np.asarray(k)[pt].reshape(-1, Hkv, D)[:L]     # (L, Hkv, D)
        cv = np.asarray(v)[pt].reshape(-1, Hkv, D)[:L]
        qf = np.asarray(q)[:L].reshape(L, Hkv, rep, D) / np.sqrt(D)
        s = np.einsum("thrd,mhd->thrm", qf, ck)
        s = np.where(np.tril(np.ones((L, L), bool))[:, None, None],
                     s, -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want = np.einsum("thrm,mhd->thrd", p, cv).reshape(L, Hkv * rep, D)
        np.testing.assert_allclose(np.asarray(got)[:L], want,
                                   rtol=2e-5, atol=2e-5)

    def test_padding_rows_are_zero(self):
        """Rows past span_len — and whole padding spans — must come out
        exactly zero (the engine ignores them, but NaNs would poison a
        donated accumulation downstream)."""
        Hkv, rep, D, ps, bq = 1, 2, 8, 4, 4
        q, k, v, a = _kernel_case(5, [(3, 6), (1, 4)], Hkv * rep, Hkv, D,
                                  ps, 3, bq)
        got = np.asarray(pra.ragged_attention_pallas(
            q, k, v, a["span_pt"], a["block_seq"], a["block_qpos"],
            a["span_len"], a["ctx_len"], interpret=True))
        assert np.isfinite(got).all()
        assert (got[3] == 0).all()          # span 0 rows past len 3
        assert (got[5:] == 0).all()         # span 1's block tail
        ref = np.asarray(pra.ragged_attention_reference(
            q, k, v, a["span_pt"], a["block_seq"], a["block_qpos"],
            a["span_len"], a["ctx_len"]))
        assert (ref[3] == 0).all() and (ref[5:] == 0).all()

    def test_span_exactly_fills_last_block(self):
        """Mask boundary: a span whose length is an exact multiple of
        block_q leaves NO padding rows in its last block — the row mask
        (qpos + r < span_len) must keep every row of that block live,
        and the block after it must belong to the next span.  This is
        the exactly-once coverage geometry kernellint's prover models;
        pin interpret-mode parity on it."""
        Hkv, rep, D, ps, bq = 2, 2, 8, 4, 4
        # span 0: 8 tokens / block_q 4 = two FULL blocks (ctx == len,
        # fresh prefill); span 1 starts on the very next block
        spec = [(8, 8), (3, 5)]
        q, k, v, a = _kernel_case(21, spec, Hkv * rep, Hkv, D, ps, 3, bq)
        got = np.asarray(pra.ragged_attention_pallas(
            q, k, v, a["span_pt"], a["block_seq"], a["block_qpos"],
            a["span_len"], a["ctx_len"], interpret=True))
        want = np.asarray(pra.ragged_attention_reference(
            q, k, v, a["span_pt"], a["block_seq"], a["block_qpos"],
            a["span_len"], a["ctx_len"]))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
        # every row of span 0's two blocks is LIVE (no zero padding rows
        # inside a full block) and finite
        assert np.isfinite(got).all()
        assert (np.abs(got[:8]).sum(axis=(1, 2)) > 0).all()

    def test_single_token_span_on_block_boundary(self):
        """Mask boundary: a single-token decode span whose context ends
        exactly on a page boundary (ctx_len == k * page_size) — the
        kv-page loop's last page is FULL, so an off-by-one in the page
        mask (pos < ctx vs pos <= ctx) flips the boundary key's
        contribution.  Pin parity against the gather reference."""
        Hkv, rep, D, ps, bq = 2, 2, 8, 4, 2
        # ctx 8 = exactly 2 full pages; sibling spans keep the batch
        # from degenerating to one block
        spec = [(1, 2 * ps), (1, ps), (3, 3)]
        q, k, v, a = _kernel_case(22, spec, Hkv * rep, Hkv, D, ps, 3, bq)
        got = np.asarray(pra.ragged_attention_pallas(
            q, k, v, a["span_pt"], a["block_seq"], a["block_qpos"],
            a["span_len"], a["ctx_len"], interpret=True))
        want = np.asarray(pra.ragged_attention_reference(
            q, k, v, a["span_pt"], a["block_seq"], a["block_qpos"],
            a["span_len"], a["ctx_len"]))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
        # oracle for span 0's decode row: dense softmax over ALL 8
        # context keys — dropping the boundary key is the bug this pins
        pt = np.asarray(a["span_pt"])[0][:2]
        ck = np.asarray(k)[pt].reshape(-1, Hkv, D)       # (8, Hkv, D)
        cv = np.asarray(v)[pt].reshape(-1, Hkv, D)
        qf = np.asarray(q)[0].reshape(Hkv, rep, D) / np.sqrt(D)
        s = np.einsum("hrd,mhd->hrm", qf, ck)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        oracle = np.einsum("hrm,mhd->hrd", p, cv).reshape(Hkv * rep, D)
        np.testing.assert_allclose(got[0], oracle, rtol=2e-5, atol=2e-5)

    def test_dispatcher_reference_fallback(self):
        """kernels.ragged_attention with fused kernels disabled routes to
        the gather reference."""
        from paddle_tpu import framework, kernels
        q, k, v, a = _kernel_case(9, [(1, 5), (4, 4)], 4, 2, 8, 4, 3, 4)
        flags = framework.get_state().flags
        prev = flags.get("FLAGS_use_fused_kernels", True)
        try:
            flags["FLAGS_use_fused_kernels"] = False
            got = kernels.ragged_attention(
                q, k, v, a["span_pt"], a["block_seq"], a["block_qpos"],
                a["span_len"], a["ctx_len"])
        finally:
            flags["FLAGS_use_fused_kernels"] = prev
        want = pra.ragged_attention_reference(
            q, k, v, a["span_pt"], a["block_seq"], a["block_qpos"],
            a["span_len"], a["ctx_len"])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)


class TestBuildRaggedBatch:
    def test_layout_invariants(self):
        spans = [generation.RaggedSpan([5], 9, [3, 7, 7]),
                 generation.RaggedSpan([1, 2, 3, 4, 5], 5, [2, 9])]
        b = generation.build_ragged_batch(spans, num_blocks=4, num_spans=4,
                                          block_q=2, page_size=4,
                                          pages_per_seq=3)
        # span 0: one block; span 1: three blocks (5 tokens / block_q 2)
        np.testing.assert_array_equal(b["block_seq"], [0, 1, 1, 1])
        np.testing.assert_array_equal(b["block_qpos"], [0, 0, 2, 4])
        np.testing.assert_array_equal(b["span_len"][:2], [1, 5])
        np.testing.assert_array_equal(b["ctx_len"][:2], [9, 5])
        np.testing.assert_array_equal(b["out_rows"][:2], [0, 6])
        # decode token of span 0 lands at position 8 = page idx 2 -> 7
        assert b["row_page"][0] == 7 and b["row_off"][0] == 0
        assert b["row_pos"][0] == 8
        # span 1's rows scatter at positions 0..4 across pages [2, 9]
        np.testing.assert_array_equal(b["row_page"][2:7], [2, 2, 2, 2, 9])
        np.testing.assert_array_equal(b["row_off"][2:7], [0, 1, 2, 3, 0])
        # padding rows target scratch page 0; unused blocks belong to the
        # reserved padding span (num_spans - 1) with span_len 0
        assert (b["row_page"][1] == 0) and (b["row_page"][7] == 0)
        assert b["span_len"][3] == 0
        # span_pt pads the tail with the last real page
        np.testing.assert_array_equal(b["span_pt"][1], [2, 9, 9])

    def test_rejects_overflow_and_empty(self):
        mk = generation.RaggedSpan
        with pytest.raises(ValueError, match="does not fit"):
            generation.build_ragged_batch(
                [mk([1, 2, 3], 3, [1])], num_blocks=1, num_spans=2,
                block_q=2, page_size=4, pages_per_seq=1)
        with pytest.raises(ValueError, match="exceed num_spans"):
            generation.build_ragged_batch(
                [mk([1], 1, [1]), mk([1], 1, [1])], num_blocks=4,
                num_spans=2, block_q=2, page_size=4, pages_per_seq=1)
        with pytest.raises(ValueError, match="cannot hold"):
            generation.build_ragged_batch(
                [mk([1], 9, [1])], num_blocks=2, num_spans=2, block_q=2,
                page_size=4, pages_per_seq=3)
        with pytest.raises(ValueError, match="at least one token"):
            generation.build_ragged_batch(
                [mk([], 1, [1])], num_blocks=2, num_spans=2, block_q=2,
                page_size=4, pages_per_seq=1)


class TestGenerateRagged:
    @pytest.mark.parametrize("page_size,chunk,block_q",
                             [(4, 5, 4), (16, 8, 4), (4, 1, 2)])
    def test_token_exact_vs_dense_and_paged(self, tiny, page_size, chunk,
                                            block_q):
        """The whole functional chain — chunked ragged prefill + 1-token
        ragged decode spans — reproduces dense generate() AND the paged
        path exactly, greedy, across chunk budgets (chunk=1 is the
        pathological all-chunks case)."""
        cfg, params = tiny
        for seed in range(2):
            ids = jnp.asarray(np.random.default_rng(seed).integers(
                0, cfg.vocab_size, (2, 7)), jnp.int32)
            want = generation.generate(params, ids, cfg, max_new_tokens=5)
            paged = generation.generate_paged(
                params, ids, cfg, max_new_tokens=5, page_size=page_size)
            got = generation.generate_ragged(
                params, ids, cfg, max_new_tokens=5, page_size=page_size,
                prefill_chunk_tokens=chunk, block_q=block_q)
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(want))
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(paged))


class TestEngineRagged:
    def _engine(self, tiny, **kw):
        from paddle_tpu.inference import LLMEngine
        cfg, params = tiny
        kw.setdefault("num_slots", 2)
        kw.setdefault("page_size", 4)
        kw.setdefault("max_seq_len", 32)
        kw.setdefault("prefill_chunk_tokens", 4)
        kw.setdefault("block_q", 2)
        return LLMEngine(params, cfg, **kw)

    def test_mixed_step_is_one_dispatch(self, tiny):
        """THE acceptance bar: a step advancing a decoding slot AND a
        prefilling slot issues exactly ONE attention dispatch, carrying
        both span kinds in one ragged batch."""
        cfg, params = tiny
        eng = self._engine(tiny)
        rng = np.random.default_rng(0)
        a = eng.submit(rng.integers(0, cfg.vocab_size, 3).tolist(),
                       max_new_tokens=8)
        eng.step()                 # admit A + its whole 3-token chunk
        eng.step()                 # A decodes
        assert not eng._slots[
            next(iter(eng._slots))].prefilling
        b = eng.submit(rng.integers(0, cfg.vocab_size, 11).tolist(),
                       max_new_tokens=4)
        calls = {"n": 0}
        real = eng._ragged
        real_fused = eng._ragged_fused

        def _counting(fn):
            def wrapper(*args, **kw):
                calls["n"] += 1
                return fn(*args, **kw)
            return wrapper

        # plain steps route the fused dispatch by default; count BOTH
        # executables so the one-dispatch bar holds whichever path runs
        eng._ragged = _counting(real)
        eng._ragged_fused = _counting(real_fused)
        snap0 = eng.stats_snapshot()
        eng.step()                 # A's decode span + B's first chunk
        assert calls["n"] == 1
        kinds = sorted(k for _s, k, _n in eng._batch_spans)
        assert kinds == ["chunk", "decode"]
        snap1 = eng.stats_snapshot()
        assert snap1["decode_tokens"] - snap0["decode_tokens"] == 1
        assert snap1["prefill_chunks"] - snap0["prefill_chunks"] == 1
        assert snap1["prefill_tokens"] - snap0["prefill_tokens"] == 4
        assert (snap1["ragged_batch_tokens"]
                - snap0["ragged_batch_tokens"]) == 5
        eng._ragged = real
        eng._ragged_fused = real_fused
        while not (a.done() and b.done()):
            eng.step()
        for h in (a, b):
            want = np.asarray(generation.generate(
                params, jnp.asarray([h.prompt], jnp.int32), cfg,
                max_new_tokens=h.max_new_tokens))[0].tolist()
            assert list(h.result(timeout=5)) == want

    @pytest.mark.parametrize("mode", ["swap", "recompute"])
    def test_chunked_preempt_resume_token_exact(self, tiny, mode):
        """Chunked prefill under page pressure: prompts longer than the
        chunk budget prefill across steps, get preempted (including
        mid-prefill victims), resume in either mode, and still match the
        offline greedy chain."""
        cfg, params = tiny
        rng = np.random.default_rng(1)
        eng = self._engine(tiny, max_seq_len=16, num_pages=5,
                           preempt_mode=mode)
        prompts = [rng.integers(0, cfg.vocab_size, 9).tolist()
                   for _ in range(3)]
        outs = eng.generate(prompts, max_new_tokens=4)
        for p, got in zip(prompts, outs):
            want = np.asarray(generation.generate(
                params, jnp.asarray([p], jnp.int32), cfg,
                max_new_tokens=4))[0].tolist()
            assert got == want
        assert eng.stats["preemptions"] >= 1
        assert eng.stats["prefill_chunks"] >= 3  # 9 tokens / budget 4
        from paddle_tpu.inference import faults as F
        F.check_invariants(eng)

    def test_recompile_sentinel_silent_after_warmup(self, tiny):
        """The bucket menu's recompile class is GONE: after the first
        (warmup) compile, a workload mixing short prompts, long chunked
        prompts, and preempt-resume drives the ONE ragged executable —
        the sentinel must not see a single post-warmup recompile."""
        cfg, params = tiny
        eng = self._engine(tiny, max_seq_len=16, num_pages=5,
                           preempt_mode="recompute")
        sent = obs.RecompileSentinel(tracer=eng.tracer,
                                     registry=obs.Registry())
        sent.watch("ragged_step", eng._ragged)
        sent.watch("ragged_step_fused", eng._ragged_fused)
        rng = np.random.default_rng(2)
        h = eng.submit(rng.integers(0, cfg.vocab_size, 2).tolist(),
                       max_new_tokens=2)
        eng.step()                       # warmup: the one compile
        assert sent.check() == {}        # baselined, silent
        handles = [h]
        for n in (7, 3, 9, 5, 11):       # mixed lengths, some > budget,
            handles.append(              # pool pressure -> preemption
                eng.submit(rng.integers(0, cfg.vocab_size, n).tolist(),
                           max_new_tokens=3))
        with warnings.catch_warnings():
            warnings.simplefilter("error", obs.RecompileWarning)
            steps = 0
            while any(not x.done() for x in handles) and steps < 500:
                eng.step()
                assert sent.check() == {}, \
                    "post-warmup recompile in the unified ragged step"
                steps += 1
        assert all(x.done() for x in handles)
        assert eng.stats["preemptions"] >= 1   # the workload DID churn
        assert sent.counts() == {"ragged_step": 0,
                                 "ragged_step_fused": 0}
