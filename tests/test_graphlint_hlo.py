"""Graph Doctor v2 tests: the HLO tier, the static memory walker, the
bucket-menu lint, `--fix` patches, `.graphlintrc`, and the baseline diff.

One seeded-bad snippet per new finding code (FUSION_BREAK,
COLLECTIVE_SEQ, LAYOUT_TRANSPOSE, MEM_PEAK, MEM_TEMP_BLOAT,
RECOMPILE_BUCKET_MISS), a clean counterpart for each, the acceptance
bound (jaxpr-tier MEM_PEAK within 2x of `compiled.memory_analysis()` on
the llama step), and — the bar — every shipped bench model lints clean
at the new codes through the full lower+compile pipeline.
"""

import importlib.util
import json
import os
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu  # noqa: F401 — x64 on, same dtype world as the library
from paddle_tpu import analysis, profiler
from paddle_tpu.analysis import Severity, hlo as hlo_lib
from paddle_tpu.analysis import memory as memory_lib


def warnings_of(report, code):
    return [f for f in report.by_code(code)
            if f.severity >= Severity.WARNING]


def _tiny_engine(**kw):
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    return LLMEngine(params, cfg, num_slots=2, page_size=4,
                     max_seq_len=16, **kw)


# ---------------------------------------------------------------------------
# FUSION_BREAK (CPU XLA fuses everything it sees, so the seeded-bad module
# is a synthetic optimized-HLO dump through the public analyze_hlo_text —
# the same text surface a TPU compile produces; the real-pipeline path is
# covered by the shipped-models test below)
# ---------------------------------------------------------------------------

_BROKEN_CHAIN_HLO = """
HloModule seeded_bad, is_scheduled=true

ENTRY %main.9 (Arg_0.1: f32[512,512]) -> f32[512,512] {
  %Arg_0.1 = f32[512,512]{1,0} parameter(0)
  %tanh.2 = f32[512,512]{1,0} tanh(f32[512,512]{1,0} %Arg_0.1)
  %multiply.3 = f32[512,512]{1,0} multiply(f32[512,512]{1,0} %tanh.2, f32[512,512]{1,0} %tanh.2)
  %tanh.4 = f32[512,512]{1,0} tanh(f32[512,512]{1,0} %multiply.3)
  %multiply.5 = f32[512,512]{1,0} multiply(f32[512,512]{1,0} %tanh.4, f32[512,512]{1,0} %tanh.4)
  %tanh.6 = f32[512,512]{1,0} tanh(f32[512,512]{1,0} %multiply.5)
  ROOT %multiply.7 = f32[512,512]{1,0} multiply(f32[512,512]{1,0} %tanh.6, f32[512,512]{1,0} %tanh.6)
}
"""

_FUSED_CHAIN_HLO = """
HloModule fused_fine, is_scheduled=true

%fused_computation (param_0.1: f32[512,512]) -> f32[512,512] {
  %param_0.1 = f32[512,512]{1,0} parameter(0)
  %tanh.2 = f32[512,512]{1,0} tanh(f32[512,512]{1,0} %param_0.1)
  %multiply.3 = f32[512,512]{1,0} multiply(f32[512,512]{1,0} %tanh.2, f32[512,512]{1,0} %tanh.2)
  %tanh.4 = f32[512,512]{1,0} tanh(f32[512,512]{1,0} %multiply.3)
  %multiply.5 = f32[512,512]{1,0} multiply(f32[512,512]{1,0} %tanh.4, f32[512,512]{1,0} %tanh.4)
  %tanh.6 = f32[512,512]{1,0} tanh(f32[512,512]{1,0} %multiply.5)
  ROOT %multiply.7 = f32[512,512]{1,0} multiply(f32[512,512]{1,0} %tanh.6, f32[512,512]{1,0} %tanh.6)
}

ENTRY %main.9 (Arg_0.1: f32[512,512]) -> f32[512,512] {
  %Arg_0.1 = f32[512,512]{1,0} parameter(0)
  ROOT %fusion = f32[512,512]{1,0} fusion(f32[512,512]{1,0} %Arg_0.1), kind=kLoop, calls=%fused_computation
}
"""


class TestFusionBreak:
    def test_unfused_chain_flagged(self):
        r = hlo_lib.analyze_hlo_text("", _BROKEN_CHAIN_HLO)
        hits = warnings_of(r, "FUSION_BREAK")
        assert hits and "UNFUSED elementwise" in hits[0].message
        assert len(hits[0].data["chain"]) >= 4

    def test_fused_chain_clean(self):
        r = hlo_lib.analyze_hlo_text("", _FUSED_CHAIN_HLO)
        assert not r.by_code("FUSION_BREAK")

    def test_small_arrays_ignored(self):
        small = _BROKEN_CHAIN_HLO.replace("512,512", "8,8")
        r = hlo_lib.analyze_hlo_text("", small)
        assert not r.by_code("FUSION_BREAK")

    def test_chain_through_barrier_ops(self):
        # pass-through ops (opt-barrier/tuple/gte) must not hide a chain
        barrier = _BROKEN_CHAIN_HLO.replace(
            "%tanh.4 = f32[512,512]{1,0} tanh(f32[512,512]{1,0} "
            "%multiply.3)",
            "%tuple.b = (f32[512,512]{1,0}) tuple(f32[512,512]{1,0} "
            "%multiply.3)\n"
            "  %opt-barrier.b = (f32[512,512]{1,0}) opt-barrier("
            "(f32[512,512]{1,0}) %tuple.b)\n"
            "  %get-tuple-element.b = f32[512,512]{1,0} get-tuple-element("
            "(f32[512,512]{1,0}) %opt-barrier.b), index=0\n"
            "  %tanh.4 = f32[512,512]{1,0} tanh(f32[512,512]{1,0} "
            "%get-tuple-element.b)")
        r = hlo_lib.analyze_hlo_text("", barrier)
        assert warnings_of(r, "FUSION_BREAK")

    def test_real_compile_pipeline_runs(self):
        # the full lower+compile path parses a real CPU module without
        # findings (CPU XLA fuses elementwise chains)
        def f(x):
            return jnp.tanh(jnp.tanh(x) * 2.0).sum()

        r = analysis.analyze_hlo(f, jnp.ones((64, 64), jnp.float32))
        assert not r.by_code("FUSION_BREAK")


# ---------------------------------------------------------------------------
# COLLECTIVE_SEQ (real lowering: shard_map psums on the 8-device CPU mesh)
# ---------------------------------------------------------------------------


class TestCollectiveSeq:
    def setup_method(self, _m):
        from jax.sharding import Mesh
        self.mesh = Mesh(np.array(jax.devices()[:8]), ("d",))

    def _shmapped(self, f):
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        return jax.jit(shard_map(f, mesh=self.mesh,
                                 in_specs=(P("d"), P("d")), out_specs=P()))

    def test_independent_psums_flagged(self):
        def f(x, y):
            return jax.lax.psum(x, "d") + jax.lax.psum(y, "d")

        g = self._shmapped(f)
        x = jnp.ones((8, 4096), jnp.float32)
        r = analysis.analyze_hlo(g, x, x, compile=False)
        hits = warnings_of(r, "COLLECTIVE_SEQ")
        assert hits and hits[0].data["count"] == 2

    def test_combined_psum_clean(self):
        def f(x, y):
            # the guaranteed single-collective form: concatenate, then
            # ONE psum (a tuple psum lowers to one all_reduce per leaf)
            s = jax.lax.psum(jnp.concatenate([x, y], axis=-1), "d")
            return s[:, :4096] + s[:, 4096:]

        g = self._shmapped(f)
        x = jnp.ones((8, 4096), jnp.float32)
        r = analysis.analyze_hlo(g, x, x, compile=False)
        assert not r.by_code("COLLECTIVE_SEQ")

    def test_dependent_psums_clean(self):
        def f(x, y):
            a = jax.lax.psum(x * y, "d")
            return jax.lax.psum(a * a, "d")    # depends on the first

        g = self._shmapped(f)
        x = jnp.ones((8, 4096), jnp.float32)
        r = analysis.analyze_hlo(g, x, x, compile=False)
        assert not r.by_code("COLLECTIVE_SEQ")

    def test_small_collectives_ignored(self):
        def f(x, y):
            return jax.lax.psum(x, "d") + jax.lax.psum(y, "d")

        g = self._shmapped(f)
        x = jnp.ones((8, 8), jnp.float32)      # 32 B/shard < 1 KiB floor
        r = analysis.analyze_hlo(g, x, x, compile=False)
        assert not r.by_code("COLLECTIVE_SEQ")


# ---------------------------------------------------------------------------
# LAYOUT_TRANSPOSE (real compile: swap+merge forces a materialized copy)
# ---------------------------------------------------------------------------


class TestLayoutTranspose:
    def test_materialized_relayout_flagged(self):
        def bad(x, w):
            t = jnp.swapaxes(x, 1, 2).reshape(64, 64 * 64)
            return (t @ w).sum()

        r = analysis.analyze_hlo(bad, jnp.ones((8, 64, 8, 64), jnp.float32),
                                 jnp.ones((4096, 8), jnp.float32))
        hits = warnings_of(r, "LAYOUT_TRANSPOSE")
        assert hits and hits[0].data["bytes"] >= 1 << 20

    def test_foldable_transpose_clean(self):
        def good(x, w):
            return (x.T @ w).sum()     # folds into dot dimension numbers

        r = analysis.analyze_hlo(good, jnp.ones((512, 512), jnp.float32),
                                 jnp.ones((512, 512), jnp.float32))
        assert not r.by_code("LAYOUT_TRANSPOSE")


# ---------------------------------------------------------------------------
# MEM_PEAK / MEM_TEMP_BLOAT (HLO tier: buffer-assignment ground truth)
# ---------------------------------------------------------------------------


class TestHloMemory:
    def test_temp_bloat_loop_flagged(self):
        def bloat(x):
            a = jnp.outer(x, x)        # 16 MiB from a 8 KiB input
            return (a @ a).sum()

        r = analysis.analyze_hlo(bloat, jnp.ones((2048,), jnp.float32))
        hits = warnings_of(r, "MEM_TEMP_BLOAT")
        assert hits and hits[0].data["temp_size_in_bytes"] > 8 << 20

    def test_flat_program_clean(self):
        def fine(x, w):
            return (x @ w).sum()

        r = analysis.analyze_hlo(fine, jnp.ones((256, 256), jnp.float32),
                                 jnp.ones((256, 256), jnp.float32))
        assert not r.by_code("MEM_TEMP_BLOAT")
        # MEM_PEAK rides along as INFO with the buffer stats
        peak = r.by_code("MEM_PEAK")
        assert peak and peak[0].data["peak_bytes"] > 0

    def test_budget_escalates_to_warning(self):
        def fine(x):
            return (x @ x).sum()

        r = analysis.analyze_hlo(fine, jnp.ones((256, 256), jnp.float32),
                                 options={"mem_peak_budget_bytes": 1024})
        assert warnings_of(r, "MEM_PEAK")


# ---------------------------------------------------------------------------
# static memory walker (jaxpr tier)
# ---------------------------------------------------------------------------


class TestStaticMemory:
    def test_donation_shrinks_peak(self):
        import functools

        def step(p, g):
            return jax.tree.map(lambda a, b: a - 0.1 * b, p, g)

        p = jnp.ones((256, 256), jnp.float32)
        est_plain = memory_lib.estimate(jax.jit(step), p, p)
        est_don = memory_lib.estimate(
            functools.partial(jax.jit(step, donate_argnums=(0,))), p, p)
        assert est_don["peak_bytes"] < est_plain["peak_bytes"]
        assert est_don["donated_bytes"] == p.nbytes

    def test_peak_attributed_to_eqn_path(self):
        def f(x):
            big = jnp.outer(x, x)          # the peak lives here
            return (big * 2.0).sum()

        est = profiler.static_memory(f, jnp.ones((512,), jnp.float32))
        assert est["peak_bytes"] >= 2 * 512 * 512 * 4
        assert "mul" in est["peak_path"] or "dot" in est["peak_path"]
        assert est["top"] and est["top"][0]["live_bytes"] <= \
            est["peak_bytes"]

    def test_scan_ys_accumulate_but_body_reuses(self):
        def f(x):
            def body(c, _):
                return c * 1.01, c.sum()
            c, ys = jax.lax.scan(body, x, None, length=100)
            return c, ys

        est = profiler.static_memory(f, jnp.ones((128, 128), jnp.float32))
        buf = 128 * 128 * 4
        # carry + stacked ys (100 scalars), NOT 100x the carry
        assert est["peak_bytes"] < 4 * buf

    def test_memory_checker_emits_info(self):
        r = analysis.analyze(lambda x: (x * 2).sum(), jnp.ones((64,)))
        peak = r.by_code("MEM_PEAK")
        assert peak and peak[0].severity == Severity.INFO

    def test_jaxpr_budget_warning(self):
        r = analysis.analyze(
            lambda x: (x * 2.0).sum(), jnp.ones((256, 256), jnp.float32),
            options={"mem_peak_budget_bytes": 1024})
        assert warnings_of(r, "MEM_PEAK")

    def test_llama_step_within_2x_of_xla(self):
        # THE acceptance bound: jaxpr-tier estimate vs compiled
        # buffer-assignment truth on the real train step
        fn, args, _extra = _graphlint.TARGETS["llama"]()
        closed = jax.make_jaxpr(fn)(*args)
        est = memory_lib.jaxpr_memory(closed)
        ma = fn.lower(*args).compile().memory_analysis()
        xla = ma.temp_size_in_bytes + ma.output_size_in_bytes
        assert xla > 0
        ratio = est.peak_bytes / xla
        assert 0.5 <= ratio <= 2.0, \
            f"estimate {est.peak_bytes} vs XLA {xla} (ratio {ratio:.2f})"


# ---------------------------------------------------------------------------
# RECOMPILE_BUCKET_MISS (deprecated menu lint — the unified ragged step
# retired the engine's bucket machinery, but the standalone lint + fix
# patch stay loadable for saved reports and rc files) and the ragged
# step's one-signature guarantee that replaced the menu
# ---------------------------------------------------------------------------


class TestBucketMenuDeprecated:
    def test_straddling_menu_flagged_with_edit(self):
        r = analysis.lint_bucket_menu([8, 16], [7, 9, 10])
        hits = warnings_of(r, "RECOMPILE_BUCKET_MISS")
        assert hits
        assert hits[0].data["suggested_menu"] == [12, 16]
        assert hits[0].data["edge"] == [8, 16]

    def test_straddle_mid_menu_keeps_top_coverage(self):
        r = analysis.lint_bucket_menu([8, 16, 32, 64], [30, 33, 35])
        hits = warnings_of(r, "RECOMPILE_BUCKET_MISS")
        assert hits and hits[0].data["edge"] == [32, 64]
        assert max(hits[0].data["suggested_menu"]) == 64

    def test_well_bucketed_workload_clean(self):
        r = analysis.lint_bucket_menu([8, 16], [5, 6, 14, 15])
        assert not r.by_code("RECOMPILE_BUCKET_MISS")

    def test_length_past_menu_flagged(self):
        r = analysis.lint_bucket_menu([8, 16], [40])
        assert warnings_of(r, "RECOMPILE_BUCKET_MISS")

    def test_engine_rejects_retired_bucket_args(self):
        # the menu kwargs are GONE, not silently ignored
        with pytest.raises(TypeError):
            _tiny_engine(prefill_buckets=[8, 16])
        with pytest.raises(TypeError):
            _tiny_engine(expected_prompt_lens=[7, 9, 10])

    def test_rcfile_suppressing_deprecated_code_still_loads(self, tmp_path):
        # old rc files naming RECOMPILE_BUCKET_MISS must not crash the
        # loader or the analyzer now that no checker emits the code
        rc = tmp_path / ".graphlintrc"
        rc.write_text('suppress = ["RECOMPILE_BUCKET_MISS"]\n'
                      '[severity]\nRECOMPILE_BUCKET_MISS = "info"\n')
        cfg = analysis.load_rcfile(str(rc))
        r = analysis.analyze(lambda x: x * 2.0, jnp.ones((8,)), config=cfg)
        assert r.ok(Severity.WARNING)


class TestRaggedOneSignature:
    def test_chunk_budget_token_exact(self):
        # chunk size is a latency/throughput knob, never a token knob
        eng_small = _tiny_engine(prefill_chunk_tokens=4, block_q=4)
        eng_default = _tiny_engine()
        prompts = [[1, 2, 3], [4, 5, 6, 7, 8, 9]]
        a = eng_small.generate(prompts, max_new_tokens=4)
        b = eng_default.generate(prompts, max_new_tokens=4)
        assert a == b

    def test_ragged_probe_single_signature(self):
        eng = _tiny_engine()
        r = analysis.analyze(eng._ragged, *eng.ragged_probe_args(),
                             options={"expected_signatures": 1})
        assert not r.by_code("RECOMPILE_SHAPE_POLY")

    def test_second_signature_fires(self):
        # the whole point of the unified step: ONE compiled signature.
        # A differently-sized batch geometry is a real second compile and
        # the shape-poly gate must see it.
        eng = _tiny_engine()
        other = _tiny_engine(prefill_chunk_tokens=16, block_q=4)
        r = analysis.analyze(
            eng._ragged, *eng.ragged_probe_args(),
            probe_args=[other.ragged_probe_args()],
            options={"expected_signatures": 1})
        assert warnings_of(r, "RECOMPILE_SHAPE_POLY")


# ---------------------------------------------------------------------------
# --fix patches
# ---------------------------------------------------------------------------


class TestFixes:
    def test_donation_fix_names_exact_argnum(self):
        @jax.jit
        def step(p, g):
            return jax.tree.map(lambda a, b: a - 0.1 * b, p, g)

        p = {"w": jnp.ones((64, 64), jnp.float32)}
        r = analysis.analyze(step, p, p,
                             options={"donation_min_bytes": 1 << 10})
        patches = analysis.fixes.suggest_fixes(r)
        don = [x for x in patches if "DONATION_MISSING" in x.codes]
        assert don and "donate_argnums=(0,)" in don[0].diff
        assert "step" in don[0].diff

    def test_multiple_argnums_one_tuple(self):
        @jax.jit
        def step(p, o, g):
            new_p = jax.tree.map(lambda a, b: a - 0.1 * b, p, g)
            return new_p, jax.tree.map(lambda a: a * 0.9, o)

        x = jnp.ones((64, 64), jnp.float32)
        r = analysis.analyze(step, x, x, x,
                             options={"donation_min_bytes": 1 << 10})
        don = [p for p in analysis.fixes.suggest_fixes(r)
               if "DONATION_MISSING" in p.codes]
        assert don and "donate_argnums=(0, 1)" in don[0].diff

    def test_bucket_fix_carries_menu_edit(self):
        r = analysis.lint_bucket_menu([8, 16], [7, 9, 10])
        patches = analysis.fixes.suggest_fixes(r)
        assert any("prefill_buckets = [12, 16]" in p.diff for p in patches)

    def test_graphlint_fix_flag_smoke(self, capsys):
        # --fix on a clean target prints nothing extra and still exits 0
        assert _graphlint.main(["engine_swap_out", "--fix"]) == 0
        out = capsys.readouterr().out
        assert "clean" in out


# ---------------------------------------------------------------------------
# .graphlintrc
# ---------------------------------------------------------------------------


class TestRcFile:
    def _bad(self):
        def bad(x):
            return (x * np.float64(2.0)).sum()
        return bad, jnp.ones((8, 8), jnp.float32)

    def test_toml_rc_suppresses(self, tmp_path):
        rc = tmp_path / ".graphlintrc"
        rc.write_text('suppress = ["DTYPE_*"]\n')
        cfg = analysis.load_rcfile(str(rc))
        fn, x = self._bad()
        r = analysis.analyze(fn, x, config=cfg)
        assert not r.by_code("DTYPE_*") and r.suppressed >= 1

    def test_json_rc_supported(self, tmp_path):
        rc = tmp_path / ".graphlintrc"
        rc.write_text(json.dumps({"suppress": ["DTYPE_*"]}))
        cfg = analysis.load_rcfile(str(rc))
        fn, x = self._bad()
        assert not analysis.analyze(fn, x, config=cfg).by_code("DTYPE_*")

    def test_severity_override_demotes(self, tmp_path):
        rc = tmp_path / ".graphlintrc"
        rc.write_text('[severity]\nDTYPE_F64_PROMOTION = "info"\n')
        cfg = analysis.load_rcfile(str(rc))
        fn, x = self._bad()
        r = analysis.analyze(fn, x, config=cfg)
        hits = r.by_code("DTYPE_F64_PROMOTION")
        assert hits and all(f.severity == Severity.INFO for f in hits)
        assert r.ok(Severity.WARNING)      # demoted below the gate

    def test_per_call_unions_with_rc(self, tmp_path):
        rc = tmp_path / ".graphlintrc"
        rc.write_text('suppress = ["COST_*"]\n')
        cfg = analysis.load_rcfile(str(rc))
        fn, x = self._bad()
        r = analysis.analyze(fn, x, config=cfg, suppress=["DTYPE_*"])
        assert not r.by_code("COST_*") and not r.by_code("DTYPE_*")

    def test_bad_severity_rejected(self, tmp_path):
        rc = tmp_path / ".graphlintrc"
        rc.write_text('[severity]\nDTYPE_F64_PROMOTION = "fatal"\n')
        with pytest.raises(ValueError, match="severity"):
            analysis.load_rcfile(str(rc))

    def test_find_rcfile_walks_up(self, tmp_path):
        (tmp_path / ".graphlintrc").write_text("suppress = []\n")
        sub = tmp_path / "a" / "b"
        sub.mkdir(parents=True)
        assert analysis.find_rcfile(str(sub)) == \
            str(tmp_path / ".graphlintrc")

    def test_shipped_rc_parses(self):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        cfg = analysis.load_rcfile(os.path.join(root, ".graphlintrc"))
        assert cfg["suppress"] == [] and cfg["severity"] == {}


# ---------------------------------------------------------------------------
# baseline diff mode
# ---------------------------------------------------------------------------


class TestBaseline:
    def test_new_code_detected(self):
        base = {"targets": {"t": {"codes": {"COST_SUMMARY": "info"}}}}
        cur = {"t": {"codes": {"COST_SUMMARY": "info",
                               "DONATION_MISSING": "warning"}}}
        news = _graphlint._baseline_diff(cur, base)
        assert news and "DONATION_MISSING" in news[0]

    def test_escalation_detected(self):
        base = {"targets": {"t": {"codes": {"MEM_PEAK": "info"}}}}
        cur = {"t": {"codes": {"MEM_PEAK": "warning"}}}
        news = _graphlint._baseline_diff(cur, base)
        assert news and "escalated" in news[0]

    def test_no_drift_passes(self):
        base = {"targets": {"t": {"codes": {"MEM_PEAK": "info"}}}}
        assert not _graphlint._baseline_diff(
            {"t": {"codes": {"MEM_PEAK": "info"}}}, base)

    def test_cli_roundtrip(self, tmp_path, capsys):
        snap = tmp_path / "base.json"
        rc = _graphlint.main(["engine_swap_out", "--write-baseline",
                              str(snap), "--json"])
        assert rc == 0 and snap.exists()
        assert _graphlint.main(["engine_swap_out", "--baseline",
                                str(snap)]) == 0
        capsys.readouterr()

    def test_shipped_baseline_has_all_targets(self):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(root, "GRAPHLINT_BASELINE.json")) as f:
            base = json.load(f)
        assert set(base["targets"]) == set(_graphlint.TARGETS)


# ---------------------------------------------------------------------------
# serving-path cost coverage (paged attention + swap gather/scatter)
# ---------------------------------------------------------------------------


class TestServingCost:
    def test_swap_gather_counts_moved_bytes_not_pool(self):
        eng = _tiny_engine()
        idx = jnp.zeros((eng.cache.pages_per_seq,), jnp.int32)
        est = profiler.static_cost(eng._swap_out, eng.cache.pools["k"],
                                   eng.cache.pools["v"], idx)
        assert est["total_bytes"] > 0
        gathers = [c for c in analysis.cost.per_eqn_costs(
            jax.make_jaxpr(eng._swap_out)(
                eng.cache.pools["k"], eng.cache.pools["v"], idx))
            if c["primitive"] == "gather"]
        assert gathers
        pool_b = eng.cache.pools["k"].nbytes
        pages_b = pool_b // eng.cache.num_pages * eng.cache.pages_per_seq
        for c in gathers:
            # pure data movement: no flops, and bytes sized to the pages
            # that MOVE (2x gathered slice + indices), not the pool sum
            assert c["flops"] == 0
            assert c["bytes"] <= 2 * pages_b + 1024

    def test_swap_scatter_counts_updates(self):
        eng = _tiny_engine()
        pool = eng.cache.pools["k"]
        idx = jnp.zeros((eng.cache.pages_per_seq,), jnp.int32)
        host = jax.ShapeDtypeStruct(
            (pool.shape[0], eng.cache.pages_per_seq) + pool.shape[2:],
            pool.dtype)
        closed = jax.make_jaxpr(eng._swap_in)(
            pool, eng.cache.pools["v"], idx, host, host)
        scatters = [c for c in analysis.cost.per_eqn_costs(closed)
                    if c["primitive"] == "scatter"]
        assert scatters
        host_b = int(np.prod(host.shape)) * np.dtype(host.dtype).itemsize
        for c in scatters:
            assert c["flops"] == 0
            assert c["bytes"] <= 3 * host_b   # 2x updates + indices

    def _ragged_pallas_costs(self, eng):
        closed = jax.make_jaxpr(eng._ragged)(*eng.ragged_probe_args())
        return [c for c in analysis.cost.per_eqn_costs(closed)
                if c["primitive"] == "pallas_call"]

    def test_ragged_attention_registered_flops_and_bytes(self):
        eng = _tiny_engine()
        pallas = self._ragged_pallas_costs(eng)
        assert pallas, "unified step lost its pallas ragged-attention eqn"
        for c in pallas:
            assert c["flops"] > 0 and c["bytes"] > 0   # registered, not 0
        # the registered bytes formula charges the pages each span's
        # row-blocks READ (span tables x page size), NOT the pool: a
        # bigger pool must not change the traffic estimate
        big = _tiny_engine(num_pages=33)
        big_pallas = self._ragged_pallas_costs(big)
        assert [c["bytes"] for c in big_pallas] == \
            [c["bytes"] for c in pallas]
        assert big.cache.pools["k"].nbytes > eng.cache.pools["k"].nbytes


# ---------------------------------------------------------------------------
# the acceptance bar: every shipped bench model stays clean at the NEW
# codes through the full lower+compile HLO tier
# ---------------------------------------------------------------------------


def _load_graphlint():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "graphlint.py")
    spec = importlib.util.spec_from_file_location("graphlint_hlo_t", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_graphlint = _load_graphlint()

NEW_CODES = ("FUSION_BREAK", "COLLECTIVE_SEQ", "LAYOUT_TRANSPOSE",
             "MEM_PEAK", "MEM_TEMP_BLOAT", "RECOMPILE_BUCKET_MISS")


@pytest.mark.parametrize("target", sorted(_graphlint.TARGETS))
def test_shipped_model_hlo_tier_clean(target):
    fn, args, extra = _graphlint.TARGETS[target]()
    report = analysis.analyze_hlo(
        fn, *args, suppress=list(_graphlint.SHIPPED_SUPPRESSIONS),
        options=extra.get("options"))
    bad = [str(f) for f in report if f.severity >= Severity.WARNING
           and f.code in NEW_CODES]
    assert not bad, f"{target} HLO tier:\n" + "\n".join(bad)
    # and the memory walker covers the target (bench tracks this number)
    jr = analysis.analyze(fn, *args, checkers=["memory"])
    assert jr.by_code("MEM_PEAK")[0].data["peak_bytes"] > 0
