"""Distributed API: collectives, auto_parallel, fleet, TP/SP layers.

All on the 8-virtual-device CPU mesh (SURVEY.md §4 test strategy).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet, mesh as mesh_lib, mp_layers


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    mesh_lib.set_global_mesh(None)


class TestCollectives:
    def test_all_reduce_values(self):
        g = dist.new_group()
        n = g.nranks
        assert n == 8
        x = np.ones((n, 2), np.float32) * np.arange(n)[:, None]
        t = paddle.to_tensor(x)
        dist.all_reduce(t, group=g)
        got = np.asarray(t.data)
        np.testing.assert_allclose(
            got, np.full((n, 2), sum(range(n)), np.float32))

    def test_all_reduce_max(self):
        g = dist.new_group()
        n = g.nranks
        x = np.arange(n, dtype=np.float32).reshape(n, 1)
        t = paddle.to_tensor(x)
        dist.all_reduce(t, op=dist.ReduceOp.MAX, group=g)
        np.testing.assert_allclose(np.asarray(t.data),
                                   np.full((n, 1), n - 1, np.float32))

    def test_all_gather(self):
        g = dist.new_group()
        n = g.nranks
        x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
        t = paddle.to_tensor(x)
        outs = []
        dist.all_gather(outs, t, group=g)
        assert len(outs) == n
        for i in range(n):
            np.testing.assert_allclose(np.asarray(outs[i].data), x[i:i+1])

    def test_reduce_scatter(self):
        g = dist.new_group()
        n = g.nranks
        x = np.ones((n * n, 2), np.float32)
        t = paddle.to_tensor(np.zeros((n, 2), np.float32))
        dist.reduce_scatter(t, paddle.to_tensor(x), group=g)
        got = np.asarray(t.data)
        np.testing.assert_allclose(got, np.full((n, 2), n, np.float32))

    def test_alltoall(self):
        g = dist.new_group()
        n = g.nranks
        x = np.arange(n * n, dtype=np.float32).reshape(n * n, 1)
        out = dist.alltoall(jnp.asarray(x), group=g)
        got = np.asarray(out).reshape(n, n)
        want = np.arange(n * n).reshape(n, n).T  # transpose of rank-block matrix
        np.testing.assert_allclose(got, want)

    def test_broadcast(self):
        g = dist.new_group()
        n = g.nranks
        x = np.arange(n * 2, dtype=np.float32).reshape(n, 2)
        t = paddle.to_tensor(x)
        dist.broadcast(t, src=3, group=g)
        got = np.asarray(t.data)
        np.testing.assert_allclose(got, np.tile(x[3:4], (n, 1)))


class TestAutoParallel:
    def test_shard_tensor_and_placements(self):
        pm = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["x", "y"])
        arr = jnp.zeros((8, 16))
        out = dist.shard_tensor(arr, pm, [dist.Shard(0), dist.Shard(1)])
        from jax.sharding import NamedSharding
        assert isinstance(out.sharding, NamedSharding)
        assert out.sharding.spec == jax.sharding.PartitionSpec("x", "y")
        pl = dist.auto_parallel.get_placements(out)
        assert pl[0] == dist.Shard(0) and pl[1] == dist.Shard(1)

    def test_reshard(self):
        pm = dist.ProcessMesh(np.arange(8), ["x"])
        arr = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)
        sharded = dist.shard_tensor(arr, pm, [dist.Shard(0)])
        repl = dist.reshard(sharded, pm, [dist.Replicate()])
        np.testing.assert_allclose(np.asarray(repl), np.asarray(arr))
        assert not [a for a in repl.sharding.spec if a is not None]

    def test_shard_tensor_on_paddle_tensor(self):
        pm = dist.ProcessMesh(np.arange(8), ["x"])
        t = paddle.to_tensor(np.zeros((8, 2), np.float32))
        out = dist.shard_tensor(t, pm, [dist.Shard(0)])
        assert out is t
        assert "x" in str(t.data.sharding.spec)


class TestFleet:
    def test_init_topology_groups(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 1, "sharding_degree": 2}
        hcg = fleet.init(is_collective=True, strategy=strategy)
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_sharding_parallel_world_size() == 2
        g = hcg.get_model_parallel_group()
        assert g is not None and g.nranks == 2
        assert mesh_lib.get_global_mesh() is not None

    def test_init_default_pure_dp(self):
        hcg = fleet.init(is_collective=True)
        assert hcg.get_data_parallel_world_size() == 8


class TestMPLayers:
    def _fleet_tp4(self):
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
        return fleet.init(strategy=s)

    def test_column_row_roundtrip_matches_dense(self):
        self._fleet_tp4()
        paddle.seed(0)
        col = mp_layers.ColumnParallelLinear(16, 32, gather_output=False,
                                             has_bias=True)
        row = mp_layers.RowParallelLinear(32, 16, input_is_parallel=True,
                                          has_bias=True)
        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
        y = row(col(x))
        # dense reference with the same weights
        W1 = np.asarray(col.weight.data)
        b1 = np.asarray(col.bias.data)
        W2 = np.asarray(row.weight.data)
        b2 = np.asarray(row.bias.data)
        want = (np.asarray(x.data) @ W1 + b1) @ W2 + b2
        np.testing.assert_allclose(np.asarray(y.data), want, rtol=1e-5, atol=1e-5)

    def test_vocab_parallel_embedding(self):
        self._fleet_tp4()
        emb = mp_layers.VocabParallelEmbedding(64, 16)
        ids = paddle.to_tensor(np.random.randint(0, 64, (2, 8)))
        out = emb(ids)
        assert out.shape == [2, 8, 16]

    def test_parallel_cross_entropy(self):
        self._fleet_tp4()
        ce = mp_layers.ParallelCrossEntropy()
        logits = paddle.to_tensor(np.random.randn(2, 8, 64).astype(np.float32),
                                  stop_gradient=False)
        labels = paddle.to_tensor(np.random.randint(0, 64, (2, 8)))
        loss = ce(logits, labels)
        assert np.isfinite(np.asarray(loss.data)).all()

    def test_sequence_parallel_linears(self):
        self._fleet_tp4()
        col = mp_layers.ColumnSequenceParallelLinear(16, 32, gather_output=False)
        row = mp_layers.RowSequenceParallelLinear(32, 16, input_is_parallel=True)
        x = paddle.to_tensor(np.random.randn(8, 2, 16).astype(np.float32))  # (S,B,E)
        x = mp_layers.ScatterOp(x, axis=0)
        y = row(col(x))
        assert y.shape == [8, 2, 16]

    def test_rng_tracker(self):
        mp_layers.model_parallel_random_seed(1234)
        tr = mp_layers.get_rng_state_tracker()
        with tr.rng_state("global_seed"):
            a = paddle.randn([4])
        with tr.rng_state("global_seed"):
            b = paddle.randn([4])
        # continuing the same stream -> different draws
        assert not np.allclose(np.asarray(a.data), np.asarray(b.data))


class TestZeroShardSpec:
    def test_adds_axis_first_divisible(self):
        from jax.sharding import PartitionSpec as P
        mesh = mesh_lib.make_mesh(data=2, sharding=4)
        spec = mesh_lib.zero_shard_spec(P(None, None), (8, 6), mesh)
        assert spec == P("sharding", None)
        spec2 = mesh_lib.zero_shard_spec(P(None, None), (6, 8), mesh)
        assert spec2 == P(None, "sharding")
        spec3 = mesh_lib.zero_shard_spec(P(None,), (7,), mesh)
        assert spec3 == P(None,)


class TestContextParallel:
    """Ring/Ulysses attention over the sep axis — the capability the reference
    reserved (topology.py:63 'sep') but never implemented (SURVEY.md §5)."""

    def _data(self, B=4, S=64, Hq=8, Hkv=4, D=16):
        rng = np.random.default_rng(7)
        import jax.numpy as jnp
        q = jnp.asarray(rng.normal(size=(B, S, Hq, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32)
        return q, k, v

    def test_ring_matches_reference(self):
        import jax.numpy as jnp
        from paddle_tpu.distributed.context_parallel import context_parallel_attention
        from paddle_tpu.kernels import attention_reference
        mesh = mesh_lib.make_mesh(data=2, sep=4)
        q, k, v = self._data()
        ref = attention_reference(q, k, v, causal=True)
        out = context_parallel_attention(q, k, v, mesh=mesh, impl="ring", causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_ulysses_matches_reference(self):
        from paddle_tpu.distributed.context_parallel import context_parallel_attention
        from paddle_tpu.kernels import attention_reference
        mesh = mesh_lib.make_mesh(sep=8)
        q, k, v = self._data()
        ref = attention_reference(q, k, v, causal=True)
        out = context_parallel_attention(q, k, v, mesh=mesh, impl="ulysses", causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_ring_gradients(self):
        import jax
        from paddle_tpu.distributed.context_parallel import context_parallel_attention
        from paddle_tpu.kernels import attention_reference
        mesh = mesh_lib.make_mesh(sep=4)
        q, k, v = self._data(B=2, S=32, Hq=4, Hkv=4, D=8)
        g = jax.grad(lambda q, k, v: context_parallel_attention(
            q, k, v, mesh=mesh, impl="ring", causal=True).sum(), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: attention_reference(
            q, k, v, causal=True).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)

    def test_llama_train_step_with_sep_axis(self):
        """e2e: ShardedTrainState on a dp2 x sep4 mesh auto-enables ring attention."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig
        from paddle_tpu.distributed.parallelize import ShardedTrainState
        from paddle_tpu.optimizer.functional import AdamW
        mesh = mesh_lib.make_mesh(data=2, sep=4)
        cfg = LlamaConfig.tiny()
        st = ShardedTrainState(cfg, llama, mesh, AdamW(learning_rate=1e-3),
                               zero_stage=1)
        assert st.config.context_parallel == "ring"
        params, opt = st.init(jax.random.PRNGKey(0))
        toks = np.random.default_rng(3).integers(0, cfg.vocab_size, (4, 33))
        batch = st.shard_batch(llama.lm_batch_from_tokens(jnp.asarray(toks, jnp.int32)))
        params, opt, m = st.step(params, opt, batch)
        assert np.isfinite(float(m["loss"]))

    def test_sep_loss_matches_single_device(self):
        """Ring-attention training loss == single-device loss (same init/batch)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig
        cfg = LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        toks = np.random.default_rng(3).integers(0, cfg.vocab_size, (2, 33))
        batch = llama.lm_batch_from_tokens(jnp.asarray(toks, jnp.int32))
        base = float(llama.loss_fn(params, batch, cfg))
        mesh = mesh_lib.make_mesh(sep=4)
        mesh_lib.set_global_mesh(mesh)
        try:
            import dataclasses
            cfg_cp = dataclasses.replace(cfg, context_parallel="ring")
            cp = float(llama.loss_fn(params, batch, cfg_cp))
        finally:
            mesh_lib.set_global_mesh(None)
        np.testing.assert_allclose(cp, base, rtol=1e-5)


class TestPipelineParallel:
    """Single-jit microbatch pipeline over the pipe axis (C27 analog)."""

    def test_pipeline_apply_matches_scan(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.pipeline import pipeline_apply
        rng = np.random.default_rng(0)
        L, B, S, E = 8, 8, 16, 32
        W = jnp.asarray(rng.normal(size=(L, E, E)) * 0.1, jnp.float32)
        bb = jnp.asarray(rng.normal(size=(L, E)) * 0.1, jnp.float32)
        x = jnp.asarray(rng.normal(size=(B, S, E)), jnp.float32)

        def block(h, lp):
            w, b = lp
            return jnp.tanh(h @ w + b)

        ref = x
        for i in range(L):
            ref = block(ref, (W[i], bb[i]))
        mesh = mesh_lib.make_mesh(data=2, pipe=4)
        out = pipeline_apply(block, (W, bb), x, mesh=mesh, n_micro=4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_llama_pipeline_loss_matches_single_device(self):
        import jax
        import jax.numpy as jnp
        import dataclasses
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig
        cfg = LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        toks = np.random.default_rng(3).integers(0, cfg.vocab_size, (4, 33))
        batch = llama.lm_batch_from_tokens(jnp.asarray(toks, jnp.int32))
        base = float(llama.loss_fn(params, batch, cfg))
        mesh = mesh_lib.make_mesh(data=2, pipe=2, model=2)
        cfg_pp = dataclasses.replace(cfg, mesh=mesh, pp_microbatches=2)
        pp = float(llama.loss_fn(params, batch, cfg_pp))
        np.testing.assert_allclose(pp, base, rtol=1e-5)

    def test_train_step_4d_hybrid(self):
        """dp x pp x tp train step through ShardedTrainState."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig
        from paddle_tpu.distributed.parallelize import ShardedTrainState
        from paddle_tpu.optimizer.functional import AdamW
        mesh = mesh_lib.make_mesh(data=2, pipe=2, model=2)
        cfg = LlamaConfig.tiny()
        st = ShardedTrainState(cfg, llama, mesh, AdamW(learning_rate=1e-3),
                               zero_stage=1)
        params, opt = st.init(jax.random.PRNGKey(0))
        toks = np.random.default_rng(3).integers(0, cfg.vocab_size, (8, 33))
        batch = st.shard_batch(llama.lm_batch_from_tokens(jnp.asarray(toks, jnp.int32)))
        l0 = None
        for _ in range(3):
            params, opt, m = st.step(params, opt, batch)
            l0 = l0 or float(m["loss"])
        assert np.isfinite(float(m["loss"]))
        assert float(m["loss"]) < l0
