"""Distributed API: collectives, auto_parallel, fleet, TP/SP layers.

All on the 8-virtual-device CPU mesh (SURVEY.md §4 test strategy).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet, mesh as mesh_lib, mp_layers


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    mesh_lib.set_global_mesh(None)


class TestCollectives:
    def test_all_reduce_values(self):
        g = dist.new_group()
        n = g.nranks
        assert n == 8
        x = np.ones((n, 2), np.float32) * np.arange(n)[:, None]
        t = paddle.to_tensor(x)
        dist.all_reduce(t, group=g)
        got = np.asarray(t.data)
        np.testing.assert_allclose(
            got, np.full((n, 2), sum(range(n)), np.float32))

    def test_all_reduce_max(self):
        g = dist.new_group()
        n = g.nranks
        x = np.arange(n, dtype=np.float32).reshape(n, 1)
        t = paddle.to_tensor(x)
        dist.all_reduce(t, op=dist.ReduceOp.MAX, group=g)
        np.testing.assert_allclose(np.asarray(t.data),
                                   np.full((n, 1), n - 1, np.float32))

    def test_all_gather(self):
        g = dist.new_group()
        n = g.nranks
        x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
        t = paddle.to_tensor(x)
        outs = []
        dist.all_gather(outs, t, group=g)
        assert len(outs) == n
        for i in range(n):
            np.testing.assert_allclose(np.asarray(outs[i].data), x[i:i+1])

    def test_reduce_scatter(self):
        g = dist.new_group()
        n = g.nranks
        x = np.ones((n * n, 2), np.float32)
        t = paddle.to_tensor(np.zeros((n, 2), np.float32))
        dist.reduce_scatter(t, paddle.to_tensor(x), group=g)
        got = np.asarray(t.data)
        np.testing.assert_allclose(got, np.full((n, 2), n, np.float32))

    def test_alltoall(self):
        g = dist.new_group()
        n = g.nranks
        x = np.arange(n * n, dtype=np.float32).reshape(n * n, 1)
        out = dist.alltoall(jnp.asarray(x), group=g)
        got = np.asarray(out).reshape(n, n)
        want = np.arange(n * n).reshape(n, n).T  # transpose of rank-block matrix
        np.testing.assert_allclose(got, want)

    def test_broadcast(self):
        g = dist.new_group()
        n = g.nranks
        x = np.arange(n * 2, dtype=np.float32).reshape(n, 2)
        t = paddle.to_tensor(x)
        dist.broadcast(t, src=3, group=g)
        got = np.asarray(t.data)
        np.testing.assert_allclose(got, np.tile(x[3:4], (n, 1)))


class TestAutoParallel:
    def test_shard_tensor_and_placements(self):
        pm = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["x", "y"])
        arr = jnp.zeros((8, 16))
        out = dist.shard_tensor(arr, pm, [dist.Shard(0), dist.Shard(1)])
        from jax.sharding import NamedSharding
        assert isinstance(out.sharding, NamedSharding)
        assert out.sharding.spec == jax.sharding.PartitionSpec("x", "y")
        pl = dist.auto_parallel.get_placements(out)
        assert pl[0] == dist.Shard(0) and pl[1] == dist.Shard(1)

    def test_reshard(self):
        pm = dist.ProcessMesh(np.arange(8), ["x"])
        arr = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)
        sharded = dist.shard_tensor(arr, pm, [dist.Shard(0)])
        repl = dist.reshard(sharded, pm, [dist.Replicate()])
        np.testing.assert_allclose(np.asarray(repl), np.asarray(arr))
        assert not [a for a in repl.sharding.spec if a is not None]

    def test_shard_tensor_on_paddle_tensor(self):
        pm = dist.ProcessMesh(np.arange(8), ["x"])
        t = paddle.to_tensor(np.zeros((8, 2), np.float32))
        out = dist.shard_tensor(t, pm, [dist.Shard(0)])
        assert out is t
        assert "x" in str(t.data.sharding.spec)


class TestFleet:
    def test_init_topology_groups(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 1, "sharding_degree": 2}
        hcg = fleet.init(is_collective=True, strategy=strategy)
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_sharding_parallel_world_size() == 2
        g = hcg.get_model_parallel_group()
        assert g is not None and g.nranks == 2
        assert mesh_lib.get_global_mesh() is not None

    def test_init_default_pure_dp(self):
        hcg = fleet.init(is_collective=True)
        assert hcg.get_data_parallel_world_size() == 8


class TestMPLayers:
    def _fleet_tp4(self):
        s = fleet.DistributedStrategy()
        s.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
        return fleet.init(strategy=s)

    def test_column_row_roundtrip_matches_dense(self):
        self._fleet_tp4()
        paddle.seed(0)
        col = mp_layers.ColumnParallelLinear(16, 32, gather_output=False,
                                             has_bias=True)
        row = mp_layers.RowParallelLinear(32, 16, input_is_parallel=True,
                                          has_bias=True)
        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
        y = row(col(x))
        # dense reference with the same weights
        W1 = np.asarray(col.weight.data)
        b1 = np.asarray(col.bias.data)
        W2 = np.asarray(row.weight.data)
        b2 = np.asarray(row.bias.data)
        want = (np.asarray(x.data) @ W1 + b1) @ W2 + b2
        np.testing.assert_allclose(np.asarray(y.data), want, rtol=1e-5, atol=1e-5)

    def test_vocab_parallel_embedding(self):
        self._fleet_tp4()
        emb = mp_layers.VocabParallelEmbedding(64, 16)
        ids = paddle.to_tensor(np.random.randint(0, 64, (2, 8)))
        out = emb(ids)
        assert out.shape == [2, 8, 16]

    def test_parallel_cross_entropy(self):
        self._fleet_tp4()
        ce = mp_layers.ParallelCrossEntropy()
        logits = paddle.to_tensor(np.random.randn(2, 8, 64).astype(np.float32),
                                  stop_gradient=False)
        labels = paddle.to_tensor(np.random.randint(0, 64, (2, 8)))
        loss = ce(logits, labels)
        assert np.isfinite(np.asarray(loss.data)).all()

    def test_sequence_parallel_linears(self):
        self._fleet_tp4()
        col = mp_layers.ColumnSequenceParallelLinear(16, 32, gather_output=False)
        row = mp_layers.RowSequenceParallelLinear(32, 16, input_is_parallel=True)
        x = paddle.to_tensor(np.random.randn(8, 2, 16).astype(np.float32))  # (S,B,E)
        x = mp_layers.ScatterOp(x, axis=0)
        y = row(col(x))
        assert y.shape == [8, 2, 16]

    def test_rng_tracker(self):
        mp_layers.model_parallel_random_seed(1234)
        tr = mp_layers.get_rng_state_tracker()
        with tr.rng_state("global_seed"):
            a = paddle.randn([4])
        with tr.rng_state("global_seed"):
            b = paddle.randn([4])
        # continuing the same stream -> different draws
        assert not np.allclose(np.asarray(a.data), np.asarray(b.data))


class TestZeroShardSpec:
    def test_adds_axis_first_divisible(self):
        from jax.sharding import PartitionSpec as P
        mesh = mesh_lib.make_mesh(data=2, sharding=4)
        spec = mesh_lib.zero_shard_spec(P(None, None), (8, 6), mesh)
        assert spec == P("sharding", None)
        spec2 = mesh_lib.zero_shard_spec(P(None, None), (6, 8), mesh)
        assert spec2 == P(None, "sharding")
        spec3 = mesh_lib.zero_shard_spec(P(None,), (7,), mesh)
        assert spec3 == P(None,)


class TestContextParallel:
    """Ring/Ulysses attention over the sep axis — the capability the reference
    reserved (topology.py:63 'sep') but never implemented (SURVEY.md §5)."""

    def _data(self, B=4, S=64, Hq=8, Hkv=4, D=16):
        rng = np.random.default_rng(7)
        import jax.numpy as jnp
        q = jnp.asarray(rng.normal(size=(B, S, Hq, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32)
        return q, k, v

    def test_ring_matches_reference(self):
        import jax.numpy as jnp
        from paddle_tpu.distributed.context_parallel import context_parallel_attention
        from paddle_tpu.kernels import attention_reference
        mesh = mesh_lib.make_mesh(data=2, sep=4)
        q, k, v = self._data()
        ref = attention_reference(q, k, v, causal=True)
        out = context_parallel_attention(q, k, v, mesh=mesh, impl="ring", causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_ulysses_matches_reference(self):
        from paddle_tpu.distributed.context_parallel import context_parallel_attention
        from paddle_tpu.kernels import attention_reference
        mesh = mesh_lib.make_mesh(sep=8)
        q, k, v = self._data()
        ref = attention_reference(q, k, v, causal=True)
        out = context_parallel_attention(q, k, v, mesh=mesh, impl="ulysses", causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_ring_with_document_mask(self):
        """Custom (S, S) masks compose with the ring: rows shard with q,
        columns slice per ring step (previously rejected outright)."""
        import jax.numpy as jnp
        from paddle_tpu.distributed.context_parallel import context_parallel_attention
        from paddle_tpu.kernels import attention_reference
        mesh = mesh_lib.make_mesh(sep=4)
        q, k, v = self._data()
        S = q.shape[1]
        # block-diagonal document mask: two docs of S/2 tokens
        doc = np.arange(S) // (S // 2)
        keep = jnp.asarray(doc[:, None] == doc[None, :])
        ref = attention_reference(q, k, v, causal=True,
                                  mask=keep[None, None])
        for impl in ("ring", "ulysses"):
            out = context_parallel_attention(q, k, v, mesh=mesh, impl=impl,
                                             causal=True, mask=keep)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-5, err_msg=impl)
        # additive float masks too
        add = jnp.where(keep, 0.0, -1e9).astype(jnp.float32)
        out = context_parallel_attention(q, k, v, mesh=mesh, impl="ring",
                                         causal=True, mask=add)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
        # batched masks are still rejected with a clear error
        with pytest.raises(ValueError, match=r"\(S, S\) mask"):
            context_parallel_attention(q, k, v, mesh=mesh, causal=True,
                                       mask=jnp.ones((2, 1, S, S), bool))

    def test_fully_masked_rows_agree_across_impls_and_encodings(self):
        """Degenerate (fully-masked) rows return 0 — identically for bool
        and additive (-1e9) masks, in both the ring and the local kernel
        (ADVICE r3: three different behaviors previously)."""
        import jax.numpy as jnp
        from paddle_tpu.distributed.context_parallel import context_parallel_attention
        from paddle_tpu.kernels import attention_reference
        mesh = mesh_lib.make_mesh(sep=4)
        q, k, v = self._data()
        S = q.shape[1]
        keep = jnp.ones((S, S), bool).at[5, :].set(False)  # row 5: no keys
        add = jnp.where(keep, 0.0, -1e9).astype(jnp.float32)
        outs = [attention_reference(q, k, v, mask=keep[None, None]),
                attention_reference(q, k, v, mask=add[None, None]),
                context_parallel_attention(q, k, v, mesh=mesh, impl="ring",
                                           causal=False, mask=keep),
                context_parallel_attention(q, k, v, mesh=mesh, impl="ring",
                                           causal=False, mask=add)]
        for i, o in enumerate(outs):
            arr = np.asarray(o)
            assert np.isfinite(arr).all(), f"impl {i} produced NaN"
            np.testing.assert_allclose(arr[:, 5], 0.0, atol=1e-6,
                                       err_msg=f"impl {i}")
            np.testing.assert_allclose(arr, np.asarray(outs[0]), atol=2e-5,
                                       err_msg=f"impl {i}")

    def test_mask_inside_enclosing_shard_map(self):
        """The manual-axes path takes LOCAL mask chunks — (S/n, S) rows for
        ring — and must not trip the global square-shape check."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed._shard_map_compat import shard_map
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.distributed.context_parallel import context_parallel_attention
        from paddle_tpu.kernels import attention_reference
        mesh = mesh_lib.make_mesh(sep=4)
        q, k, v = self._data(B=2, S=32, Hq=4, Hkv=4, D=8)
        S = q.shape[1]
        doc = np.arange(S) // (S // 2)
        keep = jnp.asarray(doc[:, None] == doc[None, :])
        spec = P(None, "sep", None, None)

        def local(q_, k_, v_, m_):
            return context_parallel_attention(q_, k_, v_, causal=True,
                                              mask=m_)

        out = shard_map(local, mesh=mesh,
                        in_specs=(spec, spec, spec, P("sep", None)),
                        out_specs=spec, check_vma=False)(q, k, v, keep)
        ref = attention_reference(q, k, v, causal=True, mask=keep[None, None])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    @pytest.mark.slow
    def test_ring_mask_gradients(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.context_parallel import context_parallel_attention
        from paddle_tpu.kernels import attention_reference
        mesh = mesh_lib.make_mesh(sep=4)
        q, k, v = self._data(B=2, S=32, Hq=4, Hkv=4, D=8)
        S = q.shape[1]
        doc = np.arange(S) // (S // 4)
        keep = jnp.asarray(doc[:, None] == doc[None, :])
        g = jax.grad(lambda q, k, v: context_parallel_attention(
            q, k, v, mesh=mesh, impl="ring", causal=True,
            mask=keep).sum(), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: attention_reference(
            q, k, v, causal=True,
            mask=keep[None, None]).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)

    def test_ring_gradients(self):
        import jax
        from paddle_tpu.distributed.context_parallel import context_parallel_attention
        from paddle_tpu.kernels import attention_reference
        mesh = mesh_lib.make_mesh(sep=4)
        q, k, v = self._data(B=2, S=32, Hq=4, Hkv=4, D=8)
        g = jax.grad(lambda q, k, v: context_parallel_attention(
            q, k, v, mesh=mesh, impl="ring", causal=True).sum(), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: attention_reference(
            q, k, v, causal=True).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)

    @pytest.mark.slow
    def test_llama_train_step_with_sep_axis(self):
        """e2e: ShardedTrainState on a dp2 x sep4 mesh auto-enables ring attention."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig
        from paddle_tpu.distributed.parallelize import ShardedTrainState
        from paddle_tpu.optimizer.functional import AdamW
        mesh = mesh_lib.make_mesh(data=2, sep=4)
        cfg = LlamaConfig.tiny()
        st = ShardedTrainState(cfg, llama, mesh, AdamW(learning_rate=1e-3),
                               zero_stage=1)
        assert st.config.context_parallel == "ring"
        params, opt = st.init(jax.random.PRNGKey(0))
        toks = np.random.default_rng(3).integers(0, cfg.vocab_size, (4, 33))
        batch = st.shard_batch(llama.lm_batch_from_tokens(jnp.asarray(toks, jnp.int32)))
        params, opt, m = st.step(params, opt, batch)
        assert np.isfinite(float(m["loss"]))

    @pytest.mark.slow
    def test_sep_loss_matches_single_device(self):
        """Ring-attention training loss == single-device loss (same init/batch)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig
        cfg = LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        toks = np.random.default_rng(3).integers(0, cfg.vocab_size, (2, 33))
        batch = llama.lm_batch_from_tokens(jnp.asarray(toks, jnp.int32))
        base = float(llama.loss_fn(params, batch, cfg))
        mesh = mesh_lib.make_mesh(sep=4)
        mesh_lib.set_global_mesh(mesh)
        try:
            import dataclasses
            cfg_cp = dataclasses.replace(cfg, context_parallel="ring")
            cp = float(llama.loss_fn(params, batch, cfg_cp))
        finally:
            mesh_lib.set_global_mesh(None)
        np.testing.assert_allclose(cp, base, rtol=1e-5)


class TestPipelineParallel:
    """Single-jit microbatch pipeline over the pipe axis (C27 analog)."""

    def test_pipeline_apply_matches_scan(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.pipeline import pipeline_apply
        rng = np.random.default_rng(0)
        L, B, S, E = 8, 8, 16, 32
        W = jnp.asarray(rng.normal(size=(L, E, E)) * 0.1, jnp.float32)
        bb = jnp.asarray(rng.normal(size=(L, E)) * 0.1, jnp.float32)
        x = jnp.asarray(rng.normal(size=(B, S, E)), jnp.float32)

        def block(h, lp):
            w, b = lp
            return jnp.tanh(h @ w + b)

        ref = x
        for i in range(L):
            ref = block(ref, (W[i], bb[i]))
        mesh = mesh_lib.make_mesh(data=2, pipe=4)
        out = pipeline_apply(block, (W, bb), x, mesh=mesh, n_micro=4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    @pytest.mark.slow
    def test_llama_pipeline_loss_matches_single_device(self):
        import jax
        import jax.numpy as jnp
        import dataclasses
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig
        cfg = LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        toks = np.random.default_rng(3).integers(0, cfg.vocab_size, (4, 33))
        batch = llama.lm_batch_from_tokens(jnp.asarray(toks, jnp.int32))
        base = float(llama.loss_fn(params, batch, cfg))
        mesh = mesh_lib.make_mesh(data=2, pipe=2, model=2)
        cfg_pp = dataclasses.replace(cfg, mesh=mesh, pp_microbatches=2)
        pp = float(llama.loss_fn(params, batch, cfg_pp))
        np.testing.assert_allclose(pp, base, rtol=1e-5)

    @pytest.mark.slow
    def test_native_bf16_tp_pp_cpu_bug_still_present(self):
        """Pin for VERDICT r3 weak #6: bf16 tp x pp numerics have never
        executed as bf16 anywhere but TPU, because XLA's CPU SPMD
        partitioner CHECK-FAILS (hard abort) on them — which is why
        pipeline._cpu_needs_f32 upcasts the CPU harness.  This test
        re-runs the native composition in a SUBPROCESS (the abort kills
        the process, an in-process xfail cannot catch it).  The day the
        child EXITS 0, the upstream bug is fixed: delete
        FORCE_NATIVE_DTYPE_ON_CPU/_cpu_needs_f32 and run the bf16 parity
        suite natively."""
        import subprocess
        import sys

        child = (
            "import os\n"
            "os.environ['JAX_PLATFORMS']='cpu'\n"
            "os.environ['XLA_FLAGS']="
            "'--xla_force_host_platform_device_count=8'\n"
            "import dataclasses\n"
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "import jax.numpy as jnp\n"
            "import numpy as np\n"
            "from paddle_tpu.distributed import mesh as mesh_lib\n"
            "from paddle_tpu.distributed import pipeline as pipe_lib\n"
            "from paddle_tpu.distributed.parallelize import "
            "ShardedTrainState\n"
            "from paddle_tpu.models import llama\n"
            "from paddle_tpu.models.llama import LlamaConfig\n"
            "from paddle_tpu.optimizer.functional import AdamW\n"
            "pipe_lib.FORCE_NATIVE_DTYPE_ON_CPU = True\n"
            "mesh = mesh_lib.make_mesh(pipe=2, model=2)\n"
            "cfg = dataclasses.replace(LlamaConfig.tiny(), "
            "dtype=jnp.bfloat16)\n"
            "st = ShardedTrainState(cfg, llama, mesh, "
            "AdamW(learning_rate=1e-3))\n"
            "params, opt = st.init(jax.random.PRNGKey(0))\n"
            "toks = np.random.default_rng(0).integers(0, cfg.vocab_size, "
            "(4, 33))\n"
            "batch = st.shard_batch(llama.lm_batch_from_tokens("
            "jnp.asarray(toks, jnp.int32)))\n"
            "params, opt, m = st.step(params, opt, batch)\n"
            "assert np.isfinite(float(m['loss']))\n"
            "print('NATIVE_BF16_OK')\n")
        r = subprocess.run([sys.executable, "-c", child],
                           capture_output=True, text=True, timeout=600)
        if r.returncode == 0 and "NATIVE_BF16_OK" in r.stdout:
            pytest.fail(
                "native bf16 tp x pp now WORKS on the CPU partitioner — "
                "the XLA bug is fixed; remove pipeline._cpu_needs_f32 / "
                "FORCE_NATIVE_DTYPE_ON_CPU and enable native bf16 parity "
                "tests")
        # the child must have died of the PINNED bug, not of test rot
        # (a python traceback would mean this pin broke and passes
        # vacuously forever)
        assert "Traceback (most recent call last)" not in r.stderr, (
            f"bf16 pin child broke for an unrelated reason:\n"
            f"{r.stderr[-2000:]}")

    def test_seq_leaves_override(self):
        """seq_leaves names the sequence leaves explicitly: a (B, C) soft
        target stops being mis-sharded over the sep axis (ADVICE r3)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig
        from paddle_tpu.distributed.parallelize import ShardedTrainState
        from paddle_tpu.optimizer.functional import AdamW
        mesh = mesh_lib.make_mesh(sep=2, data=2)
        cfg = LlamaConfig.tiny()
        st = ShardedTrainState(cfg, llama, mesh, AdamW(learning_rate=1e-3),
                               seq_leaves={"input_ids", "labels"})
        batch = {
            "input_ids": np.zeros((4, 32), np.int32),
            "labels": np.zeros((4, 32), np.int32),
            "soft_targets": np.zeros((4, 3), np.float32),  # dim1 != seq
        }
        sharded = st.shard_batch(batch)
        spec_ids = sharded["input_ids"].sharding.spec
        spec_soft = sharded["soft_targets"].sharding.spec
        assert "sep" in str(spec_ids), spec_ids
        assert "sep" not in str(spec_soft), spec_soft

    @pytest.mark.slow
    def test_train_step_4d_hybrid(self):
        """dp x pp x tp train step through ShardedTrainState."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig
        from paddle_tpu.distributed.parallelize import ShardedTrainState
        from paddle_tpu.optimizer.functional import AdamW
        mesh = mesh_lib.make_mesh(data=2, pipe=2, model=2)
        cfg = LlamaConfig.tiny()
        st = ShardedTrainState(cfg, llama, mesh, AdamW(learning_rate=1e-3),
                               zero_stage=1)
        params, opt = st.init(jax.random.PRNGKey(0))
        toks = np.random.default_rng(3).integers(0, cfg.vocab_size, (8, 33))
        batch = st.shard_batch(llama.lm_batch_from_tokens(jnp.asarray(toks, jnp.int32)))
        l0 = None
        for _ in range(3):
            params, opt, m = st.step(params, opt, batch)
            l0 = l0 or float(m["loss"])
        assert np.isfinite(float(m["loss"]))
        assert float(m["loss"]) < l0


class TestZeroStages:
    """ZeRO 0/1/2/3 — reference: fleet/meta_parallel/sharding/
    group_sharded_optimizer_stage2.py:53 and group_sharded_stage3.py:59."""

    def _train(self, zero_stage, steps=3):
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig
        from paddle_tpu.distributed.parallelize import ShardedTrainState
        from paddle_tpu.optimizer.functional import AdamW
        mesh = mesh_lib.make_mesh(data=2, sharding=4)
        cfg = LlamaConfig.tiny()
        st = ShardedTrainState(cfg, llama, mesh, AdamW(learning_rate=1e-3),
                               zero_stage=zero_stage)
        params, opt = st.init(jax.random.PRNGKey(0))
        toks = np.random.default_rng(7).integers(0, cfg.vocab_size, (8, 33))
        batch = st.shard_batch(
            llama.lm_batch_from_tokens(jnp.asarray(toks, jnp.int32)))
        losses = []
        for _ in range(steps):
            params, opt, m = st.step(params, opt, batch)
            losses.append(float(m["loss"]))
        return st, params, opt, losses

    def test_invalid_stage_rejected(self):
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig
        from paddle_tpu.distributed.parallelize import ShardedTrainState
        mesh = mesh_lib.make_mesh(data=2, sharding=4)
        with pytest.raises(ValueError, match="zero_stage"):
            ShardedTrainState(LlamaConfig.tiny(), llama, mesh, zero_stage=4)

    @pytest.mark.slow
    def test_loss_parity_across_stages(self):
        ref = self._train(0)[3]
        for stage in (1, 2, 3):
            got = self._train(stage)[3]
            np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    @pytest.mark.slow
    def test_stage3_param_memory_inverse_n(self):
        """Stage-3 stored params occupy ~1/N of stage-0 bytes per device."""
        def local_bytes(tree):
            return sum(
                x.addressable_shards[0].data.size * x.dtype.itemsize
                for x in jax.tree.leaves(tree))

        _, p0, o0, _ = self._train(0, steps=1)
        _, p3, o3, _ = self._train(3, steps=1)
        n = 4  # sharding axis size
        b0, b3 = local_bytes(p0), local_bytes(p3)
        assert b3 < b0 / (n / 2), f"params not sharded: {b0} -> {b3}"
        m0 = local_bytes(o0.m) + local_bytes(o0.v) + local_bytes(o0.master)
        m3 = local_bytes(o3.m) + local_bytes(o3.v) + local_bytes(o3.master)
        assert m3 < m0 / (n / 2), f"opt state not sharded: {m0} -> {m3}"

    def test_stage2_constrains_grads(self):
        """Stage >= 2 pins every gradient leaf to the zero-sharded layout
        (the reduce-scatter form is then the TPU partitioner's lowering; the
        CPU backend keeps all-reduce+slice, so assert on the constraint)."""
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig
        from paddle_tpu.distributed.parallelize import ShardedTrainState
        mesh = mesh_lib.make_mesh(data=2, sharding=4)
        st1 = ShardedTrainState(LlamaConfig.tiny(), llama, mesh, zero_stage=1)
        st2 = ShardedTrainState(LlamaConfig.tiny(), llama, mesh, zero_stage=2)
        assert st1._grad_shardings is None
        assert st2._grad_shardings is not None
        specs = {s.spec for s in jax.tree.leaves(st2._grad_shardings)}
        assert any("sharding" in str(sp) for sp in specs)


class TestPipelineSchedules:
    """1F1B + interleaved schedules — reference pipeline_parallel.py:387,822."""

    def _llama_setup(self):
        import dataclasses
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig
        cfg = LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        toks = np.random.default_rng(5).integers(0, cfg.vocab_size, (4, 33))
        batch = llama.lm_batch_from_tokens(jnp.asarray(toks, jnp.int32))
        return dataclasses, llama, cfg, params, batch

    def test_bf16_tp_pp_mesh_trains(self):
        """Regression: bf16 + tensor x pipeline mesh hard-crashed XLA's CPU
        SPMD partitioner ('Invalid binary instruction opcode copy'); the
        pipeline now runs its CPU harness in f32 (TPU keeps bf16)."""
        import dataclasses
        from paddle_tpu.models import llama
        from paddle_tpu.models.llama import LlamaConfig
        from paddle_tpu.distributed.parallelize import ShardedTrainState
        from paddle_tpu.optimizer.functional import AdamW

        cfg = LlamaConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=4, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=32,
            pp_microbatches=2, dtype=jnp.bfloat16)
        mesh = mesh_lib.make_mesh(model=2, pipe=2)
        losses = {}
        for sched in (None, "1f1b"):
            c = dataclasses.replace(cfg, pp_schedule=sched)
            st = ShardedTrainState(c, llama, mesh, AdamW(learning_rate=1e-3),
                                   zero_stage=1)
            params, opt = st.init(jax.random.PRNGKey(0))
            toks = np.random.default_rng(0).integers(0, 256, (4, 33))
            batch = st.shard_batch(llama.lm_batch_from_tokens(
                jnp.asarray(toks, jnp.int32)))
            params, opt, m = st.step(params, opt, batch)
            losses[sched] = float(m["loss"])
            assert np.isfinite(losses[sched])
        np.testing.assert_allclose(losses[None], losses["1f1b"], rtol=5e-2)

    @pytest.mark.slow
    def test_interleaved_forward_parity(self):
        dc, llama, cfg, params, batch = self._llama_setup()
        mesh = mesh_lib.make_mesh(pipe=2)
        # tiny() has 2 layers; interleave needs L % (P*V) == 0 -> V=1 w/ P=2
        # use a 4-layer config for V=2
        cfg4 = dc.replace(cfg, num_hidden_layers=4)
        params4 = llama.init_params(cfg4, jax.random.PRNGKey(0))
        base4 = float(llama.loss_fn(params4, batch, cfg4))
        cfg_v = dc.replace(cfg4, mesh=mesh, pp_microbatches=2,
                           pp_virtual_stages=2)
        got = float(llama.loss_fn(params4, batch, cfg_v))
        np.testing.assert_allclose(got, base4, rtol=1e-5)

    @pytest.mark.slow
    def test_1f1b_loss_and_grads_parity(self):
        dc, llama, cfg, params, batch = self._llama_setup()
        loss_ref, grads_ref = jax.value_and_grad(llama.loss_fn)(
            params, batch, cfg)
        mesh = mesh_lib.make_mesh(pipe=2, model=2)
        cfg_pp = dc.replace(cfg, mesh=mesh, pp_microbatches=2,
                            pp_schedule="1f1b")
        loss, grads = llama.loss_and_grads(params, batch, cfg_pp)
        np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
        flat_r, _ = jax.tree_util.tree_flatten(grads_ref)
        flat_g, _ = jax.tree_util.tree_flatten(grads)
        for a, b in zip(flat_g, flat_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    @pytest.mark.slow
    def test_1f1b_stash_bounded_by_stages(self):
        """The 1F1B activation stash is (P, ...) — independent of n_micro."""
        from paddle_tpu.distributed import pipeline as pipe
        mesh = mesh_lib.make_mesh(pipe=4)
        mesh_lib.set_global_mesh(mesh)
        rng = np.random.default_rng(0)
        L, Dm, B = 4, 8, 16
        Ws = jnp.asarray(rng.standard_normal((L, Dm, Dm)) * 0.3, jnp.float32)
        x = jnp.asarray(rng.standard_normal((B, Dm)), jnp.float32)
        lbl = jnp.asarray(rng.integers(0, Dm, (B,)), jnp.int32)

        def block(h, W):
            return jnp.tanh(h @ W)

        def head(y, hp, lb):
            ll = jnp.take_along_axis(jax.nn.log_softmax(y @ hp),
                                     lb[..., None], axis=-1)
            return -jnp.sum(ll) / B

        Wh = jnp.asarray(rng.standard_normal((Dm, Dm)) * 0.3, jnp.float32)
        P_ = 4

        def scan_carry_avals(jaxpr):
            found = []

            def walk(jpr):
                for eqn in jpr.eqns:
                    if eqn.primitive.name == "scan":
                        nc = eqn.params["num_carry"]
                        found.append([v.aval for v in eqn.invars[
                            eqn.params["num_consts"]:
                            eqn.params["num_consts"] + nc]])
                    for val in eqn.params.values():
                        leaves = jax.tree.leaves(
                            val, is_leaf=lambda x: hasattr(x, "eqns")
                            or hasattr(x, "jaxpr"))
                        for sub in leaves:
                            if hasattr(sub, "jaxpr"):   # ClosedJaxpr
                                walk(sub.jaxpr)
                            elif hasattr(sub, "eqns"):  # Jaxpr
                                walk(sub)
            walk(jaxpr.jaxpr)
            return found

        for M in (8, 16):
            jaxpr = jax.make_jaxpr(
                lambda Ws, Wh, x, M=M: pipe.pipeline_1f1b(
                    block, head, Ws, Wh, x, lbl, mesh=mesh, n_micro=M,
                    remat=False))(Ws, Wh, x)
            carries = scan_carry_avals(jaxpr)
            assert carries, "no scan found in 1F1B jaxpr"
            ticks = max(carries, key=len)  # the tick scan has the big carry
            mb_elems = (B // M) * Dm
            # activation-sized carries: stash (P, mb), act/grad wires (mb),
            # and the M-sized IO buffer dxb.  Nothing else may scale with M.
            m_sized = [a for a in ticks
                       if a.shape and int(np.prod(a.shape)) >= M * mb_elems
                       and a.shape[0] == M]
            assert len(m_sized) == 1, f"extra M-sized carries: {m_sized}"
            stash = [a for a in ticks if a.shape and a.shape[0] == P_
                     and int(np.prod(a.shape)) == P_ * mb_elems]
            assert stash, "stash buffer not (P, ...)-shaped"
        # loss parity across M while stash stays (P, ...)
        l4 = pipe.pipeline_1f1b(block, head, Ws, Wh, x, lbl, mesh=mesh,
                                n_micro=4, remat=False)[0]
        l16 = pipe.pipeline_1f1b(block, head, Ws, Wh, x, lbl, mesh=mesh,
                                 n_micro=16, remat=False)[0]
        np.testing.assert_allclose(float(l4), float(l16), rtol=1e-5)

    @pytest.mark.slow
    def test_moe_llama_trains_under_pipeline(self):
        """MoE + pipeline — the pairing the reference rejects (llama.py:285
        analog removed this round)."""
        import dataclasses
        from paddle_tpu.models import moe_llama
        from paddle_tpu.models.moe_llama import MoELlamaConfig
        from paddle_tpu.distributed.parallelize import ShardedTrainState
        from paddle_tpu.optimizer.functional import AdamW
        mesh = mesh_lib.make_mesh(pipe=2, extra_axes={"expert": 2})
        cfg = MoELlamaConfig.tiny()
        st = ShardedTrainState(cfg, moe_llama, mesh, AdamW(learning_rate=1e-3))
        params, opt = st.init(jax.random.PRNGKey(0))
        toks = np.random.default_rng(9).integers(0, cfg.vocab_size, (8, 17))
        batch = st.shard_batch(
            moe_llama.lm_batch_from_tokens(jnp.asarray(toks, jnp.int32)))
        l0 = None
        for _ in range(3):
            params, opt, m = st.step(params, opt, batch)
            l0 = l0 or float(m["loss"])
        assert np.isfinite(float(m["loss"]))
        assert float(m["loss"]) < l0

    @pytest.mark.slow
    def test_moe_llama_1f1b(self):
        import dataclasses
        from paddle_tpu.models import moe_llama
        from paddle_tpu.models.moe_llama import MoELlamaConfig
        cfg = MoELlamaConfig.tiny()
        params = moe_llama.init_params(cfg, jax.random.PRNGKey(0))
        toks = np.random.default_rng(11).integers(0, cfg.vocab_size, (4, 17))
        batch = moe_llama.lm_batch_from_tokens(jnp.asarray(toks, jnp.int32))
        # MoE routes per microbatch under a pipeline, so the reference is
        # the GPipe-pipelined loss (same microbatching), grads by AD through
        # the wavefront scan — 1F1B must reproduce them exactly
        mesh = mesh_lib.make_mesh(pipe=2)
        cfg_gp = dataclasses.replace(cfg, mesh=mesh, pp_microbatches=2)
        loss_ref, grads_ref = jax.value_and_grad(moe_llama.loss_fn)(
            params, batch, cfg_gp)
        cfg_pp = dataclasses.replace(cfg_gp, pp_schedule="1f1b")
        loss, grads = moe_llama.loss_and_grads(params, batch, cfg_pp)
        np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
        flat_r, _ = jax.tree_util.tree_flatten(grads_ref)
        flat_g, _ = jax.tree_util.tree_flatten(grads)
        for a, b in zip(flat_g, flat_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)
