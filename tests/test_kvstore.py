"""Tiered KV prefix store (inference/kvstore.py): host-RAM tier under
the radix prefix index, with optional disk spill.

Covers the PR-17 tiered-store satellite: store-level put/get semantics
(byte-exact copies, idempotent demotion, LRU capacity with spill-or-
drop), the engine demote/promote round trip being BYTE-exact in the
device pool, disk spill surviving a process restart (fresh store
reopened on the same directory still serves a token-exact splice), and
`_recover_pools` invalidating only the device tier — host copies were
taken while the KV was live, so they stay warm."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.inference import LLMEngine
from paddle_tpu.inference import faults as F
from paddle_tpu.inference.kvstore import KVHandoff, TieredPrefixStore
from paddle_tpu.models import generation, llama
from paddle_tpu.models.llama import LlamaConfig


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _engine(params, cfg, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("prefill_chunk_tokens", 4)
    kw.setdefault("block_q", 2)
    return LLMEngine(params, cfg, **kw)


def _ref_tokens(params, cfg, prompt, n):
    return np.asarray(generation.generate(
        params, jnp.asarray([prompt], jnp.int32), cfg,
        max_new_tokens=n))[0].tolist()


class TestStoreUnit:
    def test_put_get_byte_exact_and_isolated(self):
        store = TieredPrefixStore()
        rng = np.random.default_rng(0)
        k = rng.standard_normal((2, 4, 8)).astype(np.float32)
        v = rng.standard_normal((2, 4, 8)).astype(np.float32)
        assert store.put((1, 2, 3, 4), k, v)
        # the store copied: mutating the caller's buffer after put must
        # not corrupt the cached page
        k_orig = k.copy()
        k[:] = -1.0
        got_k, got_v = store.get([1, 2, 3, 4])
        assert np.array_equal(got_k, k_orig)
        assert np.array_equal(got_v, v)
        assert store.hits == 1 and store.promoted_pages == 1

    def test_put_is_idempotent(self):
        store = TieredPrefixStore()
        k = np.ones((2, 4), np.float32)
        assert store.put((9, 9, 9, 9), k, k)
        assert not store.put((9, 9, 9, 9), k, k)
        assert len(store) == 1 and store.demoted_pages == 1

    def test_miss_counts_and_returns_none(self):
        store = TieredPrefixStore()
        assert store.get((5, 5)) is None
        assert store.misses == 1 and store.hits == 0

    def test_lru_capacity_drops_oldest_without_spill_dir(self):
        page = np.ones((4, 4), np.float32)          # 64 bytes each
        store = TieredPrefixStore(capacity_bytes=3 * 2 * page.nbytes)
        for i in range(4):
            store.put((i, i, i, i), page, page)
        # oldest entry dropped (no disk tier), newest three retained
        assert not store.contains((0, 0, 0, 0))
        assert all(store.contains((i, i, i, i)) for i in (1, 2, 3))
        assert store.resident_bytes <= 3 * 2 * page.nbytes

    def test_get_refreshes_lru_order(self):
        page = np.ones((4, 4), np.float32)
        store = TieredPrefixStore(capacity_bytes=2 * 2 * page.nbytes)
        store.put((0,) * 4, page, page)
        store.put((1,) * 4, page, page)
        assert store.get((0,) * 4) is not None     # touch: 0 is now MRU
        store.put((2,) * 4, page, page)
        assert store.contains((0,) * 4)
        assert not store.contains((1,) * 4)

    def test_spill_to_disk_past_capacity(self, tmp_path):
        page = np.arange(16, dtype=np.float32).reshape(4, 4)
        store = TieredPrefixStore(capacity_bytes=0,
                                  spill_dir=str(tmp_path))
        store.put((3, 1, 4, 1), page, 2 * page)
        snap = store.snapshot()
        assert snap["ram_pages"] == 0 and snap["disk_pages"] == 1
        assert snap["spilled_pages"] == 1
        got_k, got_v = store.get((3, 1, 4, 1))
        assert np.array_equal(got_k, page)
        assert np.array_equal(got_v, 2 * page)
        assert store.loaded_pages == 1

    def test_reopened_store_reindexes_spill(self, tmp_path):
        page = np.full((2, 4), 7.0, np.float32)
        a = TieredPrefixStore(capacity_bytes=0, spill_dir=str(tmp_path))
        a.put((8, 6, 7, 5), page, page)
        # "process restart": a FRESH store on the same directory
        b = TieredPrefixStore(spill_dir=str(tmp_path))
        assert b.contains((8, 6, 7, 5))
        got_k, _ = b.get((8, 6, 7, 5))
        assert np.array_equal(got_k, page)

    def test_clear_removes_ram_and_disk(self, tmp_path):
        page = np.ones((2, 4), np.float32)
        store = TieredPrefixStore(capacity_bytes=0,
                                  spill_dir=str(tmp_path))
        store.put((1, 1, 1, 1), page, page)
        store.put((2, 2, 2, 2), page, page)
        store.clear()
        assert len(store) == 0
        assert not list(tmp_path.glob("kvp_*.npz"))

    def test_first_chunks_needs_page_size(self):
        store = TieredPrefixStore()
        page = np.ones((2, 4), np.float32)
        store.put((1, 2, 3, 4), page, page)
        store.put((1, 2, 3, 4, 5, 6, 7, 8), page, page)
        assert store.first_chunks() == ()        # no page_size stamped
        store.page_size = 4
        assert store.first_chunks() == ((1, 2, 3, 4),)

    def test_handoff_nbytes_counts_real_pages_only(self):
        hk = np.zeros((2, 8, 4, 2, 16), np.float32)   # 8-page staging
        h = KVHandoff([1, 2, 3], 8, 2, hk, hk.copy())
        per_page = 2 * hk.nbytes // 8
        assert h.nbytes == 2 * per_page
        assert KVHandoff([1], 0, 0, None, None).nbytes == 0


class TestEngineTier:
    def test_demote_promote_round_trip_byte_exact(self, tiny):
        """LRU eviction gathers the dying pages' KV to the host tier;
        the next admission of the same prompt promotes them back — and
        the promoted device pages hold bit-identical KV, proven by
        comparing pool contents across the round trip (token-exactness
        alone would survive small numeric drift; the tier must not
        introduce ANY)."""
        cfg, params = tiny
        store = TieredPrefixStore()
        eng = _engine(params, cfg, kvstore=store)
        prompt = list(range(1, 10))
        ref = _ref_tokens(params, cfg, prompt, 2)
        assert eng.generate([prompt], max_new_tokens=2)[0] == ref
        probe = np.asarray(prompt + [0], np.int32)
        matched, pages = eng.prefix_index.lookup(probe, len(prompt))
        assert matched >= eng.cache.page_size and pages
        pool_k = np.asarray(eng.cache.pools["k"])
        saved = {p: pool_k[:, p].copy() for p in pages}
        evicted = eng.prefix_index.evict(10 ** 6)
        assert evicted == len(pages)
        assert eng.stats["kv_demoted_pages"] >= 2
        assert store.demoted_pages == evicted
        # same prompt again: page-aligned promotion through _swap_in
        assert eng.generate([prompt], max_new_tokens=2)[0] == ref
        assert eng.stats["kv_promoted_pages"] >= 2
        assert eng.stats["prefix_tier_hits"] >= 1
        m2, pages2 = eng.prefix_index.lookup(probe, len(prompt))
        assert m2 == matched
        pool_k2 = np.asarray(eng.cache.pools["k"])
        for old, new in zip(pages, pages2):
            assert np.array_equal(pool_k2[:, new], saved[old])

    def test_disk_spill_survives_process_restart(self, tiny, tmp_path):
        """capacity_bytes=0 forces every demotion straight to disk; a
        FRESH store reopened on the same spill_dir, attached to a FRESH
        engine, must serve a token-exact spliced admission — cached
        prefixes outlive the process."""
        cfg, params = tiny
        prompt = list(range(2, 12))
        ref = _ref_tokens(params, cfg, prompt, 3)
        store = TieredPrefixStore(capacity_bytes=0,
                                  spill_dir=str(tmp_path))
        eng = _engine(params, cfg, kvstore=store)
        assert eng.generate([prompt], max_new_tokens=3)[0] == ref
        eng.prefix_index.evict(10 ** 6)
        assert store.snapshot()["disk_pages"] >= 2
        # restart: new store, new engine, same directory
        store2 = TieredPrefixStore(spill_dir=str(tmp_path))
        eng2 = _engine(params, cfg, kvstore=store2)
        assert eng2.generate([prompt], max_new_tokens=3)[0] == ref
        assert eng2.stats["kv_promoted_pages"] >= 2
        assert store2.loaded_pages >= 2
        assert eng2.stats["prefix_hits"] >= 1
        F.check_invariants(eng2)

    def test_recover_pools_leaves_host_tier_intact(self, tiny):
        """Pool recovery must clear the DEVICE index (its pages now hold
        zeroed KV) but never the host tier — those copies were gathered
        while the KV was live, and re-warming from them is the whole
        point of a tiered store."""
        cfg, params = tiny
        store = TieredPrefixStore()
        eng = _engine(params, cfg, kvstore=store)
        prompt = list(range(1, 10))
        ref = _ref_tokens(params, cfg, prompt, 2)
        assert eng.generate([prompt], max_new_tokens=2)[0] == ref
        eng.prefix_index.evict(10 ** 6)
        host_keys = set(store.keys())
        assert host_keys
        eng.cache.pools["k"].delete()
        eng.cache.pools["v"].delete()
        assert eng._recover_pools(RuntimeError("boom"))
        assert eng.prefix_index.cached_pages == 0
        assert set(store.keys()) == host_keys
        # the recovered engine warms straight from the host tier
        assert eng.generate([prompt], max_new_tokens=2)[0] == ref
        assert eng.stats["kv_promoted_pages"] >= 2
        F.check_invariants(eng)

    def test_attach_rejects_page_size_mismatch(self, tiny):
        cfg, params = tiny
        store = TieredPrefixStore(page_size=8)
        with pytest.raises(ValueError, match="page_size"):
            _engine(params, cfg, kvstore=store)       # engine uses 4

    def test_scripted_engine_demotes_and_promotes(self):
        """The tier also runs under ScriptedEngines (opaque 1-D KV
        stubs) — that is what lets the chaos soaks exercise it at
        chaos-suite speed."""
        store = TieredPrefixStore()
        eng = F.ScriptedEngine(num_slots=2, page_size=4, max_seq_len=16,
                               prefill_chunk_tokens=4, block_q=2,
                               kvstore=store)
        prompt = [5, 6, 7, 8, 9, 1, 2]
        ref = F.ScriptedEngine.reference_tokens(prompt, 3)
        assert eng.generate([prompt], max_new_tokens=3)[0] == ref
        eng.prefix_index.evict(10 ** 6)
        assert len(store) >= 1
        assert eng.generate([prompt], max_new_tokens=3)[0] == ref
        assert eng.stats["kv_promoted_pages"] >= 1
        F.check_invariants(eng)
