"""Graph Doctor tier 6 tests: the Pallas kernel verifier.

Seeded-bad kernels per finding code (OOB index map, uncovered /
overlapping output coverage, dead pl.when cells, VMEM overflow at a
tiny fake budget, low-precision accumulators, scratch/output dtype
mismatch), the shipped-kernel sweep staying clean at >= WARNING, the
`vmem_bytes` export the autotuner will prune sweep points with, and THE
acceptance bar: a corrupted generated kernel injected under the rewrite
tier is rejected by the re-lint gate and rolled back.  The satellite
mechanics ride along: the cost-table longest-match regression and the
baseline loader's warn-not-crash tolerance of the v5 kernels section.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import paddle_tpu  # noqa: F401 — x64 on, same dtype world as the library
from paddle_tpu import analysis
from paddle_tpu.analysis import Finding, Report, Severity, kernellint
from paddle_tpu.analysis.core import iter_eqns

_0 = np.int32(0)


class _Ctx:
    """Minimal CheckContext stand-in: just the options kernellint reads."""

    def __init__(self, **opts):
        self._opts = opts

    def opt(self, key, default=None):
        return self._opts.get(key, default)


def _lint(fn, *args, **opts):
    closed = jax.make_jaxpr(fn)(*args)
    out = []
    for eqn, path, _w in iter_eqns(closed):
        if eqn.primitive.name == "pallas_call":
            out.extend(kernellint.lint_pallas_eqn(eqn, path, _Ctx(**opts)))
    return out


def _codes(findings, min_sev=Severity.WARNING):
    return sorted({f.code for f in findings if f.severity >= min_sev})


def _copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def _call(kernel, in_maps, out_map, grid=(2,), block=(128, 128),
          arr=(256, 128), dtype=jnp.float32, out_shape=None,
          scratch=()):
    return pl.pallas_call(
        kernel, grid=grid,
        in_specs=[pl.BlockSpec(block, m) for m in in_maps],
        out_specs=pl.BlockSpec(block, out_map),
        out_shape=jax.ShapeDtypeStruct(out_shape or arr, dtype),
        scratch_shapes=list(scratch), interpret=True)


# ---------------------------------------------------------------------------
# seeded-bad fixtures: one kernel per finding code
# ---------------------------------------------------------------------------


class TestSeededBad:
    def test_oob_index_map(self):
        """`i + 1` overruns the last block: a definite, attained OOB."""
        x = jnp.zeros((256, 128), jnp.float32)
        f = _call(_copy_kernel, [lambda i: (i + 1, _0)], lambda i: (i, _0))
        fs = _lint(f, x)
        assert _codes(fs) == ["KERNEL_OOB_BLOCK"]
        (bad,) = [f for f in fs if f.code == "KERNEL_OOB_BLOCK"]
        assert bad.severity == Severity.ERROR
        assert bad.data["index_hi"] == 2 and bad.data["nblocks"] == 2

    def test_oob_negative_index(self):
        x = jnp.zeros((256, 128), jnp.float32)
        f = _call(_copy_kernel, [lambda i: (i - 1, _0)], lambda i: (i, _0))
        assert "KERNEL_OOB_BLOCK" in _codes(_lint(f, x))

    def test_uncovered_constant_output_row(self):
        """A constant output index writes 1 of 2 blocks — the other row
        of blocks is never written."""
        x = jnp.zeros((256, 128), jnp.float32)
        f = _call(_copy_kernel, [lambda i: (i, _0)], lambda i: (_0, _0))
        fs = _lint(f, x)
        (bad,) = [f for f in fs if f.code == "KERNEL_OUT_UNCOVERED"]
        assert bad.severity == Severity.ERROR

    def test_uncovered_grid_too_short(self):
        """grid=(1,) over a 2-block output: block 1 never written."""
        x = jnp.zeros((256, 128), jnp.float32)
        f = _call(_copy_kernel, [lambda i: (_0, _0)], lambda i: (i, _0),
                  grid=(1,), block=(128, 128), arr=(256, 128))
        fs = [f for f in _lint(f, x) if f.code == "KERNEL_OUT_UNCOVERED"]
        assert fs and "never written" in fs[0].message

    def test_overlap_non_consecutive_revisit(self):
        """The output ignores grid dim 0 while dim 1 (inner) is used:
        revisits of the same output block are non-consecutive, so the
        accumulate-then-flush idiom cannot apply."""
        x = jnp.zeros((128, 128), jnp.float32)
        f = pl.pallas_call(
            _copy_kernel, grid=(2, 2),
            in_specs=[pl.BlockSpec((64, 64), lambda i, j: (i, j))],
            out_specs=pl.BlockSpec((64, 64), lambda i, j: (_0, j)),
            out_shape=jax.ShapeDtypeStruct((64, 128), jnp.float32),
            interpret=True)
        assert "KERNEL_OUT_OVERLAP" in _codes(_lint(f, x))

    def test_trailing_reduce_dim_is_assumption_not_overlap(self):
        """The accumulate idiom itself — unused TRAILING grid dim — must
        NOT warn (every shipped matmul-style kernel uses it)."""
        x = jnp.zeros((128, 128), jnp.float32)
        f = pl.pallas_call(
            _copy_kernel, grid=(2, 2),
            in_specs=[pl.BlockSpec((64, 64), lambda i, j: (i, j))],
            out_specs=pl.BlockSpec((64, 64), lambda i, j: (i, _0)),
            out_shape=jax.ShapeDtypeStruct((128, 64), jnp.float32),
            interpret=True)
        fs = _lint(f, x)
        assert "KERNEL_OUT_OVERLAP" not in _codes(fs)
        assume = [f for f in fs if f.code == "KERNEL_ASSUME"]
        assert assume and "accumulate" in assume[0].data["assumptions"][-1]

    def test_dead_grid_cell(self):
        """A pl.when predicate statically false on EVERY grid cell."""
        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

            @pl.when(pl.program_id(0) < 0)
            def _():
                o_ref[...] = x_ref[...] * 2

        x = jnp.zeros((256, 128), jnp.float32)
        f = _call(kernel, [lambda i: (i, _0)], lambda i: (i, _0))
        fs = [f for f in _lint(f, x) if f.code == "KERNEL_DEAD_GRID_CELL"]
        assert fs and fs[0].severity == Severity.WARNING

    def test_live_when_is_not_flagged(self):
        """`pl.when(i == 0)` runs on SOME cell — no finding (the shipped
        ragged/gmm kernels' first-visit init idiom)."""
        def kernel(x_ref, o_ref):
            @pl.when(pl.program_id(0) == 0)
            def _():
                o_ref[...] = jnp.zeros_like(o_ref)
            o_ref[...] += x_ref[...]

        x = jnp.zeros((256, 128), jnp.float32)
        f = _call(kernel, [lambda i: (i, _0)], lambda i: (_0, _0),
                  out_shape=(128, 128))
        assert "KERNEL_DEAD_GRID_CELL" not in _codes(_lint(f, x))

    def test_vmem_overflow_at_tiny_budget(self):
        """The same kernel passes at the real chip budget and overflows
        at a seeded 1 KiB budget — the static OOM predictor."""
        x = jnp.zeros((256, 128), jnp.float32)
        f = _call(_copy_kernel, [lambda i: (i, _0)], lambda i: (i, _0))
        assert _codes(_lint(f, x)) == []
        fs = _lint(f, x, kernellint_vmem_budget_bytes=1024)
        (bad,) = [f for f in fs if f.code == "KERNEL_VMEM_OVERFLOW"]
        assert bad.severity == Severity.WARNING
        assert bad.data["vmem_bytes"] > 1024

    def test_lowp_accum_dot(self):
        """bf16 x bf16 dot accumulating in bf16 (no f32 accumulator)."""
        def kernel(a_ref, b_ref, o_ref):
            o_ref[...] = jnp.dot(a_ref[...], b_ref[...])

        xb = jnp.zeros((128, 128), jnp.bfloat16)
        f = pl.pallas_call(
            kernel, grid=(1,),
            in_specs=[pl.BlockSpec((128, 128), lambda i: (_0, _0))] * 2,
            out_specs=pl.BlockSpec((128, 128), lambda i: (_0, _0)),
            out_shape=jax.ShapeDtypeStruct((128, 128), jnp.bfloat16),
            interpret=True)
        fs = [f for f in _lint(f, xb, xb) if f.code == "KERNEL_LOWP_ACCUM"]
        assert fs and "preferred_element_type" in fs[0].suggestion

    def test_lowp_accum_scratch_running_sum(self):
        """A bf16 scratch ref read AND written across grid steps — a
        running sum losing mantissa."""
        def kernel(x_ref, o_ref, acc_ref):
            acc_ref[...] = acc_ref[...] + x_ref[...].astype(jnp.bfloat16)
            o_ref[...] = acc_ref[...]

        x = jnp.zeros((256, 128), jnp.float32)
        f = _call(kernel, [lambda i: (i, _0)], lambda i: (_0, _0),
                  dtype=jnp.bfloat16, out_shape=(128, 128),
                  scratch=[pltpu.VMEM((128, 128), jnp.bfloat16)])
        assert "KERNEL_LOWP_ACCUM" in _codes(_lint(f, x))

    def test_dtype_mismatch_scratch_narrower_than_output(self):
        """bf16 scratch feeding an f32 output: the output precision is
        laundered, not computed."""
        def kernel(x_ref, o_ref, acc_ref):
            acc_ref[...] = x_ref[...].astype(jnp.bfloat16)
            o_ref[...] = acc_ref[...].astype(jnp.float32)

        x = jnp.zeros((256, 128), jnp.float32)
        f = _call(kernel, [lambda i: (i, _0)], lambda i: (i, _0),
                  scratch=[pltpu.VMEM((128, 128), jnp.bfloat16)])
        assert "KERNEL_DTYPE_MISMATCH" in _codes(_lint(f, x))

    def test_f32_scratch_is_clean(self):
        """The blessed pattern — f32 scratch accumulator, cast on the
        final flush — produces no dtype findings."""
        def kernel(x_ref, o_ref, acc_ref):
            acc_ref[...] = acc_ref[...] + x_ref[...].astype(jnp.float32)
            o_ref[...] = acc_ref[...].astype(jnp.bfloat16)

        x = jnp.zeros((256, 128), jnp.bfloat16)
        f = _call(kernel, [lambda i: (i, _0)], lambda i: (_0, _0),
                  dtype=jnp.bfloat16, out_shape=(128, 128),
                  scratch=[pltpu.VMEM((128, 128), jnp.float32)])
        assert _codes(_lint(f, x)) == []


# ---------------------------------------------------------------------------
# the interval evaluator's exactness on the shipped index-map shapes
# ---------------------------------------------------------------------------


class TestIntervalProofs:
    def test_floordiv_mod_maps_prove_exact(self):
        """The flash dkv shape — `b*r + t//nq` and `t % nq` — must be
        proven in-bounds EXACTLY (no assumption fallback): the pjit
        floor_divide/remainder special cases carry attainment."""
        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        _r, _nq = np.int32(2), np.int32(2)
        x = jnp.zeros((4, 2, 128), jnp.float32)
        f = pl.pallas_call(
            kernel, grid=(2, 4),
            in_specs=[pl.BlockSpec(
                (1, 1, 128),
                lambda b, t: (b * _r + t // _nq, t % _nq, _0))],
            out_specs=pl.BlockSpec(
                (1, 1, 128),
                lambda b, t: (b * _r + t // _nq, t % _nq, _0)),
            out_shape=jax.ShapeDtypeStruct((4, 2, 128), jnp.float32),
            interpret=True)
        fs = _lint(f, x)
        assert "KERNEL_OOB_BLOCK" not in _codes(fs)
        # no in-bounds assumptions either: the proof is exact
        assume = [a for f in fs if f.code == "KERNEL_ASSUME"
                  for a in f.data["assumptions"] if "in-bounds" in a]
        assert assume == []

    def test_floordiv_overrun_is_caught(self):
        """The same shape with a grid one step too long: `b // r` walks
        past the last block and the OOB endpoint is attained."""
        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        _r = np.int32(2)
        x = jnp.zeros((2, 128), jnp.float32)
        f = pl.pallas_call(
            kernel, grid=(6,),
            in_specs=[pl.BlockSpec((1, 128), lambda b: (b // _r, _0))],
            out_specs=pl.BlockSpec((1, 128), lambda b: (b // _r, _0)),
            out_shape=jax.ShapeDtypeStruct((2, 128), jnp.float32),
            interpret=True)
        assert "KERNEL_OOB_BLOCK" in _codes(_lint(f, x))

    def test_prefetch_index_is_assumed_not_flagged(self):
        """Data-dependent block indices (the paged page-table load) are
        an ASSUMPTION, never an OOB error — the caller's invariant."""
        reports = kernellint.analyze_kernels(["paged_attention"])
        rep = reports["pallas_paged_attention._paged_kernel"]
        assert rep.ok(Severity.WARNING)
        assume = [a for f in rep.findings if f.code == "KERNEL_ASSUME"
                  for a in f.data["assumptions"]]
        assert any("prefetch" in a for a in assume)


# ---------------------------------------------------------------------------
# the shipped-kernel sweep: everything we ship proves clean
# ---------------------------------------------------------------------------


SHIPPED = sorted(kernellint.shipped_kernel_targets())


class TestShippedKernels:
    @pytest.mark.parametrize("target", SHIPPED)
    def test_shipped_kernel_is_clean(self, target):
        """THE bar: all seven shipped kernels (backward kernels included
        via grad traces) AND a generated fused-chain kernel carry zero
        >= WARNING findings."""
        reports = kernellint.analyze_kernels([target])
        assert reports, f"{target}: no pallas_call found"
        for kid, rep in reports.items():
            bad = [str(f) for f in rep if f.severity >= Severity.WARNING]
            assert rep.ok(Severity.WARNING), \
                f"{kid} has kernel findings:\n" + "\n".join(bad)

    def test_generated_chain_is_covered(self):
        """The generated fused_chain target exercises the SAME emission
        path the rewrite tier uses (fused_elementwise_chain)."""
        reports = kernellint.analyze_kernels(["fused_chain"])
        assert "pallas_fused_chain.fused_chain" in reports

    def test_every_kernel_reports_a_footprint(self):
        reports = kernellint.analyze_kernels()
        assert len(reports) >= 8    # 7 shipped modules' kernels + chain
        for kid, rep in reports.items():
            fp = [f for f in rep.findings
                  if f.code == "KERNEL_VMEM_FOOTPRINT"]
            assert fp, f"{kid}: no footprint finding"
            assert fp[0].data["vmem_bytes"] > 0

    def test_unknown_target_raises(self):
        with pytest.raises(ValueError, match="unknown kernel target"):
            kernellint.analyze_kernels(["nope"])

    def test_registered_checker_runs_inside_analyze(self):
        """The tier rides every analyze() call: kernels reached through
        a model trace get the same findings (INFO footprint here)."""
        from paddle_tpu.kernels.pallas_norm import rms_norm_pallas

        x = jnp.zeros((64, 128), jnp.float32)
        w = jnp.ones((128,), jnp.float32)
        rep = analysis.analyze(rms_norm_pallas, x, w)
        assert "kernellint" in analysis.list_checkers()
        fp = [f for f in rep.findings if f.code == "KERNEL_VMEM_FOOTPRINT"]
        assert fp and fp[0].severity == Severity.INFO


# ---------------------------------------------------------------------------
# the vmem_bytes export (the autotuner's sweep-point pruner)
# ---------------------------------------------------------------------------


class TestVmemModel:
    def test_vmem_bytes_counts_double_buffered_blocks(self):
        x = jnp.zeros((256, 128), jnp.float32)
        f = _call(_copy_kernel, [lambda i: (i, _0)], lambda i: (i, _0))
        # in block + out block, each (128, 128) f32 double-buffered
        assert kernellint.vmem_bytes(f, (x,)) == 2 * (128 * 128 * 4 * 2)

    def test_vmem_bytes_counts_scratch_once(self):
        def kernel(x_ref, o_ref, acc_ref):
            acc_ref[...] = x_ref[...]
            o_ref[...] = acc_ref[...]

        x = jnp.zeros((256, 128), jnp.float32)
        f = _call(kernel, [lambda i: (i, _0)], lambda i: (i, _0),
                  scratch=[pltpu.VMEM((128, 128), jnp.float32)])
        base = 2 * (128 * 128 * 4 * 2)
        assert kernellint.vmem_bytes(f, (x,)) == base + 128 * 128 * 4

    def test_vmem_bytes_accepts_closed_jaxpr_and_eqn(self):
        x = jnp.zeros((256, 128), jnp.float32)
        f = _call(_copy_kernel, [lambda i: (i, _0)], lambda i: (i, _0))
        closed = jax.make_jaxpr(f)(x)
        want = kernellint.vmem_bytes(f, (x,))
        assert kernellint.vmem_bytes(closed) == want
        (eqn,) = [e for e, _p, _w in iter_eqns(closed)
                  if e.primitive.name == "pallas_call"]
        assert kernellint.vmem_bytes(eqn) == want

    def test_vmem_bytes_no_pallas_raises(self):
        with pytest.raises(ValueError, match="no pallas_call"):
            kernellint.vmem_bytes(jnp.tanh, (jnp.zeros((4,)),))

    def test_budget_table_most_specific_wins(self):
        assert kernellint.vmem_budget("TPU v5 lite") == 16 << 20
        assert kernellint.vmem_budget("TPU v5p") == 32 << 20
        assert kernellint.vmem_budget("v6e") == 32 << 20
        assert kernellint.vmem_budget("v3") == 16 << 20
        # unknown chips price at the default fleet chip (v5e)
        assert kernellint.vmem_budget("cpu") == 16 << 20
        assert kernellint.vmem_budget(None) == 16 << 20


class TestKernelId:
    def test_fused_chain_names_normalize(self):
        """Generated chain kernels carry run-unstable site/length tags;
        the baseline identity must collapse them."""
        from paddle_tpu.kernels.pallas_fused_chain import (
            fused_elementwise_chain,
        )

        for n_ops, site in ((3, "a"), (4, "b")):
            fn = fused_elementwise_chain(
                lambda a: jnp.tanh(a) * 2.0, n_ops=n_ops, mode="pallas",
                site=site)
            closed = jax.make_jaxpr(fn)(jnp.zeros((512, 128), jnp.float32))
            (eqn,) = [e for e, _p, _w in iter_eqns(closed)
                      if e.primitive.name == "pallas_call"]
            assert kernellint.kernel_id(eqn) == \
                "pallas_fused_chain.fused_chain"

    def test_module_disambiguates_fwd_kernels(self):
        """pallas_attention and pallas_norm both define `_fwd_kernel`;
        the module prefix keeps their baselines separate."""
        ids = set(kernellint.analyze_kernels(["flash_attention",
                                              "rms_norm"]))
        assert "pallas_attention._fwd_kernel" in ids
        assert "pallas_norm._fwd_kernel" in ids


# ---------------------------------------------------------------------------
# THE acceptance bar: the rewrite tier's re-lint gate rejects corrupted
# generated kernels and rolls back
# ---------------------------------------------------------------------------


_REWRITE_OPTS = {
    "fusion_min_bytes": 1 << 10,
    "fusion_chain_min": 3,
    "fusion_emit": "pallas",
}


def _chain_fn(x):
    y = jnp.tanh(x)
    y = y * y
    y = jnp.tanh(y)
    y = y * 2.0
    return jnp.tanh(y)


def _fusion_report():
    return Report([Finding(
        Severity.WARNING, "FUSION_BREAK", "hlo:main",
        "chain of 5 UNFUSED elementwise ops", checker="fusion",
        data={"chain": ["tanh", "multiply", "tanh", "multiply", "tanh"],
              "bytes": 65536})])


class TestRewriteGate:
    def test_corrupted_generated_kernel_rolls_back(self, monkeypatch):
        """Inject a numerically-EXACT but statically-bad kernel into the
        fusion emitter (a dead pl.when branch — the equiv gate cannot
        see it, only kernellint can) and prove the re-lint gate rejects
        it and rolls the pass back."""
        from paddle_tpu.kernels import pallas_fused_chain as pfc

        real_make = pfc._make_kernel

        def corrupt_make(chain_fn, n_inputs, n_ops, site=""):
            kernel = real_make(chain_fn, n_inputs, n_ops, site)

            def bad(*refs):
                kernel(*refs)

                @pl.when(pl.program_id(0) < 0)   # never true: dead body
                def _():
                    refs[n_inputs][...] = refs[0][...]

            bad.__name__ = kernel.__name__
            return bad

        monkeypatch.setattr(pfc, "_make_kernel", corrupt_make)
        x = jnp.linspace(-1, 1, 128 * 128,
                         dtype=jnp.float32).reshape(128, 128)
        fn, rep = analysis.rewrite(
            _chain_fn, x, passes=["fusion"], report=_fusion_report(),
            options=dict(_REWRITE_OPTS))
        (o,) = rep.outcomes
        assert o.status == "rolled_back"
        assert "re-lint" in o.reason
        assert "KERNEL_DEAD_GRID_CELL" in o.reason
        # the rolled-back jaxpr is the ORIGINAL: no pallas_call survives
        prims = [e.primitive.name
                 for e, _p, _w in iter_eqns(fn.rewritten_jaxpr)]
        assert "pallas_call" not in prims
        np.testing.assert_allclose(np.asarray(fn(x)),
                                   np.asarray(_chain_fn(x)), rtol=1e-6)

    def test_vmem_overflow_rolls_back(self):
        """A HEALTHY generated kernel still rolls back when the VMEM
        budget says it cannot fit — the static OOM predictor as a gate
        (options thread through the re-lint analyze_jaxpr calls)."""
        x = jnp.linspace(-1, 1, 128 * 128,
                         dtype=jnp.float32).reshape(128, 128)
        _fn, rep = analysis.rewrite(
            _chain_fn, x, passes=["fusion"], report=_fusion_report(),
            options=dict(_REWRITE_OPTS,
                         kernellint_vmem_budget_bytes=1024))
        (o,) = rep.outcomes
        assert o.status == "rolled_back"
        assert "KERNEL_VMEM_OVERFLOW" in o.reason

    def test_clean_generated_kernel_still_applies(self):
        """INFO-only kernellint findings (footprint, assumptions) must
        NOT trip the gate: legit fusion keeps applying."""
        x = jnp.linspace(-1, 1, 128 * 128,
                         dtype=jnp.float32).reshape(128, 128)
        fn, rep = analysis.rewrite(
            _chain_fn, x, passes=["fusion"], report=_fusion_report(),
            options=dict(_REWRITE_OPTS))
        (o,) = rep.outcomes
        assert o.status == "applied" and rep.ok
        prims = [e.primitive.name
                 for e, _p, _w in iter_eqns(fn.rewritten_jaxpr)]
        assert "pallas_call" in prims


# ---------------------------------------------------------------------------
# satellites: cost-table longest-match + baseline v5 mechanics
# ---------------------------------------------------------------------------


class TestCostLongestMatch:
    def test_longest_substring_wins_both_orders(self):
        """'_ragged' must not swallow a '_ragged_fused' registration —
        in EITHER registration order (dict order used to decide)."""
        from paddle_tpu.analysis import cost

        def kernel_ragged_fused_probe(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        kernel_ragged_fused_probe.__name__ = "_ragged_fused_probe_kernel"
        x = jnp.zeros((256, 128), jnp.float32)
        f = _call(kernel_ragged_fused_probe, [lambda i: (i, _0)],
                  lambda i: (i, _0))
        closed = jax.make_jaxpr(f)(x)
        (eqn,) = [e for e, _p, _w in iter_eqns(closed)
                  if e.primitive.name == "pallas_call"]
        for order in (("_ragged_probe_nope", "_ragged_fused_probe"),
                      ("_ragged_fused_probe", "_ragged_probe_nope")):
            keys = {"_ragged": lambda e: 111.0, order[0]: None,
                    order[1]: None}
            try:
                cost.register_pallas_flops("_ragged", lambda e: 111.0)
                cost.register_pallas_bytes("_ragged", lambda e: 111)
                for sub in order:
                    val = 999.0 if "fused" in sub else 555.0
                    cost.register_pallas_flops(
                        sub, (lambda v: lambda e: v)(val))
                    cost.register_pallas_bytes(
                        sub, (lambda v: lambda e: int(v))(val))
                assert cost.eqn_flops(eqn) == 999.0
                assert cost.eqn_bytes(eqn) == 999
            finally:
                for k in keys:
                    cost._PALLAS_FLOPS.pop(k, None)
                    cost._PALLAS_BYTES.pop(k, None)

    def test_no_match_falls_back_to_zero(self):
        from paddle_tpu.analysis import cost

        def kernel_unregistered(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        kernel_unregistered.__name__ = "_totally_unregistered_kernel"
        x = jnp.zeros((256, 128), jnp.float32)
        f = _call(kernel_unregistered, [lambda i: (i, _0)],
                  lambda i: (i, _0))
        closed = jax.make_jaxpr(f)(x)
        (eqn,) = [e for e, _p, _w in iter_eqns(closed)
                  if e.primitive.name == "pallas_call"]
        assert cost.eqn_flops(eqn) == 0.0


def _load_graphlint():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "graphlint.py")
    spec = importlib.util.spec_from_file_location("graphlint_k", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBaselineV5:
    def test_loader_warns_not_crashes_on_unknown_sections(self, tmp_path,
                                                          capsys):
        """Older-code forward compatibility: a baseline written by a
        NEWER tool (v6 sections, extra kernels keys) must load with
        warnings, never crash — threadlint's v4 contract, extended."""
        gl = _load_graphlint()
        doc = {"schema_version": 99,
               "targets": {"llama": {"codes": {}}},
               "kernels": {"pallas_norm._fwd_kernel": {
                   "codes": {}, "counts": {}, "future_field": 1}},
               "some_v6_section": {"x": 1}}
        p = tmp_path / "b.json"
        p.write_text(json.dumps(doc))
        loaded = gl._load_baseline(str(p))
        err = capsys.readouterr().err
        assert "some_v6_section" in err and "future_field" in err
        assert loaded["schema_version"] == 99

    def test_kernels_diff_fails_on_new_code_and_count_growth(self):
        gl = _load_graphlint()
        base = {"kernels": {"k": {"codes": {"KERNEL_ASSUME": "info"},
                                  "counts": {"KERNEL_ASSUME": 1}}}}
        same = {"k": {"codes": {"KERNEL_ASSUME": "info"},
                      "counts": {"KERNEL_ASSUME": 1}}}
        assert gl._kernels_diff(same, base) == []
        grown = {"k": {"codes": {"KERNEL_ASSUME": "info"},
                       "counts": {"KERNEL_ASSUME": 2}}}
        assert any("count grew" in n
                   for n in gl._kernels_diff(grown, base))
        new = {"k": {"codes": {"KERNEL_OOB_BLOCK": "error"},
                     "counts": {"KERNEL_OOB_BLOCK": 1}}}
        assert any("NEW code" in n for n in gl._kernels_diff(new, base))

    def test_shipped_baseline_gates_kernels(self, capsys):
        """graphlint --kernels --baseline against the SHIPPED doc rides
        tier-1: a kernel change that grows a finding fails here."""
        gl = _load_graphlint()
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "GRAPHLINT_BASELINE.json")
        rc = gl.main(["--kernels", "--baseline", path, "--json"])
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == 0, ("new kernellint findings vs baseline:\n"
                         + "\n".join(out["new_vs_baseline"]))
        assert "tier_seconds" in out and "kernels" in out["tier_seconds"]
