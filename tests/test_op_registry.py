"""Generated op sweep from the registry (SURVEY C10).

The analog of the reference OpTest running every op across places/dtypes/
modes (test/legacy_test/eager_op_test.py:381): every registered op is
resolved to its public binding and swept over its declared dtypes; float
results are compared against the float32 run, and differentiable ops get a
finite-gradient check.  FLAGS_check_nan_inf gets a positive + negative test.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops import registry


def _resolve(name):
    obj = paddle
    for part in name.split("."):
        obj = getattr(obj, part)
    return obj


def _cast_arg(a, dtype):
    if isinstance(a, np.ndarray):
        return paddle.to_tensor(a.astype(dtype) if a.dtype.kind == "f"
                                else a)
    if isinstance(a, list):  # list-of-arrays ops (concat/stack/add_n/...)
        return [_cast_arg(x, dtype) for x in a]
    return a


def _run(op, dtype, rng):
    fn = _resolve(op.name)
    args, kwargs = op.sample(rng)
    targs = [_cast_arg(a, dtype) for a in args]
    out = fn(*targs, **kwargs)
    return out, targs


def _first_tensor(out):
    if isinstance(out, (tuple, list)):
        for o in out:
            if hasattr(o, "numpy"):
                return o
        return None
    return out if hasattr(out, "numpy") else None


class TestRegistryIntegrity:
    def test_at_least_100_ops(self):
        assert len(registry.all_ops()) >= 100, len(registry.all_ops())

    def test_every_op_resolves_to_public_binding(self):
        for op in registry.all_ops():
            fn = _resolve(op.name)
            assert callable(fn), op.name

    def test_sharding_classes_are_known(self):
        allowed = {"elementwise", "broadcast", "reduce", "contract",
                   "gather", "shape", "rng"}
        for op in registry.all_ops():
            assert op.sharding in allowed, (op.name, op.sharding)


def _sharding_sample(per_class=3):
    """A stratified sample of ops per GSPMD class whose first sample arg is
    an even-leading-dim float array (shardable over a 2-device axis)."""
    rng = np.random.default_rng(0)
    by_class = {}
    for op in registry.all_ops():
        if op.sharding in ("shape", "rng") or op.sample is None:
            continue
        args, _ = op.sample(rng)
        if (args and isinstance(args[0], np.ndarray)
                and args[0].dtype.kind == "f" and args[0].ndim >= 1
                and args[0].shape[0] % 2 == 0):
            by_class.setdefault(op.sharding, [])
            if len(by_class[op.sharding]) < per_class:
                by_class[op.sharding].append(op)
    return [op for ops in by_class.values() for op in ops]


@pytest.mark.parametrize("op", _sharding_sample(), ids=lambda o: o.name)
class TestShardingSweep:
    """Sharded-input correctness per GSPMD class (the sharding half of the
    reference OpTest matrix): the op must produce the single-device result
    when its first input arrives sharded over a mesh axis, and elementwise
    ops must PRESERVE the sharding (no silent all-gather)."""

    def test_sharded_input_matches_dense(self, op):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        if len(jax.devices()) < 2:  # e.g. a single real TPU chip: the
            pytest.skip("sharding sweep needs >= 2 devices")  # 1-dev axis
            # would be fully replicated and fail the propagation assert
        rng = np.random.default_rng(1)
        args, kwargs = op.sample(rng)
        fn = _resolve(op.name)
        dense = fn(*[paddle.to_tensor(a) if isinstance(a, np.ndarray) else a
                     for a in args], **kwargs)
        mesh = Mesh(np.array(jax.devices()[:2]), ("x",))
        spec = P(*(["x"] + [None] * (args[0].ndim - 1)))
        sharded0 = paddle.to_tensor(jax.device_put(
            args[0], NamedSharding(mesh, spec)))
        rest = [paddle.to_tensor(a) if isinstance(a, np.ndarray) else a
                for a in args[1:]]
        out = fn(sharded0, *rest, **kwargs)
        dt, ot = _first_tensor(dense), _first_tensor(out)
        if dt is None:
            return
        np.testing.assert_allclose(
            np.asarray(ot.numpy(), np.float32),
            np.asarray(dt.numpy(), np.float32), rtol=1e-5, atol=1e-5)
        if op.sharding == "elementwise" and ot._data.ndim == args[0].ndim:
            assert not ot._data.sharding.is_fully_replicated, (
                f"{op.name}: elementwise op gathered its sharded input")


@pytest.mark.parametrize("op", registry.all_ops(), ids=lambda o: o.name)
class TestGeneratedSweep:
    def test_dtype_sweep(self, op):
        """fp16/bf16 runs must track the fp32 run within declared tolerance
        and preserve the input dtype class."""
        base, _ = _run(op, "float32", np.random.default_rng(0))
        base_t = _first_tensor(base)
        for dtype in op.dtypes:
            if dtype == "float32":
                continue
            out, _ = _run(op, dtype, np.random.default_rng(0))
            out_t = _first_tensor(out)
            if base_t is None or out_t is None:
                continue
            got = np.asarray(out_t.numpy(), dtype=np.float64)
            want = np.asarray(base_t.numpy(), dtype=np.float64)
            if dtype in ("float16", "bfloat16"):
                rtol, atol = (op.tol or {}).get(dtype, (5e-2, 5e-2))
                np.testing.assert_allclose(got, want, rtol=rtol, atol=atol,
                                           err_msg=f"{op.name}[{dtype}]")
            else:
                np.testing.assert_array_equal(got, want,
                                              err_msg=f"{op.name}[{dtype}]")

    @pytest.mark.slow
    def test_grads_finite(self, op):
        """Differentiable ops: backward produces finite grads in every
        declared float dtype (catches NaN-at-boundary VJPs)."""
        if not op.has_vjp:
            pytest.skip("non-differentiable")
        for dtype in op.dtypes:
            if dtype not in ("float32", "float16", "bfloat16"):
                continue
            rng = np.random.default_rng(1)
            fn = _resolve(op.name)
            args, kwargs = op.sample(rng)

            def diff_arg(a):
                if isinstance(a, np.ndarray):
                    if a.dtype.kind == "f":
                        return paddle.to_tensor(a.astype(dtype),
                                                stop_gradient=False)
                    return paddle.to_tensor(a)
                if isinstance(a, list):  # concat/stack/add_n/multi_dot
                    return [diff_arg(x) for x in a]
                return a

            targs = [diff_arg(a) for a in args]
            out = fn(*targs, **kwargs)
            out_t = _first_tensor(out)
            if out_t is None or out_t.stop_gradient:
                continue
            loss = paddle.sum(out_t * out_t)
            loss.backward()
            flat = [t for a in targs
                    for t in (a if isinstance(a, list) else [a])]
            for t in flat:
                if hasattr(t, "grad") and t.grad is not None:
                    g = np.asarray(t.grad.numpy(), dtype=np.float64)
                    assert np.isfinite(g).all(), f"{op.name}[{dtype}] grad"


@pytest.fixture
def _flag():
    """Set FLAGS_check_nan_inf for one test, restoring the prior value."""
    def setter(value):
        paddle.set_flags({"FLAGS_check_nan_inf": value})
    prior = paddle.get_flags(["FLAGS_check_nan_inf"])["FLAGS_check_nan_inf"]
    yield setter
    paddle.set_flags({"FLAGS_check_nan_inf": prior})


class TestNanInfFlag:
    def test_raises_on_nan(self, _flag):
        _flag(True)
        x = paddle.to_tensor(np.array([1.0, -1.0], np.float32))
        with pytest.raises(FloatingPointError, match="log"):
            paddle.log(x)  # log(-1) = nan

    def test_silent_when_off(self, _flag):
        _flag(False)
        x = paddle.to_tensor(np.array([1.0, -1.0], np.float32))
        y = paddle.log(x)
        assert np.isnan(np.asarray(y.numpy())).any()

    def test_clean_ops_pass(self, _flag):
        _flag(True)
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        y = paddle.exp(x) + paddle.sqrt(x)
        assert np.isfinite(np.asarray(y.numpy())).all()
