"""Cost model + auto-tuner (reference auto_tuner/tuner.py, cost_model)."""

import pytest

from paddle_tpu.distributed.auto_tuner import (
    AutoTuner, ChipSpec, CostModel, V5E, V5P)
from paddle_tpu.models.llama import LlamaConfig


def _llama8b():
    return LlamaConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8)


class TestCostModel:
    def test_plan_fields_and_memory_scaling(self):
        cm = CostModel(V5P)
        cfg = _llama8b()
        base = cm.estimate(cfg, n_tokens_global=64 * 8192, seq=8192,
                           sizes={"data": 8, "sharding": 8, "model": 1,
                                  "pipe": 1, "sep": 1},
                           zero_stage=1, micro_batches=1)
        z3 = cm.estimate(cfg, n_tokens_global=64 * 8192, seq=8192,
                         sizes={"data": 8, "sharding": 8, "model": 1,
                                "pipe": 1, "sep": 1},
                         zero_stage=3, micro_batches=1)
        assert base is not None and z3 is not None
        assert z3.mem_bytes < base.mem_bytes  # zero-3 shards more state

    def test_infeasible_returns_none(self):
        cm = CostModel(V5E)  # 16 GB: 8B model replicated cannot fit
        cfg = _llama8b()
        p = cm.estimate(cfg, n_tokens_global=8 * 8192, seq=8192,
                        sizes={"data": 8, "sharding": 1, "model": 1,
                               "pipe": 1, "sep": 1},
                        zero_stage=0, micro_batches=1)
        assert p is None

    def test_pipeline_bubble_grows_with_stages(self):
        cm = CostModel(V5P)
        cfg = _llama8b()
        kw = dict(n_tokens_global=64 * 8192, seq=8192, zero_stage=1,
                  micro_batches=8)
        p2 = cm.estimate(cfg, sizes={"data": 4, "sharding": 2, "model": 4,
                                     "pipe": 2, "sep": 1}, **kw)
        p8 = cm.estimate(cfg, sizes={"data": 1, "sharding": 2, "model": 4,
                                     "pipe": 8, "sep": 1}, **kw)
        assert p2 is not None and p8 is not None
        assert p8.breakdown["bubble"] > p2.breakdown["bubble"]


class TestAutoTuner:
    def test_8b_on_64_v5p_returns_feasible_ranked_plans(self):
        plans = AutoTuner(V5P).tune(_llama8b(), n_chips=64,
                                    global_batch=128, seq=8192)
        assert plans and len(plans) <= 5
        times = [p.step_time for p in plans]
        assert times == sorted(times)
        for p in plans:
            assert p.mem_bytes < V5P.hbm_bytes
            sizes = p.mesh_sizes
            total = 1
            for v in sizes.values():
                total *= v
            assert total == 64

    def test_no_fit_raises_actionable(self):
        tiny_chip = ChipSpec("toy", 1e12, 2e9, 1e10)  # 2 GB HBM
        with pytest.raises(RuntimeError, match="no parallel plan fits"):
            AutoTuner(tiny_chip, zero_stages=(0,)).tune(
                _llama8b(), n_chips=2, global_batch=2, seq=8192)

    def test_single_chip_tiny_model(self):
        plans = AutoTuner(V5E).tune(LlamaConfig.tiny(), n_chips=1,
                                    global_batch=8, seq=64)
        assert plans[0].mesh_sizes == {"data": 1, "sharding": 1, "model": 1,
                                       "pipe": 1, "sep": 1}

    def test_measure_hook_reranks(self):
        plans = AutoTuner(V5P).tune(
            _llama8b(), 64, 128, 8192, top_k=3,
            measure=lambda p: float(p.model))  # pretend bigger tp is slower
        tps = [p.model for p in plans]
        assert tps == sorted(tps)

    def test_sep_plans_only_when_requested(self):
        plans = AutoTuner(V5P).tune(_llama8b(), 64, 128, 8192, top_k=20)
        assert all(p.sep == 1 for p in plans)
        plans_sep = AutoTuner(V5P).tune(_llama8b(), 64, 128, 8192,
                                        use_sep=True, top_k=50)
        assert any(p.sep > 1 for p in plans_sep)


class TestAutoParallelize:
    def test_plan_to_state_end_to_end(self):
        """The planner loop: tune -> mesh -> ShardedTrainState -> one step."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from paddle_tpu.distributed.auto_tuner import auto_parallelize
        from paddle_tpu.models import llama

        cfg = LlamaConfig.tiny()
        state, plan = auto_parallelize(
            cfg, llama, n_chips=8, global_batch=8, seq=64, chip=V5E,
            max_tp=2)
        sizes = plan.mesh_sizes
        assert np.prod(list(sizes.values())) == 8
        # make_mesh drops size-1 axes; the live axes must match the plan
        assert dict(state.mesh.shape) == {k: v for k, v in sizes.items()
                                          if v > 1}
        assert state.zero_stage == plan.zero_stage
        params, opt = state.init(jax.random.PRNGKey(0))
        toks = np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 65))
        batch = state.shard_batch(llama.lm_batch_from_tokens(
            jnp.asarray(toks, jnp.int32)))
        params, opt, m = state.step(params, opt, batch)
        assert np.isfinite(float(m["loss"]))
