"""Cost model + auto-tuner (reference auto_tuner/tuner.py, cost_model)."""

import time

import numpy as np
import pytest

from paddle_tpu.distributed.auto_tuner import (
    AutoTuner, ChipSpec, CostModel, V5E, V5P)
from paddle_tpu.models.llama import LlamaConfig


def _llama8b():
    return LlamaConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8)


class TestCostModel:
    def test_plan_fields_and_memory_scaling(self):
        cm = CostModel(V5P)
        cfg = _llama8b()
        base = cm.estimate(cfg, n_tokens_global=64 * 8192, seq=8192,
                           sizes={"data": 8, "sharding": 8, "model": 1,
                                  "pipe": 1, "sep": 1},
                           zero_stage=1, micro_batches=1)
        z3 = cm.estimate(cfg, n_tokens_global=64 * 8192, seq=8192,
                         sizes={"data": 8, "sharding": 8, "model": 1,
                                "pipe": 1, "sep": 1},
                         zero_stage=3, micro_batches=1)
        assert base is not None and z3 is not None
        assert z3.mem_bytes < base.mem_bytes  # zero-3 shards more state

    def test_infeasible_returns_none(self):
        cm = CostModel(V5E)  # 16 GB: 8B model replicated cannot fit
        cfg = _llama8b()
        p = cm.estimate(cfg, n_tokens_global=8 * 8192, seq=8192,
                        sizes={"data": 8, "sharding": 1, "model": 1,
                               "pipe": 1, "sep": 1},
                        zero_stage=0, micro_batches=1)
        assert p is None

    def test_pipeline_bubble_grows_with_stages(self):
        cm = CostModel(V5P)
        cfg = _llama8b()
        kw = dict(n_tokens_global=64 * 8192, seq=8192, zero_stage=1,
                  micro_batches=8)
        p2 = cm.estimate(cfg, sizes={"data": 4, "sharding": 2, "model": 4,
                                     "pipe": 2, "sep": 1}, **kw)
        p8 = cm.estimate(cfg, sizes={"data": 1, "sharding": 2, "model": 4,
                                     "pipe": 8, "sep": 1}, **kw)
        assert p2 is not None and p8 is not None
        assert p8.breakdown["bubble"] > p2.breakdown["bubble"]


class TestAutoTuner:
    def test_8b_on_64_v5p_returns_feasible_ranked_plans(self):
        plans = AutoTuner(V5P).tune(_llama8b(), n_chips=64,
                                    global_batch=128, seq=8192)
        assert plans and len(plans) <= 5
        times = [p.step_time for p in plans]
        assert times == sorted(times)
        for p in plans:
            assert p.mem_bytes < V5P.hbm_bytes
            sizes = p.mesh_sizes
            total = 1
            for v in sizes.values():
                total *= v
            assert total == 64

    def test_no_fit_raises_actionable(self):
        tiny_chip = ChipSpec("toy", 1e12, 2e9, 1e10)  # 2 GB HBM
        with pytest.raises(RuntimeError, match="no parallel plan fits"):
            AutoTuner(tiny_chip, zero_stages=(0,)).tune(
                _llama8b(), n_chips=2, global_batch=2, seq=8192)

    def test_single_chip_tiny_model(self):
        plans = AutoTuner(V5E).tune(LlamaConfig.tiny(), n_chips=1,
                                    global_batch=8, seq=64)
        assert plans[0].mesh_sizes == {"data": 1, "sharding": 1, "model": 1,
                                       "pipe": 1, "sep": 1}

    def test_measure_hook_reranks(self):
        plans = AutoTuner(V5P).tune(
            _llama8b(), 64, 128, 8192, top_k=3,
            measure=lambda p: float(p.model))  # pretend bigger tp is slower
        tps = [p.model for p in plans]
        assert tps == sorted(tps)

    def test_sep_plans_only_when_requested(self):
        plans = AutoTuner(V5P).tune(_llama8b(), 64, 128, 8192, top_k=20)
        assert all(p.sep == 1 for p in plans)
        plans_sep = AutoTuner(V5P).tune(_llama8b(), 64, 128, 8192,
                                        use_sep=True, top_k=50)
        assert any(p.sep > 1 for p in plans_sep)


class TestAutoParallelize:
    def test_plan_to_state_end_to_end(self):
        """The planner loop: tune -> mesh -> ShardedTrainState -> one step."""
        import jax
        import jax.numpy as jnp

        from paddle_tpu.distributed.auto_tuner import auto_parallelize
        from paddle_tpu.models import llama

        cfg = LlamaConfig.tiny()
        state, plan = auto_parallelize(
            cfg, llama, n_chips=8, global_batch=8, seq=64, chip=V5E,
            max_tp=2)
        sizes = plan.mesh_sizes
        assert np.prod(list(sizes.values())) == 8
        # make_mesh drops size-1 axes; the live axes must match the plan
        assert dict(state.mesh.shape) == {k: v for k, v in sizes.items()
                                          if v > 1}
        assert state.zero_stage == plan.zero_stage
        params, opt = state.init(jax.random.PRNGKey(0))
        toks = np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 65))
        batch = state.shard_batch(llama.lm_batch_from_tokens(
            jnp.asarray(toks, jnp.int32)))
        params, opt, m = state.step(params, opt, batch)
        assert np.isfinite(float(m["loss"]))


class TestPickPPSchedule:
    """Analytic GPipe vs recompute-1F1B default (VERDICT r3 weak #5)."""

    def test_small_stash_prefers_gpipe(self):
        from paddle_tpu.distributed.auto_tuner import V5E, pick_pp_schedule
        cfg = LlamaConfig.tiny()
        sched, d = pick_pp_schedule(cfg, pp=4, micro_batches=8, seq=128,
                                    mb_seqs=2, chip=V5E)
        assert sched == "gpipe"
        assert d["gpipe_stash_bytes"] < d["stash_budget_bytes"]
        assert d["relative_compute"]["1f1b"] > d["relative_compute"]["gpipe"]

    def test_huge_stash_prefers_1f1b(self):
        import dataclasses
        from paddle_tpu.distributed.auto_tuner import V5E, pick_pp_schedule
        cfg = dataclasses.replace(LlamaConfig.tiny(), hidden_size=8192)
        # 256 microbatches x long seq: the O(M) gpipe stash blows HBM while
        # the O(P) 1F1B stash fits
        sched, d = pick_pp_schedule(cfg, pp=4, micro_batches=256, seq=32768,
                                    mb_seqs=4, chip=V5E)
        assert sched == "1f1b"
        assert d["gpipe_stash_bytes"] > d["stash_budget_bytes"]
        assert d["f1b_stash_bytes"] < d["gpipe_stash_bytes"]

    def test_thread_pp_plan_sets_schedule_and_microbatches(self):
        """Direct unit test of the plan->config threading (no dependence on
        which plan the tuner happens to rank first)."""
        from paddle_tpu.distributed.auto_tuner import Plan, _thread_pp_plan
        cfg = LlamaConfig.tiny()
        assert cfg.pp_schedule is None and cfg.pp_microbatches is None
        plan = Plan(data=2, sharding=1, model=1, pipe=2, sep=1,
                    zero_stage=1, micro_batches=4, step_time=1.0,
                    mem_bytes=1e9, breakdown={"mem_act": 5e8})
        out = _thread_pp_plan(cfg, plan, global_batch=8, seq=64, chip=V5E)
        assert out.pp_microbatches == 4
        assert out.pp_schedule in ("gpipe", "1f1b")
        # a user pin survives
        import dataclasses
        pinned = dataclasses.replace(cfg, pp_schedule="1f1b")
        out2 = _thread_pp_plan(pinned, plan, global_batch=8, seq=64,
                               chip=V5E)
        assert out2.pp_schedule == "1f1b"
        # pipe=1 plans leave the config untouched
        p1 = dataclasses.replace(plan, pipe=1)
        assert _thread_pp_plan(cfg, p1, 8, 64, V5E) is cfg

    def test_reserved_bytes_shrinks_the_stash_budget(self):
        from paddle_tpu.distributed.auto_tuner import pick_pp_schedule
        import dataclasses
        cfg = dataclasses.replace(LlamaConfig.tiny(), hidden_size=4096)
        kw = dict(pp=4, micro_batches=16, seq=8192, mb_seqs=2, chip=V5E)
        s_roomy, _ = pick_pp_schedule(cfg, **kw, reserved_bytes=1e9)
        s_tight, d = pick_pp_schedule(cfg, **kw, reserved_bytes=14.5e9)
        assert (s_roomy, s_tight) == ("gpipe", "1f1b"), (s_roomy, s_tight)
        assert d["stash_budget_bytes"] < 2e9

    @pytest.mark.slow
    def test_measured_schedule_comparison_cpu_mesh(self):
        """Measured step-time evidence for the two schedules on the CPU
        mesh (a relative-cost artifact, not an assertion of which wins —
        CPU timing is noisy and the analytic model is the chooser)."""
        import dataclasses
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed import mesh as mesh_lib
        from paddle_tpu.distributed.parallelize import ShardedTrainState
        from paddle_tpu.models import llama
        from paddle_tpu.optimizer.functional import AdamW

        times = {}
        for schedule in (None, "1f1b"):  # None = gpipe-by-AD scan pipeline
            mesh = mesh_lib.make_mesh(pipe=2, data=2)
            cfg = dataclasses.replace(
                LlamaConfig.tiny(), pp_schedule=schedule)
            st = ShardedTrainState(cfg, llama, mesh, AdamW(learning_rate=1e-3))
            params, opt = st.init(jax.random.PRNGKey(0))
            toks = np.random.default_rng(0).integers(0, cfg.vocab_size,
                                                     (8, 65))
            batch = st.shard_batch(llama.lm_batch_from_tokens(
                jnp.asarray(toks, jnp.int32)))
            params, opt, m = st.step(params, opt, batch)  # compile
            t0 = time.perf_counter()
            for _ in range(3):
                params, opt, m = st.step(params, opt, batch)
            times[schedule or "gpipe"] = time.perf_counter() - t0
            assert np.isfinite(float(m["loss"]))
        # both schedules ran and produced timings
        assert set(times) == {"gpipe", "1f1b"}
        assert all(t > 0 for t in times.values())


class TestTrialRunLoop:
    """Measured trial-run refinement (C32: the reference tuner RUNS its
    candidates; here the top-k analytic plans are built + timed for real)."""

    @pytest.mark.slow
    def test_tune_with_trials_measures_and_reranks(self):
        import jax
        from paddle_tpu.distributed.auto_tuner import tune_with_trials
        from paddle_tpu.models import llama

        cfg = LlamaConfig.tiny()
        plans = tune_with_trials(cfg, llama, n_chips=4, global_batch=8,
                                 seq=64, chip=V5E, top_k=2, steps=1,
                                 devices=jax.devices()[:4], max_tp=2)
        assert len(plans) == 2
        times = [p.breakdown["measured_step_time"] for p in plans]
        assert all(t > 0 for t in times)
        assert times == sorted(times)  # re-ranked by the MEASURED time
