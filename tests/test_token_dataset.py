"""Native C++ data-IO core (native/dataio.cpp) via TokenFileDataset."""

import numpy as np
import pytest

from paddle_tpu import native
from paddle_tpu.io.token_dataset import TokenFileDataset, write_token_file


@pytest.fixture(scope="module")
def packed(tmp_path_factory):
    d = tmp_path_factory.mktemp("tok")
    rng = np.random.default_rng(0)
    data = rng.integers(0, 32000, (103, 16)).astype(np.int32)
    path = str(d / "train.bin")
    write_token_file(path, data)
    return path, data


class TestTokenFileDataset:
    def test_native_lib_builds(self):
        assert native.load("dataio") is not None, "g++ toolchain expected"

    def test_rows_and_batches(self, packed):
        path, data = packed
        ds = TokenFileDataset(path, row_len=16, batch_size=8, shuffle=False)
        assert ds.num_rows == 103
        batches = list(ds)
        assert sum(b.shape[0] for b in batches) == 103
        np.testing.assert_array_equal(np.concatenate(batches), data)

    def test_shuffle_deterministic_and_complete(self, packed):
        path, data = packed
        a = TokenFileDataset(path, 16, 8, shuffle=True, seed=7)
        b = TokenFileDataset(path, 16, 8, shuffle=True, seed=7)
        ca = np.concatenate(list(a))
        cb = np.concatenate(list(b))
        np.testing.assert_array_equal(ca, cb)      # same seed+epoch
        assert not np.array_equal(ca, data)        # actually shuffled
        # a permutation of the rows, nothing lost
        np.testing.assert_array_equal(
            np.sort(ca.sum(axis=1)), np.sort(data.sum(axis=1)))
        # next epoch: different order
        cc = np.concatenate(list(a))
        assert not np.array_equal(ca, cc)

    def test_uint16_widening(self, tmp_path):
        data = np.random.default_rng(1).integers(0, 60000, (10, 4)).astype(
            np.uint16)
        path = str(tmp_path / "u16.bin")
        write_token_file(path, data)
        ds = TokenFileDataset(path, 4, 4, dtype="uint16", shuffle=False)
        out = np.concatenate(list(ds))
        assert out.dtype == np.int32
        np.testing.assert_array_equal(out, data.astype(np.int32))

    def test_drop_last(self, packed):
        path, _ = packed
        ds = TokenFileDataset(path, 16, 8, shuffle=False, drop_last=True)
        batches = list(ds)
        assert all(b.shape == (8, 16) for b in batches)
        assert sum(b.shape[0] for b in batches) == 96

    def test_python_fallback_matches_native(self, packed, monkeypatch):
        path, data = packed
        native_out = np.concatenate(list(
            TokenFileDataset(path, 16, 8, shuffle=False)))
        monkeypatch.setattr(native, "load", lambda name: None)
        fallback = TokenFileDataset(path, 16, 8, shuffle=False)
        np.testing.assert_array_equal(
            np.concatenate(list(fallback)), native_out)
