"""hapi Model: prepare/fit/evaluate/predict/save/load/summary + callbacks.

Reference test model: test/legacy_test/test_model.py (LeNet + MNIST pattern).
"""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.metric import Accuracy
from paddle_tpu.vision.datasets import FakeData


def _mlp(num_classes=4):
    return nn.Sequential(nn.Flatten(), nn.Linear(64, 32), nn.ReLU(),
                         nn.Linear(32, num_classes))


def _prepared_model(num_classes=4):
    net = _mlp(num_classes)
    model = paddle.Model(net)
    model.prepare(
        optimizer=opt.AdamW(learning_rate=5e-3, parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=Accuracy())
    return model


def _dataset(n=64, num_classes=4, seed=0):
    # learnable mapping: label = argmax of 4 pixel groups
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(n, 1, 8, 8)).astype(np.float32)
    ys = xs.reshape(n, 4, 16).sum(-1).argmax(-1).astype(np.int64)
    import paddle_tpu.io as io

    return io.TensorDataset([paddle.to_tensor(xs), paddle.to_tensor(ys)])


class TestModelFit:
    def test_fit_reduces_loss_and_tracks_acc(self):
        model = _prepared_model()
        ds = _dataset(64)
        hist = model.fit(ds, epochs=8, batch_size=16, verbose=0)
        losses = hist["loss"]
        assert losses[-1] < losses[0]
        res = model.evaluate(ds, batch_size=16)
        assert res["acc"] > 0.5
        assert "loss" in res

    def test_fit_with_eval_data(self):
        model = _prepared_model()
        hist = model.fit(_dataset(32), eval_data=_dataset(16, seed=1),
                         epochs=2, batch_size=8, verbose=0)
        assert len(hist["loss"]) == 8

    def test_predict(self):
        import paddle_tpu.io as io

        model = _prepared_model()
        xs = np.random.randn(12, 1, 8, 8).astype(np.float32)
        ds = io.TensorDataset([paddle.to_tensor(xs)])
        outs = model.predict(ds, batch_size=4, stack_outputs=True)
        assert outs[0].shape == (12, 4)

    def test_predict_with_input_spec(self):
        """Labelled dataset + declared inputs spec -> labels dropped."""
        net = _mlp()
        model = paddle.Model(net, inputs=["image"])
        model.prepare(loss=nn.CrossEntropyLoss())
        outs = model.predict(_dataset(8), batch_size=4, stack_outputs=True)
        assert outs[0].shape == (8, 4)

    def test_train_batch_api(self):
        model = _prepared_model()
        x = np.random.randn(4, 1, 8, 8).astype(np.float32)
        y = np.array([0, 1, 2, 3], np.int64)
        out = model.train_batch([x], [y])
        loss_vals = out[0] if isinstance(out, tuple) else out
        assert np.isfinite(loss_vals[0])

    def test_save_load_roundtrip(self, tmp_path):
        model = _prepared_model()
        ds = _dataset(32)
        model.fit(ds, epochs=2, batch_size=8, verbose=0)
        path = str(tmp_path / "ckpt" / "model")
        model.save(path)
        assert os.path.exists(path + ".pdparams")
        assert os.path.exists(path + ".pdopt")

        model2 = _prepared_model()
        model2.load(path)
        w1 = np.asarray(model.network[1].weight.data)
        w2 = np.asarray(model2.network[1].weight.data)
        np.testing.assert_allclose(w1, w2)

    def test_grad_accumulation_fewer_updates(self):
        model = _prepared_model()
        w = model.network[1].weight
        before = np.asarray(w.data).copy()
        # 4 batches, accumulate 4 -> exactly one optimizer step
        model.fit(_dataset(32), epochs=1, batch_size=8, verbose=0,
                  accumulate_grad_batches=4)
        after = np.asarray(w.data)
        assert not np.allclose(before, after)

    def test_summary_counts(self):
        model = _prepared_model()
        info = model.summary()
        expected = 64 * 32 + 32 + 32 * 4 + 4
        assert info["total_params"] == expected


class TestCallbacks:
    def test_early_stopping(self):
        from paddle_tpu.hapi import EarlyStopping

        model = _prepared_model()
        es = EarlyStopping(monitor="loss", patience=0, min_delta=1e9)
        model.fit(_dataset(16), epochs=10, batch_size=8, verbose=0,
                  callbacks=[es])
        # impossible min_delta -> stops after patience+1 epochs
        assert model.stop_training

    def test_model_checkpoint(self, tmp_path):
        from paddle_tpu.hapi import ModelCheckpoint

        model = _prepared_model()
        model.fit(_dataset(16), epochs=2, batch_size=8, verbose=0,
                  callbacks=[ModelCheckpoint(save_freq=1,
                                             save_dir=str(tmp_path))])
        assert os.path.exists(str(tmp_path / "final.pdparams"))

    @pytest.mark.slow
    def test_vision_lenet_with_model(self):
        """The classic hapi demo: Model(LeNet()).fit(mnist-like)."""
        import paddle_tpu.vision as vision

        net = vision.LeNet(num_classes=3)
        model = paddle.Model(net)
        model.prepare(
            optimizer=opt.AdamW(learning_rate=1e-3,
                                parameters=net.parameters()),
            loss=nn.CrossEntropyLoss(), metrics=Accuracy())
        ds = FakeData(size=24, image_shape=(1, 28, 28), num_classes=3,
                      transform=lambda im: im.astype(np.float32) / 255.0)
        hist = model.fit(ds, epochs=1, batch_size=8, verbose=0)
        assert len(hist["loss"]) == 3


class TestFusedTrainPath:
    def test_fit_without_metrics_uses_fused_step(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu.hapi.model import Model
        paddle.seed(0)
        net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.ReLU(),
                                   paddle.nn.Linear(8, 2))
        m = Model(net)
        m.prepare(optimizer=paddle.optimizer.SGD(
            learning_rate=0.1, parameters=net.parameters()),
            loss=paddle.nn.MSELoss())
        x = np.random.default_rng(0).standard_normal((32, 4)).astype("float32")
        y = (x[:, :2] * 2).astype("float32")
        hist = m.fit(list(zip(x, y)), batch_size=8, epochs=3, verbose=0)
        assert getattr(m, "_jit_step", None)  # fused path engaged
        assert hist["loss"][-1] < hist["loss"][0]

    def test_metrics_fall_back_to_eager(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu.hapi.model import Model
        from paddle_tpu.metric import Accuracy
        paddle.seed(0)
        net = paddle.nn.Sequential(paddle.nn.Linear(4, 3))
        m = Model(net)
        m.prepare(optimizer=paddle.optimizer.SGD(
            learning_rate=0.1, parameters=net.parameters()),
            loss=paddle.nn.CrossEntropyLoss(), metrics=Accuracy())
        x = np.random.default_rng(1).standard_normal((16, 4)).astype("float32")
        y = np.random.default_rng(2).integers(0, 3, (16, 1)).astype("int64")
        m.fit(list(zip(x, y)), batch_size=8, epochs=1, verbose=0)
        assert getattr(m, "_jit_step", None) is None  # eager path kept
