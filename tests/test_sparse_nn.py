"""paddle.sparse.nn tests — conv/pool/norm parity vs dense math on
densified inputs + a tiny point-cloud training loop.
Reference: python/paddle/sparse/nn/layer/{conv,norm,pooling}.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse


def _cloud(seed=0, n=24, batch=2, size=6, ch=3):
    rng = np.random.default_rng(seed)
    idx = np.unique(np.stack([
        rng.integers(0, batch, n), rng.integers(0, size, n),
        rng.integers(0, size, n), rng.integers(0, size, n)]), axis=1)
    vals = rng.standard_normal((idx.shape[1], ch)).astype("float32")
    x = sparse.sparse_coo_tensor(idx, vals,
                                 shape=[batch, size, size, size, ch])
    dense = np.zeros((batch, size, size, size, ch), "float32")
    dense[tuple(idx)] = vals
    return x, dense, idx


def _dense_conv(dense, w, stride=1, padding=1, nd=3):
    fmt = ("NDHWC", "DHWIO", "NDHWC") if nd == 3 else ("NHWC", "HWIO", "NHWC")
    s = (stride,) * nd if isinstance(stride, int) else stride
    p = [(padding, padding)] * nd if isinstance(padding, int) else padding
    return np.asarray(jax.lax.conv_general_dilated(
        jnp.asarray(dense), w, window_strides=s, padding=p,
        dimension_numbers=fmt))


class TestSparseConv:
    def test_conv3d_matches_dense(self):
        x, dense, _ = _cloud()
        conv = sparse.nn.Conv3D(3, 4, 3, padding=1, bias_attr=False)
        out = conv(x).to_dense().numpy()
        ref = _dense_conv(dense, conv.weight._data)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_conv3d_stride2(self):
        x, dense, _ = _cloud()
        conv = sparse.nn.Conv3D(3, 4, 2, stride=2, bias_attr=False)
        out = conv(x).to_dense().numpy()
        ref = _dense_conv(dense, conv.weight._data, stride=2, padding=0)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_subm_conv3d_keeps_sites(self):
        x, dense, idx = _cloud()
        conv = sparse.nn.SubmConv3D(3, 4, 3, padding=1, bias_attr=False)
        out = conv(x)
        assert out.nnz() == x.nnz()
        ref = _dense_conv(dense, conv.weight._data)
        np.testing.assert_allclose(out.values().numpy(), ref[tuple(idx)],
                                   rtol=1e-4, atol=1e-5)

    def test_conv2d_matches_dense(self):
        rng = np.random.default_rng(1)
        idx = np.unique(np.stack([rng.integers(0, 2, 15),
                                  rng.integers(0, 5, 15),
                                  rng.integers(0, 5, 15)]), axis=1)
        vals = rng.standard_normal((idx.shape[1], 3)).astype("float32")
        x = sparse.sparse_coo_tensor(idx, vals, shape=[2, 5, 5, 3])
        dense = np.zeros((2, 5, 5, 3), "float32")
        dense[tuple(idx)] = vals
        conv = sparse.nn.Conv2D(3, 4, 3, padding=1, bias_attr=False)
        out = conv(x).to_dense().numpy()
        ref = _dense_conv(dense, conv.weight._data, nd=2)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_subm_conv2d(self):
        rng = np.random.default_rng(2)
        idx = np.unique(np.stack([rng.integers(0, 1, 10),
                                  rng.integers(0, 5, 10),
                                  rng.integers(0, 5, 10)]), axis=1)
        vals = rng.standard_normal((idx.shape[1], 2)).astype("float32")
        x = sparse.sparse_coo_tensor(idx, vals, shape=[1, 5, 5, 2])
        out = sparse.nn.SubmConv2D(2, 3, 3, padding=1)(x)
        assert out.nnz() == x.nnz() and out.shape == [1, 5, 5, 3]

    def test_bias_applied_at_active_sites(self):
        x, _, _ = _cloud()
        conv = sparse.nn.SubmConv3D(3, 4, 3, padding=1)
        no_b = sparse.nn.SubmConv3D(3, 4, 3, padding=1, bias_attr=False)
        no_b.weight._data = conv.weight._data
        d = conv(x).values().numpy() - no_b(x).values().numpy()
        np.testing.assert_allclose(
            d, np.broadcast_to(conv.bias.numpy(), d.shape),
            rtol=1e-5, atol=1e-6)

    def test_grad_reaches_weight_and_values(self):
        x, _, _ = _cloud()
        conv = sparse.nn.SubmConv3D(3, 4, 3, padding=1)
        out = conv(x)
        (out.values() ** 2).sum().backward()
        assert conv.weight.grad is not None
        assert np.abs(conv.weight.grad.numpy()).sum() > 0
        assert conv.bias.grad is not None

    def test_subm_stride_rejected(self):
        x, _, _ = _cloud()
        with pytest.raises(ValueError):
            sparse.nn.SubmConv3D(3, 4, 3, stride=2)(x)

    def test_dense_input_rejected(self):
        conv = sparse.nn.Conv3D(3, 4, 3)
        with pytest.raises(ValueError):
            conv(paddle.to_tensor(np.zeros((1, 4, 4, 4, 3), "float32")))


class TestSparsePoolNorm:
    def test_max_pool3d_matches_dense_on_positive(self):
        x, dense, _ = _cloud()
        xp = sparse.sparse_coo_tensor(
            np.asarray(x._bcoo.indices.T), np.abs(x._bcoo.data) + 0.1,
            shape=x.shape)
        dp = np.zeros_like(dense)
        dp[tuple(np.asarray(x._bcoo.indices.T))] = np.asarray(xp._bcoo.data)
        out = sparse.nn.MaxPool3D(2, stride=2)(xp)
        ref = np.asarray(jax.lax.reduce_window(
            jnp.asarray(dp), -jnp.inf, jax.lax.max,
            (1, 2, 2, 2, 1), (1, 2, 2, 2, 1), "VALID"))
        od = out.to_dense().numpy()
        active = od != 0
        np.testing.assert_allclose(od[active], ref[active],
                                   rtol=1e-5, atol=1e-6)

    def test_batch_norm_train_and_eval(self):
        x, _, _ = _cloud(ch=4)
        bn = sparse.nn.BatchNorm(4)
        out = bn(x)
        v = out.values().numpy()
        np.testing.assert_allclose(v.mean(0), 0, atol=1e-4)
        np.testing.assert_allclose(v.std(0), 1, atol=1e-2)
        assert np.abs(bn._variance.numpy() - 1).sum() > 0  # stats updated
        bn.eval()
        v2 = bn(x).values().numpy()
        assert not np.allclose(v, v2)

    def test_batch_norm_grads(self):
        x, _, _ = _cloud(ch=4)
        bn = sparse.nn.BatchNorm(4)
        (bn(x).values() ** 2).sum().backward()
        assert bn.weight.grad is not None and bn.bias.grad is not None

    def test_sync_batch_norm_convert(self):
        class Net(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = sparse.nn.SubmConv3D(3, 4, 3, padding=1)
                self.bn = sparse.nn.BatchNorm(4)

        net = sparse.nn.SyncBatchNorm.convert_sync_batchnorm(Net())
        assert isinstance(net.bn, sparse.nn.SyncBatchNorm)

    def test_relu_on_conv_output(self):
        x, _, _ = _cloud()
        out = sparse.nn.ReLU()(sparse.nn.SubmConv3D(3, 4, 3, padding=1)(x))
        assert (out.values().numpy() >= 0).all()


class TestPointCloudTraining:
    @pytest.mark.slow
    def test_tiny_pointnet_trains(self):
        """SubmConv -> BN -> ReLU -> pool -> dense head: loss decreases on a
        2-class synthetic point-cloud set (the reference sparse.nn demo
        workload shape)."""
        import paddle_tpu.nn.functional as NF

        class Net(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.c1 = sparse.nn.SubmConv3D(3, 8, 3, padding=1)
                self.bn1 = sparse.nn.BatchNorm(8)
                self.act = sparse.nn.ReLU()
                self.pool = sparse.nn.MaxPool3D(2, stride=2)
                self.head = paddle.nn.Linear(8, 2)

            def forward(self, x):
                h = self.act(self.bn1(self.c1(x)))
                h = self.pool(h)
                # global mean over active sites per batch row
                idx = h._bcoo.indices[:, 0]
                vals = h.values()
                from paddle_tpu.tensor import apply_op
                pooled = apply_op(
                    "seg_mean",
                    lambda v: jax.ops.segment_sum(v, idx, num_segments=2)
                    / jnp.maximum(jax.ops.segment_sum(
                        jnp.ones((v.shape[0], 1), v.dtype), idx,
                        num_segments=2), 1.0),
                    vals)
                return self.head(pooled)

        net = Net()
        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=net.parameters())
        clouds = []
        for s in range(4):
            x, _, idx = _cloud(seed=s, n=30)
            y = np.array([0, 1], "int64")
            clouds.append((x, paddle.to_tensor(y)))
        losses = []
        for _ in range(6):
            tot = 0.0
            for x, y in clouds:
                logits = net(x)
                loss = NF.cross_entropy(logits, y)
                loss.backward()
                opt.step()
                opt.clear_grad()
                tot += float(loss.numpy())
            losses.append(tot)
        assert losses[-1] < losses[0] * 0.9, losses


class TestReviewRegressions:
    def test_subm_padding_is_always_centered(self):
        """Reference resets subm paddings to kernel//2 regardless of the
        caller's value (phi/kernels/funcs/sparse/convolution.h:146)."""
        x, dense, idx = _cloud()
        c0 = sparse.nn.SubmConv3D(3, 4, 3, padding=0, bias_attr=False)
        c1 = sparse.nn.SubmConv3D(3, 4, 3, padding=1, bias_attr=False)
        c1.weight._data = c0.weight._data
        np.testing.assert_allclose(c0(x).values().numpy(),
                                   c1(x).values().numpy())
        ref = _dense_conv(dense, c0.weight._data, padding=1)
        np.testing.assert_allclose(c0(x).values().numpy(), ref[tuple(idx)],
                                   rtol=1e-4, atol=1e-5)

    def test_to_dense_backprops_to_weight(self):
        x, _, _ = _cloud()
        conv = sparse.nn.Conv3D(3, 4, 3, padding=1)
        out = conv(x).to_dense()
        (out * out).sum().backward()
        assert conv.weight.grad is not None
        assert np.abs(conv.weight.grad.numpy()).sum() > 0

    def test_batch_norm_value_grad_has_centering_terms(self):
        """True BN gradient: per-channel sum of dL/dx vanishes when dL/dy
        is constant (the d(mean)/dx term cancels it)."""
        x, _, _ = _cloud(ch=4)
        bn = sparse.nn.BatchNorm(4)
        xv = x.values()
        xv.stop_gradient = False
        x._values_t = xv
        out = bn(x)
        out.values().sum().backward()
        g = xv.grad.numpy()
        np.testing.assert_allclose(g.sum(axis=0), np.zeros(4), atol=1e-4)

    def test_sync_convert_preserves_running_stats(self):
        bn = sparse.nn.BatchNorm(4)
        x, _, _ = _cloud(ch=4)
        bn(x)  # update stats
        m, v = bn._mean.numpy().copy(), bn._variance.numpy().copy()

        class Net(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.bn = bn

        net = sparse.nn.SyncBatchNorm.convert_sync_batchnorm(Net())
        np.testing.assert_allclose(net.bn._mean.numpy(), m)
        np.testing.assert_allclose(net.bn._variance.numpy(), v)
