"""Graph Doctor (paddle_tpu.analysis) tests.

Every shipped checker trips on a seeded-bad snippet with its expected
Finding code, suppression/registry mechanics behave, and — the acceptance
bar — the shipped bench models (llama, moe_llama gmm + scatter,
generate_paged, the LLMEngine decode step) lint clean at WARNING level via
the same target builders tools/graphlint.py uses.
"""

import functools
import importlib.util
import json
import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu  # noqa: F401 — x64 on, same dtype world as the library
from paddle_tpu import analysis
from paddle_tpu.analysis import Finding, Severity

# thresholds scaled down so KB-sized test tensors trip the checkers
OPTS = {
    "donation_min_bytes": 1 << 10,
    "sharding_min_bytes": 1 << 10,
    "const_capture_min_bytes": 1 << 10,
    "const_subgraph_min_bytes": 64,
    "dead_code_min_flops": 1e4,
    "dead_code_min_bytes": 1 << 12,
}


def warnings_of(report, code):
    return [f for f in report.by_code(code)
            if f.severity >= Severity.WARNING]


# ---------------------------------------------------------------------------
# seeded-bad snippets: one per checker, each with its expected code
# ---------------------------------------------------------------------------


class TestDtypePromotion:
    def test_f64_upcast_flagged(self):
        def bad(x):
            return (x * np.float64(2.0)).sum()

        r = analysis.analyze(bad, jnp.ones((8, 8), jnp.float32),
                             options=OPTS)
        assert warnings_of(r, "DTYPE_F64_PROMOTION")

    def test_explicit_astype_flagged(self):
        def bad(x):
            return x.astype(jnp.float64).sum()

        r = analysis.analyze(bad, jnp.ones((8, 8), jnp.float32),
                             options=OPTS)
        assert warnings_of(r, "DTYPE_F64_PROMOTION")

    def test_f64_input_is_info_not_warning(self):
        def fine(x):
            return x.sum()

        r = analysis.analyze(fine, jnp.ones((4,), jnp.float64),
                             options=OPTS)
        assert r.by_code("DTYPE_F64_INPUT")
        assert not warnings_of(r, "DTYPE_*")

    def test_f32_model_clean(self):
        def fine(x):
            return jax.nn.softmax(x.astype(jnp.float32) * 2.0).sum()

        r = analysis.analyze(fine, jnp.ones((8, 8), jnp.bfloat16),
                             options=OPTS)
        assert not r.by_code("DTYPE_*")


class TestDonation:
    def _params(self):
        return {"w": jnp.ones((64, 64), jnp.float32)}

    def test_undonated_update_step_flagged(self):
        @jax.jit
        def step(p, g):
            return jax.tree.map(lambda a, b: a - 0.1 * b, p, g)

        r = analysis.analyze(step, self._params(), self._params(),
                             options=OPTS)
        hits = warnings_of(r, "DONATION_MISSING")
        assert hits and "args[0]" in hits[0].message

    def test_donated_step_clean(self):
        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(p, g):
            return jax.tree.map(lambda a, b: a - 0.1 * b, p, g)

        r = analysis.analyze(step, self._params(), self._params(),
                             options=OPTS)
        assert not r.by_code("DONATION_MISSING")

    def test_small_args_not_flagged(self):
        @jax.jit
        def step(p):
            return p + 1.0

        r = analysis.analyze(step, jnp.ones((4,), jnp.float32),
                             options=OPTS)
        assert not r.by_code("DONATION_MISSING")


class TestSharding:
    def setup_method(self, _m):
        from jax.sharding import Mesh
        self.mesh = Mesh(np.array(jax.devices()[:2]), ("data",))

    def _sharded_input(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(
            jnp.ones((8, 64), jnp.float32),
            NamedSharding(self.mesh, P("data", None)))

    def test_replicated_big_intermediate_flagged(self):
        @jax.jit
        def bad(x):
            big = jnp.zeros((64, 64), jnp.float32)
            return x.sum() + (big @ big.T).sum()

        r = analysis.analyze(bad, self._sharded_input(), mesh=self.mesh,
                             options=OPTS)
        assert warnings_of(r, "SHARD_REPLICATED")

    def test_constrained_intermediate_clean(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh

        @jax.jit
        def good(x):
            big = jax.lax.with_sharding_constraint(
                jnp.zeros((64, 64), jnp.float32),
                NamedSharding(mesh, P("data", None)))
            return x.sum() + (big @ big.T).sum()

        r = analysis.analyze(good, self._sharded_input(), mesh=mesh,
                             options=OPTS)
        assert not r.by_code("SHARD_REPLICATED")

    def test_replicating_constraint_is_gap(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh

        @jax.jit
        def gap(x):
            y = jax.lax.with_sharding_constraint(
                x * 2.0, NamedSharding(mesh, P(None, None)))
            return y.sum()

        r = analysis.analyze(gap, self._sharded_input(), mesh=mesh,
                             options=OPTS)
        assert warnings_of(r, "SHARD_GAP")

    def test_inert_without_mesh(self):
        @jax.jit
        def bad(x):
            return jnp.zeros((64, 64), jnp.float32).sum() + x.sum()

        r = analysis.analyze(bad, jnp.ones((8,)), options=OPTS)
        assert not r.by_code("SHARD_*")


class TestRecompileHazard:
    def test_const_capture_flagged(self):
        big = jnp.ones((64, 64), jnp.float32)  # 16 KiB > 1 KiB threshold

        def f(x):
            return x + big.sum()

        r = analysis.analyze(f, jnp.ones((4,), jnp.float32), options=OPTS)
        assert warnings_of(r, "RECOMPILE_CONST_CAPTURE")

    def test_shape_poly_probe_flagged(self):
        def f(x):
            return x.sum()

        r = analysis.analyze(
            f, jnp.ones((8,), jnp.float32), options=OPTS,
            probe_args=[(jnp.ones((16,), jnp.float32),),
                        (jnp.ones((32,), jnp.float32),)])
        assert warnings_of(r, "RECOMPILE_SHAPE_POLY")

    def test_same_signature_probe_clean(self):
        def f(x):
            return x.sum()

        r = analysis.analyze(f, jnp.ones((8,), jnp.float32), options=OPTS,
                             probe_args=[(jnp.ones((8,), jnp.float32),)])
        assert not r.by_code("RECOMPILE_SHAPE_POLY")

    def test_mutable_closure_noted(self):
        cfg = {"scale": 2.0}

        def f(x):
            return x * cfg["scale"]

        r = analysis.analyze(f, jnp.ones((4,), jnp.float32), options=OPTS)
        assert r.by_code("RECOMPILE_MUTABLE_CLOSURE")


class TestCost:
    def test_summary_and_hotspots(self):
        def f(a, b):
            return jnp.tanh(a @ b).sum()

        a = jnp.ones((32, 16), jnp.float32)
        b = jnp.ones((16, 8), jnp.float32)
        r = analysis.analyze(f, a, b, options=OPTS)
        assert r.by_code("COST_SUMMARY")
        hot = r.by_code("COST_HOTSPOT")
        assert hot and "dot_general" in hot[0].message

    def test_dot_flops_exact(self):
        from paddle_tpu.analysis import cost as cost_lib

        est = cost_lib.estimate(lambda a, b: a @ b,
                                jnp.ones((32, 16)), jnp.ones((16, 8)))
        assert est["top"][0]["flops"] == 2.0 * 32 * 16 * 8

    def test_scan_multiplies_trip_count(self):
        from paddle_tpu.analysis import cost as cost_lib

        def f(x):
            def body(c, _):
                return c @ c, None
            c, _ = jax.lax.scan(body, x, None, length=7)
            return c

        est = cost_lib.estimate(f, jnp.ones((8, 8)))
        assert est["total_flops"] == 7 * 2.0 * 8 * 8 * 8

    def test_profiler_static_cost(self):
        from paddle_tpu import profiler

        est = profiler.static_cost(lambda a: (a @ a).sum(),
                                   jnp.ones((16, 16)))
        assert est["total_flops"] > 0 and est["top"]


class TestDeadConst:
    def test_dead_heavy_output_flagged(self):
        def bad(x, w):
            dead = x @ w          # ~2*64^3 flops, never used
            return x.sum()

        r = analysis.analyze(bad, jnp.ones((64, 64), jnp.float32),
                             jnp.ones((64, 64), jnp.float32), options=OPTS)
        assert warnings_of(r, "DEAD_CODE")

    def test_dead_cheap_op_is_info(self):
        def meh(x):
            _unused = x[0] + 1.0
            return x.sum()

        r = analysis.analyze(meh, jnp.ones((8,), jnp.float32),
                             options=OPTS)
        dead = r.by_code("DEAD_CODE")
        assert dead and all(f.severity == Severity.INFO for f in dead)

    def test_const_subgraph_flagged(self):
        c1 = jnp.ones((8, 8), jnp.float32)
        c2 = jnp.ones((8, 8), jnp.float32)

        def f(x):
            return x.sum() + (c1 @ c2).sum()

        r = analysis.analyze(f, jnp.ones((4,), jnp.float32), options=OPTS)
        assert r.by_code("CONST_SUBGRAPH")

    def test_live_graph_clean(self):
        def f(x, w):
            return (x @ w).sum()

        r = analysis.analyze(f, jnp.ones((16, 16), jnp.float32),
                             jnp.ones((16, 16), jnp.float32), options=OPTS)
        assert not r.by_code("DEAD_CODE")
        assert not r.by_code("CONST_SUBGRAPH")


# ---------------------------------------------------------------------------
# framework mechanics: registry, suppressions, report
# ---------------------------------------------------------------------------


class TestFramework:
    def test_shipped_checkers_registered(self):
        have = set(analysis.list_checkers())
        assert {"dtype_promotion", "donation", "sharding",
                "recompile_hazard", "cost", "dead_code"} <= have

    def test_unknown_checker_raises(self):
        with pytest.raises(ValueError, match="unknown checker"):
            analysis.analyze(lambda x: x, jnp.ones(3), checkers=["nope"])

    def test_custom_checker_registers_and_runs(self):
        name = "test_always_fires"

        @analysis.register_checker(name)
        def chk(ctx):
            yield Finding(Severity.ERROR, "TEST_FIRE", "<top>", "boom")

        try:
            r = analysis.analyze(lambda x: x + 1, jnp.ones(3),
                                 checkers=[name])
            assert r.by_code("TEST_FIRE") and not r.ok(Severity.ERROR)
        finally:
            del analysis.core.CHECKER_REGISTRY[name]

    def test_per_call_suppression(self):
        def bad(x):
            return (x * np.float64(2.0)).sum()

        x = jnp.ones((8, 8), jnp.float32)
        r = analysis.analyze(bad, x, options=OPTS,
                             suppress=["DTYPE_F64_PROMOTION"])
        assert not r.by_code("DTYPE_F64_PROMOTION") and r.suppressed >= 1
        r = analysis.analyze(bad, x, options=OPTS, suppress=["DTYPE_*"])
        assert not r.by_code("DTYPE_*")

    def test_path_scoped_suppression(self):
        def bad(x):
            return (x * np.float64(2.0)).sum()

        x = jnp.ones((8, 8), jnp.float32)
        r = analysis.analyze(bad, x, options=OPTS,
                             suppress=["DTYPE_F64_PROMOTION@nomatch/*"])
        assert r.by_code("DTYPE_F64_PROMOTION")  # wrong path: still fires
        r = analysis.analyze(bad, x, options=OPTS,
                             suppress=["DTYPE_F64_PROMOTION@*"])
        assert not r.by_code("DTYPE_F64_PROMOTION")

    def test_process_wide_suppression_context(self):
        def bad(x):
            return (x * np.float64(2.0)).sum()

        x = jnp.ones((8, 8), jnp.float32)
        with analysis.suppressions("DTYPE_*"):
            assert not analysis.analyze(bad, x, options=OPTS).by_code(
                "DTYPE_*")
        assert analysis.analyze(bad, x, options=OPTS).by_code("DTYPE_*")

    def test_report_json_and_ok(self):
        def bad(x):
            return (x * np.float64(2.0)).sum()

        r = analysis.analyze(bad, jnp.ones((8, 8), jnp.float32),
                             options=OPTS)
        j = r.to_json()
        assert j["counts"]["warning"] >= 1
        assert any(f["code"] == "DTYPE_F64_PROMOTION" for f in j["findings"])
        assert not r.ok(Severity.WARNING) and r.ok(Severity.ERROR)

    def test_analyze_jaxpr_entry(self):
        closed = jax.make_jaxpr(lambda x: x.astype(jnp.float64).sum())(
            jnp.ones((8, 8), jnp.float32))
        r = analysis.analyze_jaxpr(closed, options=OPTS)
        assert r.by_code("DTYPE_F64_PROMOTION")

    def test_shape_dtype_struct_args(self):
        # lint without materializing params: tracing needs shapes only
        r = analysis.analyze(
            lambda p, g: jax.tree.map(lambda a, b: a - b, p, g),
            jax.ShapeDtypeStruct((64, 64), jnp.float32),
            jax.ShapeDtypeStruct((64, 64), jnp.float32), options=OPTS)
        assert isinstance(r, analysis.Report)


# ---------------------------------------------------------------------------
# static.Program bridge
# ---------------------------------------------------------------------------


class TestProgramLint:
    def test_program_lint_runs(self):
        import paddle_tpu as paddle
        from paddle_tpu import static

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", (4, 8), "float32")
            y = paddle.matmul(x, paddle.ones((8, 8), "float32"))
            z = paddle.nn.functional.relu(y)
        r = main.lint(fetch_list=[z])
        assert isinstance(r, analysis.Report)
        assert not warnings_of(r, "DEAD_CODE")

    def test_program_lint_rejects_pass_removed_fetch(self):
        import paddle_tpu as paddle
        from paddle_tpu import static

        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", (4, 8), "float32")
            dead = paddle.nn.functional.relu(x)   # not in fetch_list
            z = paddle.matmul(x, paddle.ones((8, 8), "float32"))
        pruned = main.apply_pass("dead_code_elimination", fetch_list=[z])
        with pytest.raises(KeyError, match="removed by"):
            pruned.lint(fetch_list=[dead])


# ---------------------------------------------------------------------------
# LLMEngine satellites: admission leak + shutdown join race
# ---------------------------------------------------------------------------


class TestEngineHardening:
    def _engine(self):
        from paddle_tpu.inference import LLMEngine
        from paddle_tpu.models import llama

        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        return LLMEngine(params, cfg, num_slots=2, page_size=4,
                         max_seq_len=16)

    def test_dispatch_failure_releases_slot_and_pages(self):
        eng = self._engine()
        free_slots0 = eng.cache.free_slot_count
        free_pages0 = eng.cache.free_page_count

        def boom(*a, **k):
            raise RuntimeError("ragged step exploded")

        # plain decode routes through the fused dispatch by default;
        # fail BOTH executables so the test covers whichever path the
        # step picks
        eng._ragged = boom
        eng._ragged_fused = boom
        req = eng.submit([1, 2, 3], max_new_tokens=4)
        eng.step()   # admit + the failing unified dispatch
        with pytest.raises(RuntimeError, match="ragged step exploded"):
            req.result(timeout=5)
        assert eng.cache.free_slot_count == free_slots0
        assert eng.cache.free_page_count == free_pages0
        assert not eng._slots and not eng._pending

    def test_dispatch_failure_does_not_wedge_later_requests(self):
        eng = self._engine()
        real_ragged = eng._ragged
        real_fused = eng._ragged_fused
        calls = {"n": 0}

        def _flaky(real):
            def wrapper(*a, **k):
                calls["n"] += 1
                if calls["n"] == 1:
                    raise RuntimeError("transient")
                return real(*a, **k)
            return wrapper

        eng._ragged = _flaky(real_ragged)
        eng._ragged_fused = _flaky(real_fused)
        bad = eng.submit([1, 2, 3], max_new_tokens=2)
        eng.step()   # bad rides the failing dispatch alone
        good = eng.submit([4, 5], max_new_tokens=2)
        while eng.has_work():
            if not eng.step():
                break
        with pytest.raises(RuntimeError, match="transient"):
            bad.result(timeout=5)
        assert len(good.result(timeout=5)) == 2

    def test_failed_donated_dispatch_recovers_pools(self):
        # on TPU a _ragged step that fails AFTER dispatch has already
        # consumed the donated pools; simulate by deleting them (CPU
        # ignores donation, so the buffers stay alive in normal runs)
        eng = self._engine()
        free_pages0 = eng.cache.free_page_count
        slot = eng.cache.acquire_slot()
        eng.cache.ensure_capacity(slot, 8)
        victim = _mk_request()
        eng._slots[slot] = type(
            "S", (), {"req": victim, "last_tok": 0, "ctx": 4})()
        eng.cache.pools["k"].delete()
        eng.cache.pools["v"].delete()
        assert eng._recover_pools(RuntimeError("boom"))
        assert not eng.cache.pools["k"].is_deleted()
        with pytest.raises(RuntimeError, match="KV pools lost"):
            victim.result(timeout=5)
        assert not eng._slots
        assert eng.cache.free_page_count == free_pages0
        # fresh pools admit new work end-to-end
        out = eng.generate([[1, 2]], max_new_tokens=2, timeout=60)
        assert len(out[0]) == 2

    def test_recover_pools_noop_while_alive(self):
        eng = self._engine()
        assert not eng._recover_pools(RuntimeError("x"))

    def test_shutdown_refuses_release_while_thread_alive(self):
        eng = self._engine()
        release = threading.Event()
        t = threading.Thread(target=release.wait, daemon=True)
        t.start()
        eng._thread = t  # stand-in for a wedged step thread
        eng._pending.append(_mk_request())
        slots_before = dict(eng._slots)
        with pytest.raises(RuntimeError, match="NOT released"):
            eng.shutdown(timeout=0.05)
        assert eng._slots == slots_before   # untouched while thread lives
        assert not eng._pending             # but waiters were unblocked
        release.set()
        t.join(timeout=5)
        eng.shutdown(timeout=1)             # retry completes cleanly
        assert eng._thread is None

    def test_clean_shutdown_still_works(self):
        eng = self._engine()
        eng.start()
        out = eng.generate([[1, 2]], max_new_tokens=2, timeout=60)
        assert len(out[0]) == 2
        eng.shutdown()
        assert eng._thread is None


def _mk_request():
    from paddle_tpu.inference import llm_engine
    return llm_engine._Request([1], 1, None)


# ---------------------------------------------------------------------------
# the acceptance bar: shipped bench models lint clean (same targets the
# tools/graphlint.py CLI runs; SHIPPED_SUPPRESSIONS documents exceptions)
# ---------------------------------------------------------------------------


def _load_graphlint():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "graphlint.py")
    spec = importlib.util.spec_from_file_location("graphlint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_graphlint = _load_graphlint()


@pytest.mark.parametrize("target", sorted(_graphlint.TARGETS))
def test_shipped_model_lints_clean(target):
    fn, args, extra = _graphlint.TARGETS[target]()
    report = analysis.analyze(
        fn, *args, suppress=list(_graphlint.SHIPPED_SUPPRESSIONS),
        mesh=extra.get("mesh"), probe_args=extra.get("probe_args"),
        options=extra.get("options"))
    bad = [str(f) for f in report if f.severity >= Severity.WARNING]
    assert report.ok(Severity.WARNING), \
        f"{target} has undocumented findings:\n" + "\n".join(bad)


def _baseline_path():
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "GRAPHLINT_BASELINE.json")


def test_baseline_gate_tier1(capsys):
    """graphlint --baseline rides the tier-1 entrypoint: a change that
    grows a NEW finding code (or escalates one) on any shipped target
    fails here, alongside the unit tests, without waiting for a bench
    round.  Mesh-less, so it gates in EVERY session (including
    PADDLE_HOST_DEVICES=1); the SPMD tier's gate is the multidevice
    test below.  jaxpr tier only — the HLO tier's compile budget lives
    in test_graphlint_hlo.py; the threads tier's gate (--threads against
    the same file's v4 `threads` section) lives in test_threadlint.py."""
    rc = _graphlint.main(["--baseline", _baseline_path(), "--no-hlo",
                          "--json"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0, ("new graphlint finding codes vs baseline:\n"
                     + "\n".join(out["new_vs_baseline"]))
    # one shipped doc gates every tier: the model-tier run above must
    # coexist with the v4 threads and v5 kernels sections (merge-written,
    # never dropped)
    with open(_baseline_path()) as f:
        doc = json.load(f)
    assert doc["schema_version"] == _graphlint.BASELINE_SCHEMA_VERSION
    assert "threads" in doc
    assert "kernels" in doc


@pytest.mark.multidevice(4)
def test_baseline_gate_tier1_spmd(capsys):
    """The same gate under the 2x2 mesh so the SPMD tier gates too — a
    new SHARD_RESHARD (or a reshard-count regression vs the baseline's
    per-target spmd counters) on a sharded train step fails CI."""
    rc = _graphlint.main(["--baseline", _baseline_path(), "--no-hlo",
                          "--json", "--mesh", "data=2,model=2"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0, ("new graphlint finding codes vs baseline:\n"
                     + "\n".join(out["new_vs_baseline"]))
