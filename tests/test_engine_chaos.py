"""Preemptible serving: request lifecycle (cancel / deadline / bounded
queue), preempt-then-resume correctness, the HTTP failure surface
(503/504/healthz), and the fault-injection chaos suite with the
zero-leak invariant checker (paddle_tpu/inference/faults.py)."""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.inference import (DeadlineExceeded, LLMEngine, QueueFull,
                                  RequestCancelled, serve_llm)
from paddle_tpu.inference import faults as F
from paddle_tpu.models import generation, llama
from paddle_tpu.models.llama import LlamaConfig


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _engine(params, cfg, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_seq_len", 16)
    return LLMEngine(params, cfg, **kw)


def _pool_accounted(eng):
    """Every allocatable page is free OR cached by the prefix index once
    slots are gone (the post-PR-15 analog of `free == num_pages - 1`)."""
    cached = 0 if eng.prefix_index is None else eng.prefix_index.cached_pages
    return eng.cache.free_page_count + cached == eng.cache.num_pages - 1


def _workload(cfg, seed=1, n=4):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, cfg.vocab_size,
                          int(rng.integers(2, 9))).tolist(),
             int(rng.integers(2, 7))) for _ in range(n)]


class TestLifecycle:
    def test_cancel_while_queued(self, tiny):
        cfg, params = tiny
        eng = _engine(params, cfg, num_slots=1)
        a = eng.submit([1, 2, 3], max_new_tokens=4)
        b = eng.submit([4, 5], max_new_tokens=4)
        b.cancel()                    # resolves immediately (still queued)
        assert b.done()
        with pytest.raises(RequestCancelled):
            b.result(timeout=0)
        while not a.done():
            eng.step()
        assert len(a.result(timeout=0)) == 4
        assert eng.stats["cancelled"] == 1
        F.check_invariants(eng, [a, b])

    def test_cancel_while_decoding(self, tiny):
        cfg, params = tiny
        eng = _engine(params, cfg)
        a = eng.submit([1, 2, 3, 4], max_new_tokens=8)
        eng.step()                    # admit + first decode
        assert not a.done()
        a.cancel()                    # in flight: evicted at next step
        eng.step()
        assert a.done()
        with pytest.raises(RequestCancelled):
            a.result(timeout=0)
        assert eng.stats["cancelled"] == 1
        # the cancelled request's slot/pages freed immediately (the
        # prefix index may retain its prompt pages for reuse)
        assert eng.cache.free_slot_count == 2
        assert _pool_accounted(eng)
        F.check_invariants(eng, [a])

    def test_cancel_done_request_is_noop(self, tiny):
        cfg, params = tiny
        eng = _engine(params, cfg)
        a = eng.submit([1, 2], max_new_tokens=2)
        while not a.done():
            eng.step()
        toks = a.result(timeout=0)
        a.cancel()
        assert a.result(timeout=0) == toks     # still the tokens, no error
        assert a.resolutions == 1

    def test_deadline_while_queued(self, tiny):
        cfg, params = tiny
        eng = _engine(params, cfg, num_slots=1)
        a = eng.submit([1, 2, 3], max_new_tokens=8)     # occupies the slot
        b = eng.submit([4, 5], max_new_tokens=4, deadline=0.0)
        eng.step()                    # reap runs before admission
        assert b.done()
        with pytest.raises(DeadlineExceeded):
            b.result(timeout=0)
        assert eng.stats["timed_out"] == 1
        while not a.done():
            eng.step()
        a.result(timeout=0)
        F.check_invariants(eng, [a, b])

    def test_deadline_mid_decode(self, tiny):
        cfg, params = tiny
        eng = _engine(params, cfg)
        a = eng.submit([1, 2, 3, 4], max_new_tokens=8, deadline=0.15)
        eng.step()                    # admit
        time.sleep(0.2)
        eng.step()                    # deadline reaped, slot evicted
        assert a.done()
        with pytest.raises(DeadlineExceeded):
            a.result(timeout=0)
        assert eng.stats["timed_out"] == 1
        assert _pool_accounted(eng)
        F.check_invariants(eng, [a])

    def test_queue_full_raises_typed(self, tiny):
        cfg, params = tiny
        eng = _engine(params, cfg, num_slots=1, max_pending=1)
        a = eng.submit([1, 2], max_new_tokens=3)    # queued (nothing steps)
        with pytest.raises(QueueFull) as ei:
            eng.submit([3, 4], max_new_tokens=3)
        assert ei.value.retry_after > 0
        while not a.done():
            eng.step()
        F.check_invariants(eng, [a])

    def test_submit_after_shutdown(self, tiny):
        cfg, params = tiny
        eng = _engine(params, cfg)
        eng.shutdown()
        with pytest.raises(RuntimeError, match="stopped"):
            eng.submit([1, 2], max_new_tokens=2)

    def test_shutdown_fails_queued_and_inflight(self, tiny):
        cfg, params = tiny
        eng = _engine(params, cfg, num_slots=1)
        a = eng.submit([1, 2, 3], max_new_tokens=8)
        b = eng.submit([4, 5], max_new_tokens=4)
        eng.step()                    # a in flight, b queued
        eng.shutdown()
        for h in (a, b):
            assert h.done() and h.resolutions == 1
            with pytest.raises(RuntimeError, match="shut down"):
                h.result(timeout=0)
        assert eng.cache.free_slot_count == 1
        assert _pool_accounted(eng)


class TestPreemption:
    @pytest.mark.parametrize("mode", ["swap", "recompute"])
    def test_preempt_resume_token_exact(self, tiny, mode):
        """A pool sized BELOW concurrent worst-case must still complete
        every request token-exactly vs the single-request generate_paged()
        baseline, with >= 1 preemption actually observed."""
        cfg, params = tiny
        rng = np.random.default_rng(0)
        # 2 slots, worst case 3 pages each = 6 > the 4 the pool holds
        eng = _engine(params, cfg, num_pages=5, preempt_mode=mode)
        prompts = [rng.integers(0, cfg.vocab_size, 8).tolist()
                   for _ in range(3)]
        outs = eng.generate(prompts, max_new_tokens=4)
        for p, got in zip(prompts, outs):
            want = np.asarray(generation.generate_paged(
                params, jnp.asarray([p], jnp.int32), cfg,
                max_new_tokens=4, page_size=4))[0].tolist()
            assert got == want
        assert eng.stats["preemptions"] >= 1
        assert eng.stats["resumed"] >= 1
        if mode == "swap":
            assert eng.stats["swapped_in"] >= 1
        else:
            assert eng.stats["swapped_in"] == 0
        assert _pool_accounted(eng)
        F.check_invariants(eng)

    def test_victim_policy_fewest_tokens(self, tiny):
        cfg, params = tiny
        rng = np.random.default_rng(2)
        eng = _engine(params, cfg, num_pages=5,
                      victim_policy="fewest_tokens")
        prompts = [rng.integers(0, cfg.vocab_size, 8).tolist()
                   for _ in range(3)]
        outs = eng.generate(prompts, max_new_tokens=4)
        for p, got in zip(prompts, outs):
            want = np.asarray(generation.generate(
                params, jnp.asarray([p], jnp.int32), cfg,
                max_new_tokens=4))[0].tolist()
            assert got == want
        assert eng.stats["preemptions"] >= 1
        F.check_invariants(eng)

    def test_never_preempts_last_runnable(self, tiny):
        """A lone request on a minimal pool completes with ZERO
        preemptions — the guarantee that makes the scheduler
        deadlock-free."""
        cfg, params = tiny
        eng = _engine(params, cfg, num_slots=1, num_pages=4)  # exactly fits
        out = eng.generate([[1, 2, 3, 4, 5, 6, 7, 8]], max_new_tokens=4)[0]
        assert len(out) == 4
        assert eng.stats["preemptions"] == 0
        F.check_invariants(eng)

    def test_admission_reserves_prompt_only(self, tiny):
        """Admit-on-demand: right after admission a request holds pages
        for its PROMPT (chunk), not prompt+max_new_tokens — and the first
        decode token's page is only allocated on the NEXT ragged step."""
        cfg, params = tiny
        eng = _engine(params, cfg)
        eng.submit([1, 2, 3, 4], max_new_tokens=8)   # worst case 3 pages
        eng.step()   # admit + the prompt's prefill chunk (1 page)
        used = eng.cache.num_pages - 1 - eng.cache.free_page_count
        assert used == 1    # the 4-token prompt's page, nothing more
        eng.step()   # first decode span allocates token 5's page
        used = eng.cache.num_pages - 1 - eng.cache.free_page_count
        assert used == 2


class TestServeFailureSurface:
    def test_timeout_replies_504_and_cancels(self, tiny):
        """A request missing request_timeout gets 504 and is CANCELLED —
        its slot frees immediately instead of decoding to max_new_tokens."""
        cfg, params = tiny
        eng = LLMEngine(params, cfg, num_slots=1, page_size=8,
                        max_seq_len=64)
        srv, _ = serve_llm(eng, request_timeout=0.05)
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/"
            req = urllib.request.Request(url, data=json.dumps(
                {"prompt": [1, 2, 3], "max_new_tokens": 60}).encode())
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=60)
            assert ei.value.code == 504
            # the cancel frees the slot: the engine must accept and finish
            # fresh work promptly
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                snap = eng.stats_snapshot()
                if snap["cancelled"] >= 1 and snap["free_slots"] == 1:
                    break
                time.sleep(0.05)
            snap = eng.stats_snapshot()
            assert snap["cancelled"] >= 1
            assert snap["free_slots"] == 1
            assert snap["free_pages"] + snap["prefix"]["cached_pages"] \
                == eng.cache.num_pages - 1
        finally:
            srv.shutdown()

    def test_queue_full_replies_503_with_retry_after(self, tiny):
        cfg, params = tiny
        eng = LLMEngine(params, cfg, num_slots=1, page_size=8,
                        max_seq_len=64, max_pending=1)
        srv, _ = serve_llm(eng)
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/"
            import threading

            def fire_and_forget():
                req = urllib.request.Request(url, data=json.dumps(
                    {"prompt": [1, 2, 3], "max_new_tokens": 60}).encode())
                try:
                    urllib.request.urlopen(req, timeout=120).read()
                except urllib.error.HTTPError:
                    pass   # failed by shutdown at test end
            t1 = threading.Thread(target=fire_and_forget)
            t1.start()
            # wait until the first request occupies the lone slot
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if eng.stats_snapshot()["admitted"] >= 1:
                    break
                time.sleep(0.02)
            t2 = threading.Thread(target=fire_and_forget)  # fills the queue
            t2.start()
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if eng.stats_snapshot()["queue_depth"] >= 1:
                    break
                time.sleep(0.02)
            req = urllib.request.Request(url, data=json.dumps(
                {"prompt": [7, 8], "max_new_tokens": 4}).encode())
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=60)
            assert ei.value.code == 503
            assert int(ei.value.headers["Retry-After"]) >= 1
        finally:
            srv.shutdown()

    def test_healthz(self, tiny):
        cfg, params = tiny
        eng = _engine(params, cfg)
        srv, _ = serve_llm(eng)
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/healthz"
            with urllib.request.urlopen(url, timeout=30) as resp:
                payload = json.loads(resp.read())
            assert resp.status == 200 and payload["ok"]
            eng.shutdown()        # step thread gone -> endpoint degrades
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url, timeout=30)
            assert ei.value.code == 503
        finally:
            srv.shutdown()

    def test_deadline_param_maps_504(self, tiny):
        cfg, params = tiny
        eng = LLMEngine(params, cfg, num_slots=1, page_size=8,
                        max_seq_len=64)
        srv, _ = serve_llm(eng)
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/"
            req = urllib.request.Request(url, data=json.dumps(
                {"prompt": [1, 2, 3], "max_new_tokens": 60,
                 "deadline": 0.05}).encode())
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=60)
            assert ei.value.code == 504
            assert eng.stats_snapshot()["timed_out"] >= 1
        finally:
            srv.shutdown()


# -- chaos: deterministic fault schedules + the invariant checker ----------

# every shipped schedule runs on a pool small enough to ALSO exercise
# preemption under the injected fault (num_pages=5 < 2-slot worst case)
SHIPPED_SCHEDULES = [
    ("decode_3rd_dispatch", "swap",
     [("decode", dict(nth=3))]),
    ("decode_3rd_dispatch_consumes_donated_pools", "swap",
     [("decode", dict(nth=3, consume_pools=True))]),
    ("prefill_1st_dispatch", "swap",
     [("prefill", dict(nth=1))]),
    ("prefill_2nd_dispatch_consumes_donated_pools", "recompute",
     [("prefill", dict(nth=2, consume_pools=True))]),
    ("oom_every_alloc_slot_0", "swap",
     [("page_alloc", dict(slot=0, always=True))]),
    ("oom_every_alloc_slot_1", "recompute",
     [("page_alloc", dict(slot=1, always=True))]),
    ("sampling_2nd", "swap",
     [("sample", dict(nth=2))]),
    ("swap_out_1st", "swap",
     [("swap_out", dict(nth=1))]),
    ("swap_in_1st_consumes_donated_pools", "swap",
     [("swap_in", dict(nth=1, consume_pools=True))]),
    ("double_fault_prefill_then_decode", "swap",
     [("prefill", dict(nth=2)), ("decode", dict(nth=4))]),
]


class TestChaos:
    def _make(self, params, cfg, mode):
        return lambda: _engine(params, cfg, num_pages=5, preempt_mode=mode)

    @pytest.mark.parametrize(
        "name,mode,spec", SHIPPED_SCHEDULES,
        ids=[s[0] for s in SHIPPED_SCHEDULES])
    def test_shipped_schedule(self, tiny, name, mode, spec):
        cfg, params = tiny
        rules = [F.FaultRule(point, **kw) for point, kw in spec]
        report = F.run_schedule(self._make(params, cfg, mode), rules,
                                _workload(cfg))
        assert report["ok"], report["violations"]
        assert report["fired"], "schedule never fired — it tests nothing"
        # every handle resolved: completions + failures cover the workload
        assert report["completed"] + report["failed"] == report["requests"]

    def test_fault_free_schedule_all_complete(self, tiny):
        cfg, params = tiny
        report = F.run_schedule(self._make(params, cfg, "swap"), [],
                                _workload(cfg))
        assert report["ok"] and report["failed"] == 0
        assert report["stats"]["preemptions"] >= 1   # pool pressure alone

    def test_random_schedules_smoke(self, tiny):
        cfg, params = tiny
        for seed in range(12):
            rules = F.random_schedule(seed)
            mode = "swap" if seed % 2 else "recompute"
            report = F.run_schedule(self._make(params, cfg, mode), rules,
                                    _workload(cfg, seed=seed),
                                    witness=True)
            assert report["ok"], (seed, report["violations"])
            # witness armed: order inversions / locks-across-dispatch /
            # leaked threads would have failed above; prove it watched
            assert report["threads"]["witness"]["acquisitions"] > 0

    @pytest.mark.slow
    def test_random_schedules_soak(self, tiny):
        """>= 200 seeded random schedules (acceptance criterion); each must
        leave zero leaks and a serving-capable engine."""
        cfg, params = tiny
        for seed in range(200):
            rules = F.random_schedule(seed)
            mode = "swap" if seed % 2 else "recompute"
            report = F.run_schedule(self._make(params, cfg, mode), rules,
                                    _workload(cfg, seed=seed),
                                    witness=True)
            assert report["ok"], (seed, report["violations"])

    def test_injected_oom_respects_last_runnable(self, tiny):
        """OOM-every-allocation for one slot must fail ONLY requests that
        land in it, never deadlock, never leak."""
        cfg, params = tiny
        rules = [F.FaultRule("page_alloc", slot=0, always=True)]
        report = F.run_schedule(self._make(params, cfg, "swap"), rules,
                                _workload(cfg))
        assert report["ok"]
        assert report["failed"] >= 1


# -- chaos: chunked prefill (prompts longer than the per-step budget) ------

# chunk budget 3 over 5..9-token prompts: every prefill is multi-chunk, so
# the injected fault / the pool pressure lands MID-prefill
CHUNKED_SCHEDULES = [
    ("chunk_dies_1st", "swap",
     [("prefill_chunk", dict(nth=1))]),
    ("chunk_dies_3rd_midway", "recompute",
     [("prefill_chunk", dict(nth=3))]),
    ("chunk_consumes_donated_pools", "recompute",
     [("prefill_chunk", dict(nth=2, consume_pools=True))]),
    ("chunk_then_decode_fault", "swap",
     [("prefill_chunk", dict(nth=2)), ("decode", dict(nth=5))]),
    ("oom_during_chunked_prefill", "swap",
     [("page_alloc", dict(slot=1, nth=3))]),
]


class TestChunkedPrefillChaos:
    def _make(self, params, cfg, mode):
        return lambda: _engine(params, cfg, num_pages=5, preempt_mode=mode,
                               prefill_chunk_tokens=3, block_q=2)

    def _workload(self, cfg, seed=3, n=4):
        rng = np.random.default_rng(seed)
        return [(rng.integers(0, cfg.vocab_size,
                              int(rng.integers(5, 10))).tolist(),
                 int(rng.integers(2, 5))) for _ in range(n)]

    @pytest.mark.parametrize(
        "name,mode,spec", CHUNKED_SCHEDULES,
        ids=[s[0] for s in CHUNKED_SCHEDULES])
    def test_chunked_schedule(self, tiny, name, mode, spec):
        """A request dying (or losing the pools, or getting preempted)
        MID-prefill-chunk must leave zero leaked pages/slots and every
        handle resolved exactly once."""
        cfg, params = tiny
        rules = [F.FaultRule(point, **kw) for point, kw in spec]
        report = F.run_schedule(self._make(params, cfg, mode), rules,
                                self._workload(cfg))
        assert report["ok"], report["violations"]
        assert report["fired"], "schedule never fired — it tests nothing"
        assert report["completed"] + report["failed"] == report["requests"]
        assert report["stats"]["prefill_chunks"] >= 2  # chunking happened

    @pytest.mark.parametrize("mode", ["swap", "recompute"])
    def test_preempt_mid_prefill_chunk_token_exact(self, tiny, mode):
        """Deterministic mid-prefill preemption: a slot whose prompt is
        only half-cached is preempted directly, resumes in either mode,
        and still matches the offline greedy chain."""
        cfg, params = tiny
        eng = _engine(params, cfg, preempt_mode=mode,
                      prefill_chunk_tokens=4, block_q=2)
        prompt = np.random.default_rng(7).integers(
            0, cfg.vocab_size, 9).tolist()
        h = eng.submit(prompt, max_new_tokens=4)
        eng.step()                    # admit + first 4-token chunk
        (slot, st), = eng._slots.items()
        assert st.prefilling and st.ctx == 4
        eng._preempt(slot)            # victim taken mid-prefill
        assert eng.stats["preemptions"] == 1
        while not h.done():
            eng.step()
        want = np.asarray(generation.generate(
            params, jnp.asarray([prompt], jnp.int32), cfg,
            max_new_tokens=4))[0].tolist()
        assert list(h.result(timeout=5)) == want
        assert eng.stats["resumed"] == 1
        F.check_invariants(eng, [h])


# -- chaos: speculative decoding (draft/verify faults mid-speculation) -----

# spec_k=3 over repetitive prompts on an undersized pool: every schedule
# runs verify spans under page pressure, so faults and preemption land
# MID-speculation; the checker proves no leak, no double-resolution, and
# the spec token identities hold
SPEC_SCHEDULES = [
    ("draft_fault_2nd", "swap",
     [("draft", dict(nth=2))]),
    ("verify_fault_2nd", "recompute",
     [("verify", dict(nth=2))]),
    ("verify_consumes_donated_pools", "swap",
     [("verify", dict(nth=1, consume_pools=True))]),
    ("draft_consumes_pools_poisons_dispatch", "recompute",
     [("draft", dict(nth=2, consume_pools=True))]),
    ("oom_mid_speculation", "swap",
     [("page_alloc", dict(slot=0, nth=4))]),
]


class TestSpecChaos:
    # F.EchoDrafter: always proposes, so every decode step carries a
    # verify span and mostly-rejected drafts roll back under the faults
    def _make(self, params, cfg, mode):
        return lambda: _engine(params, cfg, num_pages=5, preempt_mode=mode,
                               prefill_chunk_tokens=3, block_q=2,
                               spec_k=3, drafter=F.EchoDrafter())

    def _workload(self, cfg, seed=4, n=4):
        rng = np.random.default_rng(seed)
        out = []
        for _ in range(n):
            base = rng.integers(0, cfg.vocab_size, 3).tolist()
            out.append(((base * 3)[:8], int(rng.integers(3, 6))))
        return out

    @pytest.mark.parametrize(
        "name,mode,spec", SPEC_SCHEDULES,
        ids=[s[0] for s in SPEC_SCHEDULES])
    def test_spec_schedule(self, tiny, name, mode, spec):
        """Death/faults mid-speculation never leak pages or
        double-resolve: the new draft/verify points fire, every handle
        resolves exactly once, and the extended token identities
        (verify rows == accepted + rejected + bonus) hold at
        quiescence."""
        cfg, params = tiny
        rules = [F.FaultRule(point, **kw) for point, kw in spec]
        report = F.run_schedule(self._make(params, cfg, mode), rules,
                                self._workload(cfg))
        assert report["ok"], report["violations"]
        assert report["fired"], "schedule never fired — it tests nothing"
        assert report["completed"] + report["failed"] == report["requests"]

    @pytest.mark.parametrize("mode", ["swap", "recompute"])
    def test_fault_free_spec_under_pressure(self, tiny, mode):
        """No injected faults: pool pressure alone preempts slots that
        are actively speculating; resumes stay invariant-clean and
        speculation keeps running after the churn."""
        cfg, params = tiny
        report = F.run_schedule(self._make(params, cfg, mode), [],
                                self._workload(cfg, seed=9))
        assert report["ok"], report["violations"]
        assert report["failed"] == 0
        assert report["stats"]["preemptions"] >= 1
        assert report["stats"]["spec_steps"] >= 1


# -- chaos: prefix reuse (splice/COW/eviction under faults) ----------------

# every request shares an 8-token base prompt, so later admissions SPLICE
# cached pages and the faults land on slots holding shared, refcounted
# pages; num_pages=5 keeps the pool under pressure so COW, LRU eviction
# and preemption all run while pages are shared — the refcount proofs in
# check_invariants are armed for every schedule
PREFIX_SCHEDULES = [
    ("hit_admission_page_alloc_2nd", "swap",
     [("page_alloc", dict(nth=2))]),
    ("hit_admission_oom_always_slot_0", "recompute",
     [("page_alloc", dict(slot=0, always=True))]),
    ("decode_fault_while_shared", "swap",
     [("decode", dict(nth=4))]),
    ("chunk_consumes_pools_while_shared", "recompute",
     [("prefill_chunk", dict(nth=3, consume_pools=True))]),
    ("swap_out_fault_while_shared", "swap",
     [("swap_out", dict(nth=1))]),
]


class TestPrefixChaos:
    def _make(self, params, cfg, mode):
        return lambda: _engine(params, cfg, num_pages=5, preempt_mode=mode,
                               prefill_chunk_tokens=3, block_q=2)

    def _workload(self, cfg, seed=6, n=4):
        rng = np.random.default_rng(seed)
        base = rng.integers(0, cfg.vocab_size, 8).tolist()
        return [(base + rng.integers(0, cfg.vocab_size,
                                     int(rng.integers(1, 3))).tolist(),
                 int(rng.integers(2, 5))) for _ in range(n)]

    @pytest.mark.parametrize(
        "name,mode,spec", PREFIX_SCHEDULES,
        ids=[s[0] for s in PREFIX_SCHEDULES])
    def test_prefix_schedule(self, tiny, name, mode, spec):
        """Faults landing on prefix-hit admissions / shared slots leak
        nothing: no page is freed while its refcount > 0, refcounts
        equal page-table occupancy at quiescence, and every handle
        resolves exactly once."""
        cfg, params = tiny
        rules = [F.FaultRule(point, **kw) for point, kw in spec]
        report = F.run_schedule(self._make(params, cfg, mode), rules,
                                self._workload(cfg))
        assert report["ok"], report["violations"]
        assert report["fired"], "schedule never fired — it tests nothing"
        assert report["completed"] + report["failed"] == report["requests"]

    @pytest.mark.parametrize("mode", ["swap", "recompute"])
    def test_preempt_while_shared(self, tiny, mode):
        """Fault-free pressure run: slots holding SPLICED (refcount > 1)
        pages get preempted and resumed in both modes; splicing actually
        happened, preemption actually happened, zero leaks."""
        cfg, params = tiny
        report = F.run_schedule(self._make(params, cfg, mode), [],
                                self._workload(cfg, seed=8))
        assert report["ok"], report["violations"]
        assert report["failed"] == 0
        assert report["stats"]["prefix_hits"] >= 1
        assert report["stats"]["preemptions"] >= 1

    def test_evict_under_pressure_with_alloc_faults(self, tiny):
        """DISTINCT prompts fill the index until allocation must evict
        cached prefixes, with an injected allocation fault in the mix —
        refcount invariants hold and eviction is observed."""
        cfg, params = tiny
        rules = [F.FaultRule("page_alloc", nth=3)]
        report = F.run_schedule(self._make(params, cfg, "swap"), rules,
                                _workload(cfg, seed=11, n=5))
        assert report["ok"], report["violations"]
        assert report["fired"]
        assert report["stats"]["prefix_evictions"] >= 1


class TestInvariantChecker:
    def test_detects_leaked_slot(self, tiny):
        """The checker itself must catch a leak: acquire a slot behind the
        engine's back and verify the violation trips."""
        cfg, params = tiny
        eng = _engine(params, cfg)
        eng.cache.acquire_slot()
        with pytest.raises(F.InvariantViolation, match="slot"):
            F.check_invariants(eng)

    def test_detects_double_resolution(self, tiny):
        cfg, params = tiny
        eng = _engine(params, cfg)
        h = eng.submit([1, 2], max_new_tokens=2)
        while not h.done():
            eng.step()
        h._resolve()     # simulate an engine bug double-resolving
        with pytest.raises(F.InvariantViolation, match="resolved 2 times"):
            F.check_invariants(eng, [h])

    def test_detects_ragged_token_identity_drift(self, tiny):
        """ragged_batch_tokens must equal decode_tokens + prefill_tokens;
        a scheduler that double-counts (or drops) a span must trip."""
        cfg, params = tiny
        eng = _engine(params, cfg)
        h = eng.submit([1, 2, 3], max_new_tokens=2)
        while not h.done():
            eng.step()
        eng.stats["ragged_batch_tokens"] += 1   # seed the drift
        with pytest.raises(F.InvariantViolation,
                           match="ragged token identity"):
            F.check_invariants(eng, [h])

    def test_detects_verify_row_identity_drift(self, tiny):
        """verify_tokens must equal spec_accepted + spec_rejected +
        spec_bonus; an accept/reject pass that loses or double-counts a
        draft verdict must trip."""
        cfg, params = tiny
        eng = _engine(params, cfg, spec_k=2)
        h = eng.submit([5, 6, 5, 6, 5, 6], max_new_tokens=6)
        while not h.done():
            eng.step()
        assert eng.stats["verify_tokens"] >= 1
        eng.stats["spec_accepted"] += 1         # seed the drift
        with pytest.raises(F.InvariantViolation,
                           match="identity broken"):
            F.check_invariants(eng, [h])
