"""OpTest harness (reference: test/legacy_test/eager_op_test.py:381 OpTest).

Checks an op against a numpy reference in BOTH execution modes (eager dispatch
and jit-compiled), and checks analytic gradients against central finite
differences — the reference's check_output/check_grad contract."""

from __future__ import annotations

import jax
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.tensor import Tensor


def check_output(op_fn, np_fn, inputs, atol=1e-4, rtol=5e-4, kwargs=None):
    # default tolerances sized for float32 + XLA's fast transcendental
    # approximations (the reference keeps the same idea in
    # test/white_list/op_accuracy_white_list.py)
    """inputs: list of numpy arrays. op_fn takes Tensors; np_fn takes numpy."""
    kwargs = kwargs or {}
    tensors = [paddle.to_tensor(a) for a in inputs]
    out = op_fn(*tensors, **kwargs)
    expected = np_fn(*inputs, **kwargs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    exps = expected if isinstance(expected, (tuple, list)) else [expected]
    for o, e in zip(outs, exps):
        np.testing.assert_allclose(np.asarray(o.numpy(), dtype=np.float64),
                                   np.asarray(e, dtype=np.float64), atol=atol, rtol=rtol)

    # compiled mode: same op under jax.jit over raw arrays
    def raw_fn(*raws):
        ts = [Tensor(r, stop_gradient=True) for r in raws]
        o = op_fn(*ts, **kwargs)
        if isinstance(o, (tuple, list)):
            return tuple(x._data for x in o)
        return o._data

    jitted = jax.jit(raw_fn)(*[t._data for t in tensors])
    jouts = jitted if isinstance(jitted, tuple) else [jitted]
    for o, e in zip(jouts, exps):
        np.testing.assert_allclose(np.asarray(o, dtype=np.float64),
                                   np.asarray(e, dtype=np.float64), atol=atol, rtol=rtol)


def check_grad(op_fn, inputs, grad_inputs=None, eps=1e-3, atol=1e-2, rtol=1e-2,
               kwargs=None, reduce_out=True):
    """Numeric-vs-analytic gradient check (float64 for stability)."""
    kwargs = kwargs or {}
    inputs = [np.asarray(a, dtype=np.float64) for a in inputs]
    grad_pos = list(range(len(inputs))) if grad_inputs is None else grad_inputs

    def scalar_fn(*arrs):
        ts = [paddle.to_tensor(a, stop_gradient=False) for a in arrs]
        out = op_fn(*ts, **kwargs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return out.sum() if reduce_out else out

    # analytic via the tape
    ts = [paddle.to_tensor(a, stop_gradient=False) for a in inputs]
    out = op_fn(*ts, **kwargs)
    if isinstance(out, (tuple, list)):
        out = out[0]
    loss = out.sum() if reduce_out else out
    loss.backward()
    analytic = [ts[i].grad.numpy() if ts[i].grad is not None else np.zeros_like(inputs[i]) for i in grad_pos]

    # numeric central differences
    for gi, pos in enumerate(grad_pos):
        base = inputs[pos]
        num = np.zeros_like(base)
        flat = base.reshape(-1)
        num_flat = num.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            lo_args = [a.copy() for a in inputs]
            lo_args[pos] = base.copy()
            f_hi = float(scalar_fn(*[base if k == pos else inputs[k] for k in range(len(inputs))]).numpy())
            flat[j] = orig - eps
            f_lo = float(scalar_fn(*[base if k == pos else inputs[k] for k in range(len(inputs))]).numpy())
            flat[j] = orig
            num_flat[j] = (f_hi - f_lo) / (2 * eps)
        np.testing.assert_allclose(analytic[gi], num, atol=atol, rtol=rtol,
                                   err_msg=f"grad mismatch for input {pos}")
