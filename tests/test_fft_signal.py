"""paddle.fft + paddle.signal parity vs numpy/scipy conventions.

Mirrors the reference's test/fft/test_fft.py strategy: every transform is
checked against np.fft on shared inputs across norms/axes/n, plus analytic
gradient checks (FFT is linear: d/dx sum|F x|^2 must be finite and match
numeric grad) and stft/istft round-trip reconstruction.
"""

import numpy as np
import pytest

import paddle_tpu as paddle


RNG = np.random.default_rng(7)


def _x(shape=(3, 16)):
    return RNG.standard_normal(shape).astype(np.float32)


def _cx(shape=(3, 16)):
    return (RNG.standard_normal(shape) +
            1j * RNG.standard_normal(shape)).astype(np.complex64)


class TestFft1D:
    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    def test_fft_matches_numpy(self, norm):
        x = _cx()
        got = paddle.fft.fft(paddle.to_tensor(x), norm=norm).numpy()
        np.testing.assert_allclose(got, np.fft.fft(x, norm=norm), rtol=1e-4,
                                   atol=1e-4)

    def test_fft_real_input_promotes(self):
        x = _x()
        got = paddle.fft.fft(paddle.to_tensor(x))
        assert got.numpy().dtype == np.complex64
        np.testing.assert_allclose(got.numpy(), np.fft.fft(x), rtol=1e-4,
                                   atol=1e-4)

    @pytest.mark.parametrize("n", [8, 16, 24])
    def test_fft_n_crops_or_pads(self, n):
        x = _cx()
        got = paddle.fft.fft(paddle.to_tensor(x), n=n).numpy()
        np.testing.assert_allclose(got, np.fft.fft(x, n=n), rtol=1e-4,
                                   atol=1e-4)

    def test_ifft_roundtrip(self):
        x = _cx()
        got = paddle.fft.ifft(paddle.fft.fft(paddle.to_tensor(x))).numpy()
        np.testing.assert_allclose(got, x, rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("fn,nfn", [("rfft", np.fft.rfft),
                                        ("ihfft", lambda a: np.conj(
                                            np.fft.rfft(a)) / a.shape[-1])])
    def test_r2c(self, fn, nfn):
        x = _x()
        got = getattr(paddle.fft, fn)(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, nfn(x), rtol=1e-4, atol=1e-4)

    def test_irfft_hfft(self):
        x = _cx((3, 9))
        np.testing.assert_allclose(
            paddle.fft.irfft(paddle.to_tensor(x)).numpy(),
            np.fft.irfft(x), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            paddle.fft.hfft(paddle.to_tensor(x)).numpy(),
            np.fft.hfft(x), rtol=1e-3, atol=1e-3)

    def test_axis_argument(self):
        x = _cx((4, 8))
        np.testing.assert_allclose(
            paddle.fft.fft(paddle.to_tensor(x), axis=0).numpy(),
            np.fft.fft(x, axis=0), rtol=1e-4, atol=1e-4)

    def test_bad_norm_raises(self):
        with pytest.raises(ValueError, match="orm"):
            paddle.fft.fft(paddle.to_tensor(_x()), norm="bogus")

    def test_bad_n_raises(self):
        with pytest.raises(ValueError, match="positive"):
            paddle.fft.fft(paddle.to_tensor(_x()), n=-3)


class TestFftND:
    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    def test_fft2(self, norm):
        x = _cx((2, 8, 8))
        np.testing.assert_allclose(
            paddle.fft.fft2(paddle.to_tensor(x), norm=norm).numpy(),
            np.fft.fft2(x, norm=norm), rtol=1e-4, atol=1e-4)

    def test_fftn_axes_s(self):
        x = _cx((2, 8, 6))
        np.testing.assert_allclose(
            paddle.fft.fftn(paddle.to_tensor(x), s=(4, 8),
                            axes=(1, 2)).numpy(),
            np.fft.fftn(x, s=(4, 8), axes=(1, 2)), rtol=1e-4, atol=1e-4)

    def test_rfftn_irfftn_roundtrip(self):
        x = _x((2, 8, 8))
        spec = paddle.fft.rfftn(paddle.to_tensor(x))
        back = paddle.fft.irfftn(spec).numpy()
        np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(spec.numpy(), np.fft.rfftn(x),
                                   rtol=1e-3, atol=1e-3)

    def test_hfftn_matches_hfft_on_last_axis(self):
        x = _cx((3, 9))
        np.testing.assert_allclose(
            paddle.fft.hfftn(paddle.to_tensor(x), axes=(-1,)).numpy(),
            np.fft.hfft(x), rtol=1e-3, atol=1e-3)

    def test_hfftn_all_axes_is_fft_then_hfft(self):
        x = _cx((3, 9))
        want = np.fft.hfft(np.fft.fft(x, axis=0), axis=-1)
        np.testing.assert_allclose(
            paddle.fft.hfftn(paddle.to_tensor(x)).numpy(), want,
            rtol=1e-3, atol=1e-3)

    def test_duplicate_axes_raise(self):
        with pytest.raises(ValueError, match="duplicate"):
            paddle.fft.fftn(paddle.to_tensor(_cx((4, 4))), axes=(0, 0))

    def test_fft2_wrong_axes_len_raises(self):
        with pytest.raises(ValueError, match="two axes"):
            paddle.fft.fft2(paddle.to_tensor(_cx((4, 4))), axes=(0, 1, 2))


class TestHelpers:
    def test_fftfreq(self):
        np.testing.assert_allclose(paddle.fft.fftfreq(8, d=0.5).numpy(),
                                   np.fft.fftfreq(8, 0.5), rtol=1e-6)

    def test_rfftfreq(self):
        np.testing.assert_allclose(paddle.fft.rfftfreq(9, d=2.0).numpy(),
                                   np.fft.rfftfreq(9, 2.0), rtol=1e-6)

    def test_fftshift_roundtrip(self):
        x = _x((5, 6))
        s = paddle.fft.fftshift(paddle.to_tensor(x))
        np.testing.assert_allclose(s.numpy(), np.fft.fftshift(x))
        np.testing.assert_allclose(
            paddle.fft.ifftshift(s).numpy(), x)


class TestFftGrads:
    def test_fft_power_spectrum_grad(self):
        """d/dx sum|fft(x)|^2 == 2*N*x by Parseval — the canonical fft vjp."""
        x = paddle.to_tensor(_x((16,)), stop_gradient=False)
        spec = paddle.fft.fft(x)
        loss = paddle.sum(paddle.abs(spec) ** 2)
        loss.backward()
        n = 16
        np.testing.assert_allclose(x.grad.numpy(), 2 * n * x.numpy(),
                                   rtol=1e-3, atol=1e-3)

    def test_irfft_grad_finite(self):
        x = paddle.to_tensor(_x((3, 9)), stop_gradient=False)
        out = paddle.fft.irfft(paddle.fft.rfft(x))
        paddle.sum(out * out).backward()
        assert np.isfinite(x.grad.numpy()).all()


class TestFrameOverlapAdd:
    def test_frame_last_axis(self):
        x = _x((2, 20))
        f = paddle.signal.frame(paddle.to_tensor(x), 8, 4).numpy()
        assert f.shape == (2, 8, 4)
        for i in range(4):
            np.testing.assert_allclose(f[:, :, i], x[:, i * 4: i * 4 + 8])

    def test_frame_axis0(self):
        x = _x((20, 3))
        f = paddle.signal.frame(paddle.to_tensor(x), 8, 4, axis=0).numpy()
        assert f.shape == (4, 8, 3)
        for i in range(4):
            np.testing.assert_allclose(f[i], x[i * 4: i * 4 + 8])

    def test_overlap_add_inverts_frame_sum(self):
        # frames of a constant-1 signal overlap-add to the coverage count
        x = np.ones((1, 20), np.float32)
        f = paddle.signal.frame(paddle.to_tensor(x), 8, 4)
        y = paddle.signal.overlap_add(f, 4).numpy()
        # positions covered by k frames sum to k
        assert y.shape == (1, 20)
        np.testing.assert_allclose(y[0, 8:12], 2.0)  # interior coverage

    def test_overlap_add_axis0(self):
        fr = _x((4, 8, 3))  # (n_frames, frame_length, batch)
        y = paddle.signal.overlap_add(paddle.to_tensor(fr), 4, axis=0)
        assert tuple(y.shape) == (20, 3)
        y2 = paddle.signal.overlap_add(
            paddle.to_tensor(np.moveaxis(fr, (0, 1), (2, 1))), 4, axis=-1)
        np.testing.assert_allclose(y.numpy(), y2.numpy().T, rtol=1e-6,
                                   atol=1e-6)

    def test_frame_too_long_raises(self):
        with pytest.raises(ValueError, match="frame_length"):
            paddle.signal.frame(paddle.to_tensor(_x((2, 4))), 8, 2)


class TestStft:
    def test_stft_shape_onesided(self):
        x = paddle.to_tensor(_x((2, 64)))
        s = paddle.signal.stft(x, n_fft=16)
        assert tuple(s.shape) == (2, 9, 17)  # center pads 8 each side
        assert s.numpy().dtype == np.complex64

    def test_stft_matches_manual_dft(self):
        x = _x((64,))
        s = paddle.signal.stft(paddle.to_tensor(x), n_fft=16, hop_length=8,
                               center=False).numpy()
        # manual: frames of length 16 every 8, rfft each
        want = np.stack([np.fft.rfft(x[i * 8: i * 8 + 16])
                         for i in range(7)], axis=-1)
        np.testing.assert_allclose(s, want, rtol=1e-3, atol=1e-3)

    def test_stft_istft_roundtrip(self):
        x = _x((2, 128))
        win = paddle.to_tensor(np.hanning(32).astype(np.float32))
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft=32,
                                  hop_length=8, window=win)
        back = paddle.signal.istft(spec, n_fft=32, hop_length=8, window=win,
                                   length=128)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-3, atol=1e-3)

    def test_stft_grad_flows(self):
        x = paddle.to_tensor(_x((64,)), stop_gradient=False)
        s = paddle.signal.stft(x, n_fft=16)
        loss = paddle.sum(paddle.abs(s) ** 2)
        loss.backward()
        g = x.grad.numpy()
        assert np.isfinite(g).all() and np.abs(g).max() > 0

    def test_onesided_complex_input_raises(self):
        with pytest.raises(ValueError, match="onesided"):
            paddle.signal.stft(paddle.to_tensor(_cx((64,))), n_fft=16)

    def test_istft_wrong_fft_size_raises(self):
        with pytest.raises(ValueError, match="fft_size"):
            paddle.signal.istft(paddle.to_tensor(_cx((2, 7, 5))), n_fft=16)


class TestSpectrogramStftParity:
    """audio.features.Spectrogram (real matmul-DFT, complex-free for TPU
    plugins without complex support) must equal |signal.stft|^power."""

    def test_spectrogram_equals_stft_magnitude(self):
        x = _x((2, 400))
        spec_layer = paddle.audio.features.Spectrogram(
            n_fft=64, hop_length=16, window="hann", power=2.0)
        got = spec_layer(paddle.to_tensor(x)).numpy()
        # get_window('hann') is the periodic hann window
        win = paddle.to_tensor(
            (0.5 - 0.5 * np.cos(2 * np.pi * np.arange(64) / 64))
            .astype(np.float32))
        S = paddle.signal.stft(paddle.to_tensor(x), n_fft=64, hop_length=16,
                               window=win).numpy()
        want = np.abs(S) ** 2
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


class TestJitAndRegistry:
    def test_fft_under_jit(self):
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(a):
            return jnp.abs(jnp.fft.fft(a))

        x = _x((16,))
        got = paddle.fft.fft(paddle.to_tensor(x))
        np.testing.assert_allclose(np.abs(got.numpy()), f(x), rtol=1e-4,
                                   atol=1e-4)

    def test_registry_has_fft_ops(self):
        from paddle_tpu.ops import registry
        names = {o.name for o in registry.all_ops()}
        for want in ["fft.fft", "fft.rfftn", "fft.fftshift", "signal.stft",
                     "signal.istft", "signal.frame", "signal.overlap_add"]:
            assert want in names, want


def test_cold_gate_fft_traces_under_jit():
    """Regression: the complex-support gate must not be probed inside a jit
    trace (a cold probe there raised and cached False for the process,
    breaking every later fft call on complex-capable backends)."""
    import subprocess, sys, os
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "from paddle_tpu.tensor import Tensor\n"
        "from paddle_tpu import fft\n"
        "x = np.random.randn(4, 8).astype('float32')\n"
        "out = jax.jit(lambda r: fft.irfft(Tensor(r))._data)(x)\n"
        "assert out.shape == (4, 14)\n"
        "assert fft._COMPLEX_OK is True\n"
        "print('cold-gate ok')\n")
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, timeout=240)
    assert "cold-gate ok" in r.stdout, r.stderr[-800:]
